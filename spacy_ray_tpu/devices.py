"""Platform selection helpers.

The one safe way to get the CPU platform on this class of image is
``jax.config.update("jax_platforms", "cpu")`` *before* the backend
initializes: interpreter boot may import jax with a TPU-tunnel platform
(e.g. ``JAX_PLATFORMS=axon``) already locked in from the environment, so
mutating ``os.environ`` in-process is read too late, and a wedged tunnel
makes backend init hang forever rather than error.

This module is a leaf (no package-relative imports) so callers that must
run before anything else — test conftests, the driver's multichip dryrun —
can import it without pulling the full package.
"""

from __future__ import annotations


def force_cpu(n_devices: int = 8) -> None:
    """Select the CPU platform with at least ``n_devices`` virtual devices.

    Safe to call multiple times and after another caller already forced CPU.
    Raises (instead of silently proceeding on an accelerator backend) if the
    jax backend was already initialized on a non-CPU platform — proceeding
    there would mean hanging on a wedged relay or running a CPU-only check
    on real hardware.

    Mutates no environment variables, so nothing leaks into subprocesses
    spawned later (a child that inherited ``JAX_PLATFORMS=cpu`` would
    silently run its real-hardware work on CPU).
    """
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass  # backend already initialized; verified below
    try:
        jax.config.update("jax_num_cpu_devices", n_devices)
    except Exception:
        pass  # already initialized, or jax predates the key; verified below

    backend = jax.default_backend()  # initializes the backend if needed
    if backend != "cpu":
        raise RuntimeError(
            f"force_cpu(): backend is {backend!r}, not 'cpu' — the jax "
            "backend was already initialized on another platform before "
            "force_cpu() ran. Call it before any jax device use."
        )
    have = len(jax.devices())
    if have < n_devices:
        raise RuntimeError(
            f"force_cpu(): need {n_devices} CPU devices, have {have}. "
            "The device count was locked in before force_cpu() ran; start "
            "a fresh process, or set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n_devices}."
        )
