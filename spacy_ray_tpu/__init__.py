"""spacy-ray-tpu: a TPU-native distributed NLP pipeline training framework.

Brand-new JAX/XLA/pallas implementation of the capability surface of
explosion/spacy-ray (reference: /root/reference/spacy_ray): config-driven
training of full NLP pipelines (tagger, transition-based parser/NER, textcat,
spancat, shared CNN tok2vec and transformer backbones) scaled across
accelerators from one CLI command.

Where the reference implements distribution as asynchronous peer-to-peer
parameter ownership over Ray actors (reference proxies.py:9-133,
worker.py:23-262), this framework compiles the whole training step — forward,
backward, gradient all-reduce over ICI, and (optionally ZeRO-1-sharded)
optimizer update — into a single XLA program under `jax.jit` over a
`jax.sharding.Mesh`.
"""

__version__ = "0.1.0"

from .registry import registry  # noqa: F401
from .config import Config, load_config  # noqa: F401

# Importing these packages runs all registry registrations (architectures,
# factories, optimizers, schedules, batchers, readers, loggers) — mirroring
# the reference's entry-point-driven registration (setup.cfg:35-41).
from . import models  # noqa: F401
from .pipeline import components  # noqa: F401
from . import training  # noqa: F401
from .pipeline.language import Pipeline  # noqa: F401
from .pipeline.doc import Doc, Example, Span  # noqa: F401
from .packaging import load  # noqa: F401

__all__ = [
    "registry",
    "Config",
    "load_config",
    "Pipeline",
    "Doc",
    "Example",
    "Span",
    "load",
    "__version__",
]
