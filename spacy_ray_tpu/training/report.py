"""``telemetry report <run-dir>`` — one markdown report per training
run, digested from the artifacts the run already writes: the per-worker
``fleet-worker-*.json`` ledgers, each worker's ``metrics.jsonl`` (step
rows with per-step loss, eval rows, anomaly rows, the ``kind: "fleet"``
exit row carrying the dynamics histograms), and the alert-transition
``alerts.jsonl`` sinks.

This is the committed-evidence artifact of a fleet round: the bench
harness writes it next to its records, CI uploads it next to the ledger
artifacts on failure, and the future ``tune`` subcommand reads the same
queryable record (ROADMAP item 4). Stdlib-only and jax-free — it runs
anywhere the ledgers can be copied to.

Layout expectations (what the trainer-fleet writers produce):

* ``<run-dir>/fleet-worker-{k}.json`` — exit ledger per worker;
* ``<run-dir>/metrics/fleet-worker-{k}/metrics.jsonl`` + ``alerts.jsonl``
  (``--metrics-dir <run-dir>/metrics``, the bench/test convention) — an
  explicit ``metrics_dir`` can point elsewhere;
* a single-process run (``metrics.jsonl`` directly under the run dir or
  its ``metrics/``) gets the same report minus the fleet-only sections.
"""

from __future__ import annotations

import json
import math
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "build_run_report",
    "load_run",
    "fleet_exit_rows",
    "sum_staleness",
    "sparkline",
]

_SPARK = "▁▂▃▄▅▆▇█"


def sparkline(values: List[float], width: int = 40) -> str:
    """Downsampled unicode sparkline (empty string when no finite
    values) — the loss-curve-at-a-glance the report tables carry."""
    finite = [v for v in values if isinstance(v, (int, float))
              and math.isfinite(float(v))]
    if not finite:
        return ""
    if len(finite) > width:
        # mean-pool into `width` cells so the shape survives
        out: List[float] = []
        n = len(finite)
        for i in range(width):
            lo, hi = i * n // width, max((i + 1) * n // width, i * n // width + 1)
            chunk = finite[lo:hi]
            out.append(sum(chunk) / len(chunk))
        finite = out
    lo, hi = min(finite), max(finite)
    span = hi - lo
    if span <= 0:
        return _SPARK[0] * len(finite)
    return "".join(
        _SPARK[min(int((v - lo) / span * (len(_SPARK) - 1)), len(_SPARK) - 1)]
        for v in finite
    )


def _read_jsonl(path: Path) -> List[Dict[str, Any]]:
    rows: List[Dict[str, Any]] = []
    try:
        with open(path, encoding="utf8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except ValueError:
                    continue  # torn concurrent write: skip, don't abort
                if isinstance(row, dict):
                    rows.append(row)
    except OSError:
        pass
    return rows


def load_run(
    run_dir: Path, metrics_dir: Optional[Path] = None
) -> Dict[str, Any]:
    """Gather everything the report renders: per-worker ledgers, metrics
    rows, and alert transitions. Raises ValueError when the directory
    holds neither ledgers nor metrics (a wrong path must not produce an
    empty-but-plausible report)."""
    run_dir = Path(run_dir)
    mdir = Path(metrics_dir) if metrics_dir is not None else run_dir / "metrics"
    workers: Dict[int, Dict[str, Any]] = {}
    for p in sorted(run_dir.glob("fleet-worker-*.json")):
        try:
            ledger = json.loads(p.read_text(encoding="utf8"))
        except ValueError:
            continue
        w = ledger.get("worker")
        if isinstance(w, int):
            workers.setdefault(w, {})["ledger"] = ledger
    for d in sorted(mdir.glob("fleet-worker-*")) if mdir.is_dir() else []:
        try:
            w = int(d.name.rsplit("-", 1)[-1])
        except ValueError:
            continue
        entry = workers.setdefault(w, {})
        entry["metrics_path"] = d / "metrics.jsonl"
        entry["rows"] = _read_jsonl(d / "metrics.jsonl")
        entry["alerts"] = _read_jsonl(d / "alerts.jsonl")
    if not workers:
        single: Optional[Path] = None
        for candidate in (run_dir / "metrics.jsonl", mdir / "metrics.jsonl"):
            if candidate.is_file():
                single = candidate
                break
        if single is None:
            raise ValueError(
                f"{run_dir} holds no fleet-worker-*.json ledgers, no "
                f"{mdir}/fleet-worker-*/metrics.jsonl, and no "
                "metrics.jsonl — not a run directory this report reads"
            )
        workers[0] = {
            "metrics_path": single,
            "rows": _read_jsonl(single),
            "alerts": _read_jsonl(run_dir / "alerts.jsonl")
            or _read_jsonl(mdir / "alerts.jsonl"),
        }
    return {
        "run_dir": run_dir,
        "workers": workers,
        # elastic-membership transition ledger (evict/admit/apply rows,
        # written by the acting lead) — absent file reads as []
        "membership": _read_jsonl(run_dir / "fleet-membership.jsonl"),
    }


def fleet_exit_rows(run: Dict[str, Any]) -> Dict[int, Dict[str, Any]]:
    """worker → its newest ``kind: "fleet"`` exit row, from a
    :func:`load_run` result (workers without one are absent)."""
    out: Dict[int, Dict[str, Any]] = {}
    for w, entry in run["workers"].items():
        row = _fleet_row(entry.get("rows") or [])
        if row is not None:
            out[w] = row
    return out


def sum_staleness(rows: Any) -> Optional[Dict[str, Any]]:
    """Cross-worker staleness histogram from fleet exit rows: cumulative
    buckets on the SHARED table sum exactly per ``le``. The one
    aggregation rule, used by the report's totals column and the bench
    record's ``staleness`` block. None when no row carries counts."""
    buckets: Dict[float, int] = {}
    count = 0
    mx: Optional[float] = None
    for row in rows:
        st = (row.get("histograms") or {}).get("staleness") or {}
        for le, cum in st.get("buckets") or []:
            buckets[float(le)] = buckets.get(float(le), 0) + int(cum)
        count += int(st.get("count") or 0)
        if isinstance(st.get("max"), (int, float)):
            mx = max(mx or 0.0, float(st["max"]))
    if not count:
        return None
    return {
        "count": count,
        "max": mx,
        "buckets": [[le, buckets[le]] for le in sorted(buckets)],
    }


def _fleet_row(rows: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    for row in reversed(rows):
        if row.get("kind") == "fleet":
            return row
    return None


def _pct(part: float, total: float) -> str:
    return f"{100 * part / total:.0f}%" if total > 0 else "-"


def _fmt_ms(v: Any) -> str:
    return f"{float(v) * 1e3:.1f}ms" if isinstance(v, (int, float)) else "-"


def _loss_series(rows: List[Dict[str, Any]]) -> List[Tuple[int, float]]:
    out = []
    for row in rows:
        if row.get("kind") != "step":
            continue
        loss = row.get("loss")
        if isinstance(loss, str):
            # sanitize_json stores non-finite losses as "nan"/"inf"
            # strings (valid JSON); float() parses them back — they must
            # show up in the trajectory as non-finite points, not vanish
            try:
                loss = float(loss)
            except ValueError:
                continue
        if isinstance(loss, (int, float)):
            out.append((int(row.get("step") or 0), float(loss)))
    return out


def _sample(series: List[Tuple[int, float]], n: int = 8) -> List[Tuple[int, float]]:
    if len(series) <= n:
        return series
    idx = [round(i * (len(series) - 1) / (n - 1)) for i in range(n)]
    return [series[i] for i in idx]


def build_run_report(
    run_dir: Path,
    metrics_dir: Optional[Path] = None,
    *,
    run: Optional[Dict[str, Any]] = None,
) -> str:
    """The markdown run report (see module docstring). Sections appear
    only when their evidence exists — an honest report of what the run
    recorded, not a template of dashes. Pass an already-:func:`load_run`
    result via ``run`` to skip the second read (the bench harness loads
    once for its record AND its report)."""
    if run is None:
        run = load_run(run_dir, metrics_dir)
    workers = run["workers"]
    ids = sorted(workers)
    ledgers = {
        w: e["ledger"] for w, e in workers.items() if "ledger" in e
    }
    fleet_rows = fleet_exit_rows(run)
    lines: List[str] = [f"# Training run report: `{run['run_dir']}`", ""]

    # -- fleet header ---------------------------------------------------
    if ledgers:
        any_l = next(iter(ledgers.values()))
        total_words = sum(int(l.get("words_seen") or 0) for l in ledgers.values())
        slowest = max(float(l.get("seconds") or 0.0) for l in ledgers.values())
        wps = f"{total_words / slowest:,.0f}" if slowest > 0 else "-"
        lines += [
            f"Async trainer fleet: **{any_l.get('n_workers')} worker(s)**, "
            f"quorum {any_l.get('quorum')}, "
            f"max staleness {any_l.get('max_staleness')} — "
            f"{total_words:,} words over {slowest:.1f}s "
            f"(slowest worker) = **{wps} words/s** fleet-wide.",
            "",
            "## Per-worker summary",
            "",
            "| worker | steps | words | seconds | version | pushed "
            "| received | applied | discarded | push-failed | interrupted |",
            "|---|---|---|---|---|---|---|---|---|---|---|",
        ]
        for w in ids:
            l = ledgers.get(w)
            if not l:
                continue
            c = l.get("counters") or {}
            lines.append(
                f"| {w} | {l.get('steps')} "
                f"| {int(l.get('words_seen') or 0):,} "
                f"| {float(l.get('seconds') or 0.0):.1f} "
                f"| {l.get('version')} "
                f"| {int(c.get('grad_pushed') or 0)} "
                f"| {int(c.get('grad_received') or 0)} "
                f"| {int(c.get('grad_applied') or 0)} "
                f"| {int(c.get('grad_discarded') or 0)} "
                f"| {int(c.get('push_failed') or 0)} "
                f"| {'yes' if l.get('interrupted') else 'no'} |"
            )
        lines.append("")

    # -- membership timeline (elastic fleet, RESILIENCE.md) -------------
    member_rows = run.get("membership") or []
    if member_rows:
        final_epoch = max(
            (int(r.get("epoch") or 0) for r in member_rows), default=0
        )
        lines += [
            "## Membership timeline",
            "",
            f"Final membership epoch **{final_epoch}** across "
            f"{len(member_rows)} recorded transition(s).",
            "",
            "| unix time | event | epoch | detail | active |",
            "|---|---|---|---|---|",
        ]
        for row in sorted(
            member_rows, key=lambda r: float(r.get("ts") or 0.0)
        ):
            ev = row.get("event")
            if ev == "evict":
                detail = (
                    f"lead {row.get('lead')} evicted {row.get('evicted')}"
                )
            elif ev == "admit":
                detail = (
                    f"lead {row.get('lead')} admitted {row.get('admitted')}"
                )
            elif ev == "apply":
                detail = (
                    f"worker {row.get('worker')} re-owned "
                    f"{row.get('resharded')} shard group(s), "
                    f"opt from {row.get('opt_source')}"
                )
            elif ev == "join-requested":
                detail = f"worker {row.get('worker')} asked to rejoin"
            else:
                detail = "-"
            active = row.get("active")
            lines.append(
                f"| {float(row.get('ts') or 0.0):.1f} | {ev} "
                f"| {row.get('epoch')} | {detail} "
                f"| {active if active is not None else '-'} |"
            )
        lines.append("")

    # -- phase share ----------------------------------------------------
    phase_names = ("data", "pull", "grad", "push", "apply_wait")
    phase_rows = []
    for w in ids:
        src = ledgers.get(w) or fleet_rows.get(w) or {}
        phases = src.get("phases") or {}
        if phases:
            phase_rows.append((w, phases))
    if phase_rows:
        lines += [
            "## Phase share (per-worker loop seconds)",
            "",
            "| worker | " + " | ".join(phase_names) + " | total s |",
            "|---|" + "---|" * (len(phase_names) + 1),
        ]
        for w, phases in phase_rows:
            total = sum(float(v) for v in phases.values())
            lines.append(
                f"| {w} | "
                + " | ".join(
                    _pct(float(phases.get(p) or 0.0), total)
                    for p in phase_names
                )
                + f" | {total:.1f} |"
            )
        lines.append("")

    # -- loss trajectories ---------------------------------------------
    loss_by_worker = {
        w: _loss_series(workers[w].get("rows") or []) for w in ids
    }
    if any(loss_by_worker.values()):
        lines += ["## Per-worker loss trajectories", ""]
        for w in ids:
            series = loss_by_worker[w]
            if not series:
                continue
            finite = [v for _, v in series if math.isfinite(v)]
            nonfinite = len(series) - len(finite)
            spark = sparkline([v for _, v in series])
            head = (
                f"- worker {w} ({len(series)} step(s)"
                + (f", {nonfinite} non-finite" if nonfinite else "")
                + f"): `{spark}`"
            )
            if finite:
                head += (
                    f" first {finite[0]:.4g} last {finite[-1]:.4g} "
                    f"min {min(finite):.4g}"
                )
            lines.append(head)
            sampled = _sample(series)
            lines.append(
                "  steps "
                + "  ".join(
                    f"{s}:{v:.3g}" if math.isfinite(v) else f"{s}:nan"
                    for s, v in sampled
                )
            )
        lines.append("")

    # -- staleness / dynamics histograms --------------------------------
    stale = {
        w: (r.get("histograms") or {}).get("staleness")
        for w, r in fleet_rows.items()
    }
    stale = {w: h for w, h in stale.items() if isinstance(h, dict) and h.get("count")}
    merged_stale = sum_staleness(fleet_rows.values())
    if stale and merged_stale:
        totals = {float(le): int(cum) for le, cum in merged_stale["buckets"]}
        lines += [
            "## Staleness histogram (version lag of accepted pushes, "
            "cumulative)",
            "",
            "| le | " + " | ".join(f"worker {w}" for w in sorted(stale))
            + " | total |",
            "|---|" + "---|" * (len(stale) + 1),
        ]
        for le in sorted(totals):
            cells = []
            for w in sorted(stale):
                cum = dict(
                    (float(b[0]), int(b[1]))
                    for b in stale[w].get("buckets") or []
                ).get(le, 0)
                cells.append(str(cum))
            lines.append(
                f"| {int(le)} | " + " | ".join(cells)
                + f" | {totals[le]} |"
            )
        counts = "  ".join(
            f"worker {w}: n={stale[w]['count']} max={stale[w].get('max')}"
            for w in sorted(stale)
        )
        lines += ["", f"accepted-push totals: {counts}", ""]

    # -- wire table (compression ledger, TUNING.md §20) -----------------
    wire_rows = []
    for w in ids:
        src = ledgers.get(w) or fleet_rows.get(w) or {}
        c = src.get("counters") or {}
        if any(c.get(k) for k in ("wire_push_bytes", "wire_pull_bytes")):
            wire_rows.append((w, src, c))
    if wire_rows:
        lines += [
            "## Wire bytes (actual vs f32-equivalent)",
            "",
            "| worker | codec | delta window | pushed | pushed f32-eq "
            "| push ratio | pulled | pulled f32-eq | pull ratio |",
            "|---|---|---|---|---|---|---|---|---|",
        ]
        for w, src, c in wire_rows:
            def _mb(name: str) -> float:
                return float(c.get(name) or 0) / 1e6

            def _ratio(actual: str, raw: str) -> str:
                a, r = float(c.get(actual) or 0), float(c.get(raw) or 0)
                return f"{r / a:.1f}x" if a > 0 else "-"

            lines.append(
                f"| {w} | {src.get('grad_compression') or '-'} "
                f"| {src.get('param_delta_window', '-')} "
                f"| {_mb('wire_push_bytes'):.2f}MB "
                f"| {_mb('wire_push_bytes_uncompressed'):.2f}MB "
                f"| {_ratio('wire_push_bytes', 'wire_push_bytes_uncompressed')} "
                f"| {_mb('wire_pull_bytes'):.2f}MB "
                f"| {_mb('wire_pull_bytes_uncompressed'):.2f}MB "
                f"| {_ratio('wire_pull_bytes', 'wire_pull_bytes_uncompressed')} |"
            )
        lines.append("")
    timing = []
    for w in sorted(fleet_rows):
        h = fleet_rows[w].get("histograms") or {}
        qw, ap = h.get("quorum_wait_seconds") or {}, h.get("apply_seconds") or {}
        if qw.get("count") or ap.get("count"):
            timing.append(
                f"| {w} | {_fmt_ms(qw.get('p50'))} | {_fmt_ms(qw.get('p99'))} "
                f"| {_fmt_ms(ap.get('p50'))} | {_fmt_ms(ap.get('p99'))} "
                f"| {int(ap.get('count') or 0)} |"
            )
    if timing:
        lines += [
            "## Quorum-wait & apply timing",
            "",
            "| worker | quorum-wait p50 | p99 | apply p50 | p99 | applies |",
            "|---|---|---|---|---|---|",
            *timing,
            "",
        ]

    # -- host resources (hoststats eval-row blocks) ---------------------
    host_rows = []
    for w in ids:
        procs = [
            r.get("process")
            for r in workers[w].get("rows") or []
            if isinstance(r.get("process"), dict)
        ]
        if procs:
            host_rows.append((w, procs))
    if host_rows:
        def _hb(v: Any) -> str:
            if not isinstance(v, (int, float)):
                return "-"
            return (
                f"{v / (1 << 30):.2f}GB" if v >= 1 << 30
                else f"{v / (1 << 20):.0f}MB"
            )

        lines += [
            "## Host resources",
            "",
            "Per-worker `/proc` truth sampled at eval boundaries "
            "(training/hoststats; docs/OBSERVABILITY.md \"Host resources "
            "& the run ledger\"). High involuntary ctx switches with low "
            "cpu% = the host is contended, not the model slow.",
            "",
            "| worker | cpu% last | cpu% max | rss | rss peak | threads "
            "| fds | ctx vol | ctx invol |",
            "|---|---|---|---|---|---|---|---|---|",
        ]
        for w, procs in host_rows:
            last = procs[-1]
            cpus = [
                float(p["cpu_percent"]) for p in procs
                if isinstance(p.get("cpu_percent"), (int, float))
            ]
            peaks = [
                float(p["rss_peak_bytes"]) for p in procs
                if isinstance(p.get("rss_peak_bytes"), (int, float))
            ]
            lines.append(
                f"| {w} "
                f"| {f'{cpus[-1]:.0f}' if cpus else '-'} "
                f"| {f'{max(cpus):.0f}' if cpus else '-'} "
                f"| {_hb(last.get('rss_bytes'))} "
                f"| {_hb(max(peaks) if peaks else None)} "
                f"| {last.get('threads') if last.get('threads') is not None else '-'} "
                f"| {last.get('open_fds') if last.get('open_fds') is not None else '-'} "
                f"| {last.get('ctx_switches_voluntary') if last.get('ctx_switches_voluntary') is not None else '-'} "
                f"| {last.get('ctx_switches_involuntary') if last.get('ctx_switches_involuntary') is not None else '-'} |"
            )
        lines.append("")

    # -- alert & anomaly timeline --------------------------------------
    alert_events: List[Tuple[float, str]] = []
    for w in ids:
        for row in workers[w].get("alerts") or []:
            t = row.get("unix_time")
            if isinstance(t, (int, float)):
                alert_events.append((
                    float(t),
                    f"[worker {w}] {row.get('alert')} "
                    f"{row.get('from')} → {row.get('to')} "
                    f"({row.get('severity')}): {row.get('detail')}",
                ))
    anomaly_events: List[Tuple[float, str]] = []
    for w in ids:
        for row in workers[w].get("rows") or []:
            if row.get("kind") != "anomaly":
                continue
            t = row.get("t")
            anomaly_events.append((
                float(t) if isinstance(t, (int, float)) else 0.0,
                f"[worker {w}] {row.get('anomaly')}: {row.get('message')}",
            ))
    if alert_events or anomaly_events:
        lines += ["## Alert & anomaly timeline", ""]
        for t, text in sorted(alert_events):
            lines.append(f"- unix {t:.1f}  {text}")
        for t, text in sorted(anomaly_events):
            lines.append(f"- t+{t:.1f}s  {text}")
        lines.append("")
    else:
        lines += ["## Alert & anomaly timeline", "", "- none recorded", ""]
    return "\n".join(lines)
