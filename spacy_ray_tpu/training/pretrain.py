"""Tok2vec pretraining: the ``pretrain`` CLI / ``[pretraining]`` config block.

Capability parity with ``spacy pretrain`` (part of the spaCy training stack
the reference programs against, SURVEY.md §1 layer E2; the reference's
``spacy ray train`` consumes configs whose ``[initialize] init_tok2vec``
points at weights this command produces). The design is TPU-first, not a
port of spaCy's thinc implementation:

* The whole objective — trunk forward, head, masked loss — is ONE jitted
  program built with the same ``make_train_step`` (psum over the data
  axis, donated buffers) as supervised training; pretraining scales over
  the mesh exactly like training does.
* ``characters`` objective (default): for every token predict its first
  ``n_characters`` and last ``n_characters`` UTF-8 bytes from the trunk's
  output vector, as ``2 * n_characters`` independent 257-way softmaxes
  (256 byte values + one "absent" class for tokens shorter than the
  window). Targets are a statically-shaped [B, T, 2n] int array built at
  collation — batched MXU-friendly classification, no ragged host loops.
* ``vectors`` objective: predict the token's static vector (requires
  ``[initialize] vectors``); cosine or L2 loss, masked to real tokens.

Output: ``model-last.npz`` (+ periodic ``model{step}.npz``) holding the
trunk component's params in the portable flattened-npz schema of
``checkpoint.save_params`` — exactly what ``[initialize] init_tok2vec``
loads (shape-checked) before supervised training.
"""

from __future__ import annotations

import time
from pathlib import Path
from typing import Any, Dict, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..models.core import Context, Model, chain
from ..models.layers import Linear, Maxout
from ..registry import registry
from ..types import Padded
from .checkpoint import save_params
from .corpus import Corpus
from .loop import resolve_dot_name

N_BYTE_CLASSES = 257  # 256 byte values + "absent" (token shorter than window)


def char_targets(examples: List[Any], B: int, T: int, n: int) -> np.ndarray:
    """[B, T, 2n] int32: first n and last n UTF-8 bytes of each token
    (byte value + 1; 0 = absent). Cached per Example like the feature
    cache — pretraining re-iterates the corpus every epoch."""
    out = np.zeros((B, T, 2 * n), dtype=np.int32)
    for i, eg in enumerate(examples[:B]):
        cached = getattr(eg, "_char_cache", None)
        if cached is None or cached.shape[1] != 2 * n:
            words = eg.reference.words
            cached = np.zeros((len(words), 2 * n), dtype=np.int32)
            for j, w in enumerate(words):
                bs = w.encode("utf8")
                head, tail = bs[:n], bs[-n:]
                cached[j, : len(head)] = np.frombuffer(head, np.uint8) + 1
                cached[j, n : n + len(tail)] = (
                    np.frombuffer(tail, np.uint8).astype(np.int32) + 1
                )
            try:
                eg._char_cache = cached
            except AttributeError:  # slots-restricted Example: skip caching
                pass
        L = min(len(cached), T)
        out[i, :L] = cached[:L]
    return out


def build_char_head(width: int, n_characters: int, hidden: int = 0) -> Model:
    """Trunk vector -> [..., 2n * 257] logits. A Maxout hidden layer when
    ``hidden`` > 0 (spaCy's characters head shape), plain Linear otherwise."""
    n_out = 2 * n_characters * N_BYTE_CLASSES
    if hidden:
        return chain(Maxout(width, hidden), Linear(hidden, n_out), name="char_head")
    return Linear(width, n_out, name="char_head")


def make_char_loss(trunk: Model, head: Model, n_characters: int):
    """loss_fn(params, tokens, targets, rng) for make_train_step: masked
    mean softmax cross-entropy over 2n byte slots per real token."""

    def loss_fn(params, tokens, targets, rng):
        ctx = Context(train=True, rng=rng)
        enc: Padded = trunk.apply(params["trunk"], tokens, ctx)
        logits = head.apply(params["head"], enc, ctx).X
        B, T, _ = logits.shape
        logits = logits.reshape(B, T, 2 * n_characters, N_BYTE_CLASSES)
        tgt = targets["chars"]
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
        mask = enc.mask.astype(jnp.float32)[..., None]  # [B, T, 1]
        loss = jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask) * 2 * n_characters, 1.0)
        acc = jnp.sum((jnp.argmax(logp, -1) == tgt) * mask) / jnp.maximum(
            jnp.sum(mask) * 2 * n_characters, 1.0
        )
        return loss, {"char_acc": acc}

    return loss_fn


def make_vector_loss(trunk: Model, head: Model, loss_kind: str):
    """``vectors`` objective: predict each token's static vector; cosine or
    L2, masked to rows that actually have a vector (targets["has_vec"])."""

    def loss_fn(params, tokens, targets, rng):
        ctx = Context(train=True, rng=rng)
        enc: Padded = trunk.apply(params["trunk"], tokens, ctx)
        pred = head.apply(params["head"], enc, ctx).X.astype(jnp.float32)
        tgt = targets["vectors"].astype(jnp.float32)
        mask = (enc.mask & targets["has_vec"]).astype(jnp.float32)
        if loss_kind == "cosine":
            pn = pred / jnp.maximum(jnp.linalg.norm(pred, axis=-1, keepdims=True), 1e-8)
            tn = tgt / jnp.maximum(jnp.linalg.norm(tgt, axis=-1, keepdims=True), 1e-8)
            per_tok = 1.0 - jnp.sum(pn * tn, axis=-1)
        else:  # L2
            per_tok = jnp.sum((pred - tgt) ** 2, axis=-1)
        loss = jnp.sum(per_tok * mask) / jnp.maximum(jnp.sum(mask), 1.0)
        return loss, {}

    return loss_fn


def _batches(corpus: Corpus, size: int) -> Iterator[List[Any]]:
    buf: List[Any] = []
    for eg in corpus():
        buf.append(eg)
        if len(buf) == size:
            yield buf
            buf = []
    if buf:
        yield buf


def pretrain(
    config: Config,
    output_dir: Path,
    *,
    n_workers: Optional[int] = None,
) -> Dict[str, Any]:
    """Run the ``[pretraining]`` block of ``config``; write trunk weights to
    ``output_dir``. Returns summary stats."""
    from ..parallel.mesh import build_mesh
    from ..parallel.step import (
        make_train_step,
        place_batch,
        place_replicated,
    )
    from ..pipeline.language import Pipeline

    config = config.interpolate()
    P = dict(config.get("pretraining") or {})
    if not P:
        raise ValueError("Config has no [pretraining] block")

    nlp = Pipeline.from_config(config)
    comp_name = P.get("component") or nlp.tok2vec_name
    if comp_name is None or comp_name not in nlp.components:
        raise ValueError(
            f"[pretraining] component {comp_name!r} not in pipeline "
            f"{nlp.pipe_names} (and no tok2vec/transformer trunk found)"
        )
    comp = nlp.components[comp_name]

    # [initialize] vectors load FIRST — the trunk may embed static vectors
    # (include_static_vectors), so model build must see them, exactly as
    # Pipeline.initialize orders it
    init_cfg = config.get("initialize", {}) or {}
    vec_path = init_cfg.get("vectors")
    if vec_path and nlp.vectors is None:
        from ..pipeline.vectors import Vectors

        nlp.vectors = Vectors.from_disk(vec_path)
    from ..pipeline.vectors import use_vectors

    with use_vectors(nlp.vectors):
        comp.build_model()
    width = comp.model.dims.get("nO")
    if not width:
        raise ValueError(f"trunk {comp_name!r} does not expose an output width")

    # ---- corpus (dot-name into [corpora], like train/dev) ----
    corpora_cfg = config.get("corpora", {})
    resolved = {name: registry.resolve(block) for name, block in corpora_cfg.items()}
    corpus = resolve_dot_name(config, resolved, P.get("corpus", "corpora.pretrain"))

    # ---- objective ----
    obj = dict(P.get("objective") or {})
    obj_type = obj.get("type", "characters")
    n_chars = int(obj.get("n_characters", 4))
    if obj_type == "characters":
        head = build_char_head(width, n_chars, hidden=int(obj.get("hidden_size", 0)))
        loss_fn = make_char_loss(comp.model, head, n_chars)
    elif obj_type == "vectors":
        if nlp.vectors is None:
            raise ValueError("objective type 'vectors' needs [initialize] vectors")
        head = Linear(width, nlp.vectors.width, name="vec_head")
        loss_fn = make_vector_loss(
            comp.model, head, obj.get("loss", "cosine")
        )
    else:
        raise ValueError(f"Unknown [pretraining.objective] type {obj_type!r}")

    # ---- params + step ----
    rng = jax.random.PRNGKey(int(P.get("seed", 0)))
    rng, r_trunk, r_head = jax.random.split(rng, 3)
    with use_vectors(nlp.vectors):
        params = {"trunk": comp.init_params(r_trunk), "head": head.init(r_head)}

    n_devices = None
    if n_workers is not None:
        n_devices = int(n_workers)
    mesh = build_mesh(n_data=n_devices)
    opt_cfg = dict(P.get("optimizer") or {})
    opt_name = opt_cfg.pop("@optimizers", "Adam.v1")
    tx = registry.get("optimizers", opt_name)(**opt_cfg)
    params = place_replicated(params, mesh)
    opt_state = tx.init(params)
    step = make_train_step(loss_fn, tx, mesh, opt_state_template=opt_state)

    max_steps = int(P.get("max_steps", 1000))
    max_epochs = int(P.get("max_epochs", 0))
    batch_size = int(P.get("batch_size", 64))
    n_save_every = int(P.get("n_save_every", 0))
    if float(P.get("dropout", 0.0)):
        print(
            "# [pretraining] dropout is taken from the component's own model "
            "config here (the trunk applies its configured dropout when "
            "training); the standalone key is ignored",
            flush=True,
        )

    output_dir = Path(output_dir)
    output_dir.mkdir(parents=True, exist_ok=True)

    def save(tag: str) -> None:
        host = jax.tree_util.tree_map(np.asarray, params["trunk"])
        save_params(output_dir / f"model-{tag}.npz", host)

    # raw-text corpus lines must tokenize with THIS pipeline's tokenizer,
    # not a default rule set, or the trunk pretrains on a mismatched token
    # stream; the context keeps the enablement scoped to this run
    from .corpus import use_raw_text_tokenizer

    n_data = int(mesh.shape.get("data", 1))
    n_step = 0
    epoch = 0
    t0 = time.perf_counter()
    total_words = 0
    loss_val = float("nan")
    done = False
    with use_raw_text_tokenizer(nlp.tokenizer):
        while not done:
            epoch += 1
            for examples in _batches(corpus, batch_size):
                # B must divide evenly over the mesh data axis for P("data")
                # (same rounding the train loop applies, loop.py)
                B_pad = ((max(len(examples), n_data) + n_data - 1) // n_data) * n_data
                batch = nlp.collate(examples, with_targets=False, pad_batch_to=B_pad)
                tokens = batch["tokens"]
                if obj_type == "characters":
                    targets = {
                        "chars": char_targets(
                            examples, *_batch_bt(batch), n_chars
                        )
                    }
                else:
                    targets = _vector_targets(nlp, examples, *_batch_bt(batch))
                rng, sub = jax.random.split(rng)
                params, opt_state, loss, metrics = step(
                    params,
                    opt_state,
                    place_batch(tokens, mesh),
                    place_batch(targets, mesh),
                    sub,
                )
                n_step += 1
                total_words += int(batch["n_words"])
                if n_step % 50 == 0 or n_step == 1:
                    loss_val = float(loss)
                    extra = "".join(
                        f"  {k}={float(v):.3f}" for k, v in (metrics or {}).items()
                        if k != "grad_norm"
                    )
                    wps = total_words / max(time.perf_counter() - t0, 1e-9)
                    print(
                        f"pretrain step {n_step:>6}  loss={loss_val:.4f}{extra}  "
                        f"wps={wps:,.0f}",
                        flush=True,
                    )
                if n_save_every and n_step % n_save_every == 0:
                    save(str(n_step))
                if n_step >= max_steps:
                    done = True
                    break
            if n_step == 0:
                raise ValueError(
                    "pretraining corpus yielded no batches (empty file, or "
                    "max_length filtered every text); nothing to train on"
                )
            if max_epochs and epoch >= max_epochs:
                done = True
    loss_val = float(loss)
    save("last")
    return {
        "steps": n_step,
        "epochs": epoch,
        "loss": loss_val,
        "words": total_words,
        "output": str(output_dir / "model-last.npz"),
    }


def _batch_bt(batch: Dict[str, Any]) -> Tuple[int, int]:
    """(B, T) of a collated batch, from whatever leaf is handy."""
    leaf = jax.tree_util.tree_leaves(batch["tokens"])[0]
    return int(leaf.shape[0]), int(leaf.shape[1])


def _vector_targets(nlp, examples, B: int, T: int) -> Dict[str, np.ndarray]:
    D = nlp.vectors.width
    vecs = np.zeros((B, T, D), dtype=np.float32)
    has = np.zeros((B, T), dtype=bool)
    for i, eg in enumerate(examples[:B]):
        for j, w in enumerate(eg.reference.words[:T]):
            r = nlp.vectors.row_of(w)
            if r >= 0:
                vecs[i, j] = nlp.vectors.table[r]
                has[i, j] = True
    return {"vectors": vecs, "has_vec": has}
