"""Real spaCy DocBin (``.spacy``) byte-format reader/writer.

The reference's data path is ``spacy convert`` → a ``.spacy`` corpus
(reference bin/get-data.sh:8-12), so reference-ecosystem artifacts must
load unmodified (VERDICT r1 missing #7). The format (spaCy v3,
spacy/tokens/_serialize.py) is zlib-compressed msgpack of:

* ``attrs``: sorted list of int attr IDs (the stable ``spacy.attrs`` C-enum
  — ORTH=65 … SENT_START=80, SPACY=81; see ``ATTR_NAMES``)
* ``tokens``: C-order uint64 array [total_tokens, len(attrs)] — string
  attrs hold 64-bit string-store hashes, HEAD holds the RELATIVE offset
  (head − i) as two's-complement, SENT_START holds 1/0/−1
* ``spaces``: bool array [total_tokens, 1]
* ``lengths``: int32 tokens-per-doc
* ``strings``: every string used; the hash→string map is recovered by
  hashing each entry with spaCy's string-store hash — MurmurHash64A
  (MurmurHash2, Appleby, public domain) over utf-8 with seed 1
  (murmurhash mrmr.hash64; implemented below in pure Python and verified
  against spaCy's documented value hash("coffee") == 3197928453018144401)
* ``cats``/``flags``/optionally ``user_data``, ``span_groups``

Attr IDs above 83 (ENT_KB_ID, MORPH, ENT_ID — appended to the symbols enum
after LANG) vary by spaCy version, so they are resolved positionally: among
present IDs > 83, enum order is ENT_KB_ID < MORPH < ENT_ID (two such IDs —
the DocBin default — are ENT_KB_ID and MORPH). Unknown columns are skipped,
never misread.

The writer emits the certain-ID columns plus ENT_KB_ID/MORPH at 84/85 —
the same position-based convention the reader resolves, so this repo's
own .spacy round trip preserves entity links and morphs. CAVEAT: real
spaCy resolves attr IDs against its version's symbols enum, so a real
spaCy reader may skip (not misread) those two columns; data meant for
real-spaCy consumption with links/morphs should also keep .jsonl.

``span_groups`` (spancat corpora) round-trip: one bytes entry per doc =
msgpack list of per-group bytes (spacy/tokens/_dict_proxies.py
``SpanGroups.to_bytes``); each group is msgpack
``{"name", "attrs", "spans"}`` with every span struct-packed big-endian
(spacy/tokens/span_group.pyx ``SpanGroup.to_bytes``) — 7 fields
``>QQQllll`` (id, kb_id, label, start, end, start_char, end_char) since
spaCy 3.4, with the older 6-field ``>QQllll`` (no id) layout accepted on
read. Label/kb-id hashes resolve through the same string store.
"""

from __future__ import annotations

import struct
import zlib
from pathlib import Path
from typing import Dict, Iterable, Iterator, List, Optional, Union

import numpy as np

from ..pipeline.doc import Doc, Span

_M64 = (1 << 64) - 1

# the stable prefix of the spacy.attrs enum (spacy/attrs.pxd, values fixed
# by C-enum order since v2): only the ones DocBin can carry
ATTR_NAMES: Dict[int, str] = {
    64: "ID",
    65: "ORTH",
    66: "LOWER",
    67: "NORM",
    68: "SHAPE",
    69: "PREFIX",
    70: "SUFFIX",
    71: "LENGTH",
    72: "CLUSTER",
    73: "LEMMA",
    74: "POS",
    75: "TAG",
    76: "DEP",
    77: "ENT_IOB",
    78: "ENT_TYPE",
    79: "HEAD",
    80: "SENT_START",
    81: "SPACY",
    82: "PROB",
    83: "LANG",
}
_IDS = {v: k for k, v in ATTR_NAMES.items()}
# string-valued columns (uint64 cells are string-store hashes)
_STRING_ATTRS = {"ORTH", "LOWER", "NORM", "SHAPE", "LEMMA", "POS", "TAG",
                 "DEP", "ENT_TYPE", "ENT_KB_ID", "ENT_ID", "MORPH"}


def murmur_hash64a(data: bytes, seed: int) -> int:
    """MurmurHash64A (MurmurHash2 64-bit, Appleby, public domain)."""
    m = 0xC6A4A7935BD1E995
    r = 47
    h = (seed ^ ((len(data) * m) & _M64)) & _M64
    nblocks = len(data) // 8
    for i in range(nblocks):
        (k,) = struct.unpack_from("<Q", data, i * 8)
        k = (k * m) & _M64
        k ^= k >> r
        k = (k * m) & _M64
        h ^= k
        h = (h * m) & _M64
    tail = data[nblocks * 8 :]
    for i in range(len(tail) - 1, -1, -1):
        h ^= tail[i] << (8 * i)
    if tail:
        h = (h * m) & _M64
    h ^= h >> r
    h = (h * m) & _M64
    h ^= h >> r
    return h


def spacy_string_hash(s: str) -> int:
    """spaCy StringStore hash: MurmurHash64A(utf8, seed=1); "" is key 0."""
    if not s:
        return 0
    return murmur_hash64a(s.encode("utf8"), 1)


def _char_offsets(words: List[str], spaces: Optional[List[bool]]) -> List[int]:
    """Cumulative character start offset per token (text reconstructed as
    word + trailing space when ``spaces[i]``; unknown spaces assume True —
    the same convention the SPACY column writer uses)."""
    sp = spaces if spaces is not None else [True] * len(words)
    offsets = []
    pos = 0
    for w, s in zip(words, sp):
        offsets.append(pos)
        pos += len(w) + (1 if s else 0)
    offsets.append(pos)  # sentinel: end of text
    return offsets


def _span_groups_to_bytes(doc: Doc, strings: set) -> bytes:
    """Serialize ``doc.spans`` in spaCy's SpanGroups byte format (see
    module docstring). Adds group names / span labels / kb ids to the
    DocBin string store so readers can resolve the hashes."""
    import msgpack

    offsets = _char_offsets(doc.words, doc.spaces)
    groups: List[bytes] = []
    for name, spans in (doc.spans or {}).items():
        packed = []
        for s in spans:
            if s.label:
                strings.add(s.label)
            if s.kb_id:
                strings.add(s.kb_id)
            end_char = (
                offsets[s.end - 1] + len(doc.words[s.end - 1])
                if s.end > s.start
                else offsets[s.start]
            )
            packed.append(
                struct.pack(
                    ">QQQllll",
                    0,  # span id: unset
                    spacy_string_hash(s.kb_id),
                    spacy_string_hash(s.label),
                    int(s.start),
                    int(s.end),
                    int(offsets[s.start]),
                    int(end_char),
                )
            )
        strings.add(name)
        groups.append(
            msgpack.packb(
                {"name": name, "attrs": {}, "spans": packed}, use_bin_type=True
            )
        )
    return msgpack.packb(groups, use_bin_type=True)


def _span_groups_from_bytes(
    data: bytes, hash_to_str: Dict[int, str]
) -> Dict[str, List[Span]]:
    """Decode one doc's SpanGroups payload. Tolerates both the 7-field
    (id, kb_id, label) and pre-3.4 6-field (kb_id, label) span layouts."""
    import msgpack

    if not data:
        return {}
    out: Dict[str, List[Span]] = {}
    for group_bytes in msgpack.unpackb(data, raw=False):
        g = msgpack.unpackb(group_bytes, raw=False)
        name = g.get("name", "")
        spans: List[Span] = []
        for sb in g.get("spans", []):
            if len(sb) == 40:  # >QQQllll
                _sid, kb_h, label_h, start, end, _sc, _ec = struct.unpack(
                    ">QQQllll", sb
                )
            elif len(sb) == 32:  # >QQllll (no id field)
                kb_h, label_h, start, end, _sc, _ec = struct.unpack(">QQllll", sb)
            else:
                continue  # unknown layout: skip rather than misread
            spans.append(
                Span(
                    int(start),
                    int(end),
                    hash_to_str.get(int(label_h), ""),
                    kb_id=hash_to_str.get(int(kb_h), ""),
                )
            )
        # duplicate group names: keep the first (spaCy keys by name too)
        if name not in out:
            out[name] = spans
    return out


def _resolve_attr_names(attr_ids: List[int]) -> List[Optional[str]]:
    """Map the file's attr-ID list to names; version-dependent high IDs are
    resolved positionally (enum order ENT_KB_ID < MORPH < ENT_ID)."""
    high = sorted(a for a in attr_ids if a > 83)
    high_names: Dict[int, str] = {}
    # only when the low IDs are the standard DocBin set is the high pair
    # reliably (ENT_KB_ID, MORPH) — a custom attr config could carry e.g.
    # (ENT_KB_ID, ENT_ID), and misreading entity IDs as morphs is worse
    # than skipping the column
    default_lows = {65, 73, 74, 75, 76, 77, 78, 79}
    lows = {a for a in attr_ids if a <= 83}
    if len(high) == 3:
        names = ["ENT_KB_ID", "MORPH", "ENT_ID"]  # enum order, unambiguous
    elif len(high) == 2 and default_lows <= lows:
        names = ["ENT_KB_ID", "MORPH"]  # the DocBin default pair
    else:
        names = [None] * len(high)  # ambiguous: skip rather than misread
    for a, nm in zip(high, names):
        if nm:
            high_names[a] = nm
    return [ATTR_NAMES.get(a) or high_names.get(a) for a in attr_ids]


def read_docbin_bytes(data: bytes) -> Iterator[Doc]:
    import msgpack

    msg = msgpack.unpackb(zlib.decompress(data), raw=False, strict_map_key=False)
    attr_ids = [int(a) for a in msg["attrs"]]
    names = _resolve_attr_names(attr_ids)
    lengths = np.frombuffer(msg["lengths"], dtype="<i4")
    total = int(lengths.sum())
    tokens = np.frombuffer(msg["tokens"], dtype="<u8").reshape(total, len(attr_ids))
    spaces_buf = msg.get("spaces") or b""
    spaces_all = (
        np.frombuffer(spaces_buf, dtype=bool).reshape(-1) if spaces_buf else None
    )
    hash_to_str = {spacy_string_hash(s): s for s in msg.get("strings", [])}
    hash_to_str[0] = ""
    cats = msg.get("cats") or [None] * len(lengths)
    flags = msg.get("flags") or [{}] * len(lengths)
    span_groups = msg.get("span_groups") or [b""] * len(lengths)

    col: Dict[str, int] = {nm: i for i, nm in enumerate(names) if nm}

    def sval(row, key):
        return hash_to_str.get(int(row[col[key]]), "")

    offset = 0
    for di, n in enumerate(lengths):
        n = int(n)
        rows = tokens[offset : offset + n]
        unknown_spaces = bool(
            di < len(flags) and (flags[di] or {}).get("has_unknown_spaces")
        )
        doc_spaces = (
            [bool(x) for x in spaces_all[offset : offset + n]]
            if not unknown_spaces
            and spaces_all is not None
            and len(spaces_all) >= offset + n
            else None
        )
        offset += n
        if "ORTH" not in col:
            raise ValueError(".spacy file has no ORTH column; cannot recover words")
        words = [hash_to_str.get(int(r[col["ORTH"]]), "") for r in rows]

        def column(key):
            if key not in col:
                return None
            vals = [sval(r, key) for r in rows]
            return vals if any(vals) else None

        heads = None
        if "HEAD" in col:
            deltas = rows[:, col["HEAD"]].astype(np.int64)  # two's complement
            heads = [int(i + d) for i, d in enumerate(deltas)]
            if any(not (0 <= h < n) for h in heads):
                heads = None  # corrupt column: drop rather than crash training
            elif (
                not deltas.any()
                and "DEP" in col
                and not any(sval(r, "DEP") for r in rows)
            ):
                # spaCy's "no parse" default: ALL heads self (zero deltas)
                # AND all DEP labels empty — that exact combination is
                # missing annotation, not a fabricated flat tree. Real heads
                # with empty labels (deltas.any()) are kept.
                heads = None
        sent_starts = None
        if "SENT_START" in col:
            ss = rows[:, col["SENT_START"]].astype(np.int64)
            if np.any(ss != 0):
                # preserve the tri-state verbatim: 1=start, -1=explicitly
                # not a start, 0=unannotated (collapsing -1 to 0 would mask
                # every negative gold label out of the senter loss)
                sent_starts = [
                    1 if v == 1 else (-1 if v == -1 else 0) for v in ss
                ]
        doc = Doc(
            words=words,
            spaces=doc_spaces,
            tags=column("TAG"),
            pos=column("POS"),
            lemmas=column("LEMMA"),
            morphs=column("MORPH"),
            deps=column("DEP"),
            heads=heads,
            sent_starts=sent_starts,
            cats=dict(cats[di]) if cats[di] else {},
        )
        # entities: ENT_IOB (1=I, 2=O, 3=B, 0=unset) + ENT_TYPE hashes;
        # ENT_KB_ID (when present) carries the entity-linking gold
        if "ENT_IOB" in col and "ENT_TYPE" in col:
            has_kb = "ENT_KB_ID" in col
            iob = rows[:, col["ENT_IOB"]].astype(np.int64)
            # 0 everywhere = missing annotation; any 1/2/3 = annotated
            # (even all-O) — the distinction spaCy's scorer skip honors
            doc.ents_annotated = bool((iob != 0).any())
            start = None
            label = ""
            kb_id = ""
            for i in range(n):
                tag = int(iob[i])
                if tag == 3 or (tag == 1 and start is None):
                    if start is not None:
                        doc.ents.append(Span(start, i, label, kb_id=kb_id))
                    start = i
                    label = sval(rows[i], "ENT_TYPE")
                    kb_id = sval(rows[i], "ENT_KB_ID") if has_kb else ""
                elif tag in (0, 2):
                    if start is not None:
                        doc.ents.append(Span(start, i, label, kb_id=kb_id))
                        start = None
            if start is not None:
                doc.ents.append(Span(start, n, label, kb_id=kb_id))
        if di < len(span_groups) and span_groups[di]:
            for name, spans in _span_groups_from_bytes(
                span_groups[di], hash_to_str
            ).items():
                # drop out-of-range spans (corrupt or truncated doc) rather
                # than crash downstream target construction
                doc.spans[name] = [
                    s for s in spans if 0 <= s.start <= s.end <= n
                ]
        yield doc


def read_docbin(path: Union[str, Path]) -> Iterator[Doc]:
    yield from read_docbin_bytes(Path(path).read_bytes())


_WRITE_ATTRS = ["ORTH", "LEMMA", "POS", "TAG", "DEP", "ENT_IOB", "ENT_TYPE",
                "HEAD", "SENT_START", "SPACY"]


class DocBinWriter:
    """Incremental .spacy writer: ``add`` docs as they are produced,
    ``finalize`` serializes once. The bulk parse CLI streams predicted
    chunks through here so the host holds ~100 bytes of packed attribute
    rows per token instead of every annotated Doc at once (the whole-corpus
    materialization the round-4 advisor flagged)."""

    def __init__(self) -> None:
        import msgpack  # surface a missing dep at construction, not finalize

        self._msgpack = msgpack
        # ENT_KB_ID and MORPH sit above the fixed enum at 84/85 — the
        # "default pair" position _resolve_attr_names maps back
        # positionally. A real spaCy reader resolves IDs against its own
        # enum and may skip these two columns (see module docstring); the
        # certain-ID columns interoperate.
        write_ids = {
            **{_IDS[a]: a for a in _WRITE_ATTRS}, 84: "ENT_KB_ID", 85: "MORPH"
        }
        self._attr_ids = sorted(write_ids)
        self._names = [write_ids[a] for a in self._attr_ids]
        self._strings: set = set()
        self._rows_all: List[np.ndarray] = []
        self._spaces_all: List[np.ndarray] = []
        self._lengths: List[int] = []
        self._cats: List[dict] = []
        self._flags: List[dict] = []
        self._span_groups: List[bytes] = []

    def add(self, doc: Doc) -> None:
        attr_ids, names, strings = self._attr_ids, self._names, self._strings
        n = len(doc.words)
        self._lengths.append(n)
        self._cats.append(dict(doc.cats) if doc.cats else {})
        self._flags.append({"has_unknown_spaces": doc.spaces is None})
        self._span_groups.append(_span_groups_to_bytes(doc, strings))
        # unannotated -> ENT_IOB 0 (missing); annotated (even with zero
        # entities, when ents_annotated says so) -> explicit O everywhere.
        # Writing O for missing would fabricate negative NER gold for
        # consumers that honor the 0-vs-2 distinction (spaCy does)
        ent_iob = np.full(n, 2 if doc.has_ents_annotation else 0, np.int64)
        ent_type = [""] * n
        ent_kb = [""] * n
        for s in doc.ents:
            for i in range(s.start, s.end):
                ent_iob[i] = 3 if i == s.start else 1
                ent_type[i] = s.label
                ent_kb[i] = s.kb_id
        arr = np.zeros((n, len(attr_ids)), dtype="<u8")
        for ci, nm in enumerate(names):
            if nm == "ORTH":
                vals = [spacy_string_hash(w) for w in doc.words]
                strings.update(doc.words)
            elif nm == "LEMMA":
                lem = doc.lemmas or [""] * n
                vals = [spacy_string_hash(x) for x in lem]
                strings.update(x for x in lem if x)
            elif nm == "POS":
                p = doc.pos or [""] * n
                vals = [spacy_string_hash(x) for x in p]
                strings.update(x for x in p if x)
            elif nm == "TAG":
                t = doc.tags or [""] * n
                vals = [spacy_string_hash(x) for x in t]
                strings.update(x for x in t if x)
            elif nm == "DEP":
                d = doc.deps or [""] * n
                vals = [spacy_string_hash(x) for x in d]
                strings.update(x for x in d if x)
            elif nm == "ENT_IOB":
                vals = ent_iob.tolist()
            elif nm == "ENT_TYPE":
                vals = [spacy_string_hash(x) for x in ent_type]
                strings.update(x for x in ent_type if x)
            elif nm == "ENT_KB_ID":
                vals = [spacy_string_hash(x) for x in ent_kb]
                strings.update(x for x in ent_kb if x)
            elif nm == "MORPH":
                mo = doc.morphs or [""] * n
                vals = [spacy_string_hash(x) for x in mo]
                strings.update(x for x in mo if x)
            elif nm == "HEAD":
                if doc.heads:
                    vals = [int(h) - i for i, h in enumerate(doc.heads)]
                else:
                    vals = [0] * n
            elif nm == "SENT_START":
                if doc.sent_starts:
                    # tri-state passthrough: writing -1 for an unannotated 0
                    # would fabricate negative gold labels
                    vals = [
                        1 if v == 1 else (-1 if v == -1 else 0)
                        for v in doc.sent_starts
                    ]
                else:
                    vals = [0] * n
            elif nm == "SPACY":
                sp = doc.spaces if doc.spaces is not None else [True] * n
                vals = [1 if x else 0 for x in sp]
            else:
                vals = [0] * n
            # mask in Python ints: hashes occupy the full uint64 range and
            # HEAD/SENT_START deltas are negative (two's complement)
            arr[:, ci] = np.asarray([int(v) & _M64 for v in vals], dtype="<u8")
        self._rows_all.append(arr)
        sp = doc.spaces if doc.spaces is not None else [True] * n
        self._spaces_all.append(np.asarray(sp, dtype=bool).reshape(n, 1))

    def finalize(self, path: Union[str, Path]) -> None:
        rows_all, spaces_all = self._rows_all, self._spaces_all
        lengths = self._lengths
        tokens_buf = (
            np.vstack(rows_all).tobytes("C") if rows_all and sum(lengths) else b""
        )
        spaces_buf = (
            np.vstack(spaces_all).tobytes("C")
            if spaces_all and sum(lengths) else b""
        )
        msg = {
            "version": "0.1",
            "attrs": self._attr_ids,
            "tokens": tokens_buf,
            "spaces": spaces_buf,
            "lengths": np.asarray(lengths, dtype="<i4").tobytes("C"),
            "strings": sorted(self._strings),
            "cats": self._cats,
            "flags": self._flags,
            "span_groups": self._span_groups,
        }
        Path(path).write_bytes(
            zlib.compress(self._msgpack.packb(msg, use_bin_type=True))
        )


def write_docbin(path: Union[str, Path], docs: Iterable[Doc]) -> None:
    """Write docs in the real .spacy byte format (readable by spaCy)."""
    writer = DocBinWriter()
    for doc in docs:
        writer.add(doc)
    writer.finalize(path)
