"""Parallel input pipeline: ordered collation worker pool, epoch-level
collation cache, and per-stage instrumentation.

The training loop's host-side data path is read (corpus + batcher) →
tokenize/hash/collate (the expensive part: target construction + feature
hashing into padded arrays) → transfer (``device_put``). On CPU the device
step is slow enough to hide all of it behind ``prefetch_iter``'s single
producer thread; a real TPU step is orders of magnitude faster, so the
single-threaded producer becomes the ceiling (PERF.md round-2: compiled
cnn_tagger 5.57M w/s vs 122K e2e — a 45× input-pipeline gap).

Three pieces, composable and individually inert when disabled:

* :class:`OrderedPool` — fans a pure ``fn(item)`` out over N worker
  threads while yielding results in exact submission order. The pool runs
  ONLY the collation stage: reading the source iterator stays on one
  feeder thread (corpus/batcher state is single-threaded), and the
  consumer of the pool performs ``device_put`` + any multi-host
  collectives on its own single thread — the ordering constraint
  documented in ``prefetch.py`` is preserved by construction.
* :class:`CollateCache` — steady-state epochs re-tokenize, re-hash and
  re-collate the exact same cached Example objects into the exact same
  bucket shapes. Cache the collated HOST arrays keyed by batch identity
  and ``(B_pad, T_pad)``, under a byte budget with LRU eviction. The
  training loop bypasses the cache automatically when augmentation is
  active (fresh Example copies every epoch would only churn it) and in
  annotating mode (targets depend on per-step predictions).
* :class:`PipelineStats` — thread-safe per-stage timers (read /
  collate / transfer / queue-wait) + cache counters, surfaced in the
  training log at every eval row and stamped into bench records
  (``bench.py --input-pipeline``).
"""

from __future__ import annotations

import queue
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from .resilience import maybe_fail

__all__ = [
    "OrderedPool",
    "CollateCache",
    "PipelineStats",
    "ordered_map",
    "cached_collate",
]


# ----------------------------------------------------------------------
# Per-stage instrumentation
# ----------------------------------------------------------------------

STAGES = ("read", "collate", "transfer", "queue_wait")


class PipelineStats:
    """Thread-safe accumulator for input-pipeline stage timings.

    ``collate`` seconds accumulate across worker threads, so with N busy
    workers the collate total can exceed wall time — that is the point:
    stage seconds measure WORK, the words/s rate measures the pipeline.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.seconds: Dict[str, float] = {s: 0.0 for s in STAGES}
        self.counts: Dict[str, int] = {s: 0 for s in STAGES}
        self.cache_hits = 0
        self.cache_misses = 0
        self.cache_enabled = False
        self.workers = 1
        # optional span emitter (training/telemetry.py TraceBuffer): when
        # attached, every stage timing that carries its start stamp also
        # lands as a Chrome-trace span. One emitter serves the pooled AND
        # the inline path identically — a collate_workers = 0 run traces
        # the same read/collate/transfer stages as a pooled one, just on
        # one track (the satellite fix: single-threaded runs must be
        # comparable in traces).
        self._trace: Optional[Any] = None

    def attach_trace(self, trace: Any) -> None:
        self._trace = trace

    def add(
        self, stage: str, seconds: float, n: int = 1, t0: Optional[float] = None
    ) -> None:
        with self._lock:
            self.seconds[stage] = self.seconds.get(stage, 0.0) + seconds
            self.counts[stage] = self.counts.get(stage, 0) + n
        trace = self._trace
        if trace is not None and t0 is not None:
            trace.add_span(stage, t0, seconds, cat="pipeline")

    class _Timer:
        __slots__ = ("_stats", "_stage", "_t0")

        def __init__(self, stats: "PipelineStats", stage: str):
            self._stats = stats
            self._stage = stage

        def __enter__(self) -> "PipelineStats._Timer":
            self._t0 = time.perf_counter()
            return self

        def __exit__(self, *exc: Any) -> None:
            self._stats.add(
                self._stage, time.perf_counter() - self._t0, t0=self._t0
            )

    def timer(self, stage: str) -> "PipelineStats._Timer":
        return PipelineStats._Timer(self, stage)

    def hit(self) -> None:
        with self._lock:
            self.cache_hits += 1

    def miss(self) -> None:
        with self._lock:
            self.cache_misses += 1

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "stage_seconds": {
                    s: round(self.seconds.get(s, 0.0), 4) for s in STAGES
                },
                "stage_counts": {s: self.counts.get(s, 0) for s in STAGES},
                "cache": {
                    "enabled": self.cache_enabled,
                    "hits": self.cache_hits,
                    "misses": self.cache_misses,
                },
                "workers": self.workers,
            }


# ----------------------------------------------------------------------
# Epoch-level collation cache
# ----------------------------------------------------------------------


def _entry_nbytes(value: Any) -> int:
    """Total nbytes of every array reachable in a collated batch dict."""
    total = 0
    seen: set = set()

    def walk(node: Any) -> None:
        nonlocal total
        if isinstance(node, dict):
            for v in node.values():
                walk(v)
        elif hasattr(node, "_fields") or isinstance(node, (list, tuple)):
            for v in node:  # NamedTuple (TokenBatch) or plain sequence
                walk(v)
        elif hasattr(node, "nbytes"):
            if id(node) not in seen:
                seen.add(id(node))
                total += int(node.nbytes)

    walk(value)
    return total


class CollateCache:
    """Byte-capped LRU cache of collated host batches.

    Keyed by the IDENTITY of the Example objects in the batch plus the
    padded bucket shape — the corpus's default ``cache = true`` re-yields
    the same Example objects every epoch, so identical batches recur with
    identical keys. Each entry pins a strong reference to its Example
    list, which both keeps ``id()`` values stable for the key's lifetime
    and lets hits verify identity (no hash collisions possible). Batches
    that never recur (augmentation, streaming corpora) simply churn
    through LRU eviction — which is why callers BYPASS the cache when
    they know recurrence is impossible.

    Thread-safe: collation workers race on get/put.
    """

    def __init__(self, max_bytes: int):
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Tuple, Tuple[List[Any], Any, int]]" = (
            OrderedDict()
        )
        self._nbytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def _key(self, examples: List[Any], B: int, T: int) -> Tuple:
        return (tuple(id(eg) for eg in examples), int(B), int(T))

    def get(self, examples: List[Any], B: int, T: int) -> Optional[Any]:
        key = self._key(examples, B, T)
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.misses += 1
                return None
            held, value, _ = entry
            # identity re-check: id() keys are only valid while the entry
            # holds its examples alive — verify rather than trust
            if len(held) != len(examples) or any(
                a is not b for a, b in zip(held, examples)
            ):
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return value

    def put(self, examples: List[Any], B: int, T: int, value: Any) -> None:
        nbytes = _entry_nbytes(value)
        if nbytes > self.max_bytes:
            return  # one oversized batch must not flush the whole cache
        key = self._key(examples, B, T)
        with self._lock:
            if key in self._entries:
                return
            self._entries[key] = (list(examples), value, nbytes)
            self._nbytes += nbytes
            while self._nbytes > self.max_bytes and len(self._entries) > 1:
                _, (_, _, evicted_bytes) = self._entries.popitem(last=False)
                self._nbytes -= evicted_bytes
                self.evictions += 1

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def nbytes(self) -> int:
        with self._lock:
            return self._nbytes


def cached_collate(
    cache: Optional[CollateCache],
    examples: List[Any],
    B: int,
    T: int,
    collate: Callable[[List[Any], int, int], Any],
    stats: Optional[PipelineStats] = None,
) -> Any:
    """The one get-else-collate-and-put sequence, shared by the training
    loop's collate stage and ``bench.py --input-pipeline`` so the
    benchmark measures the exact pipeline training runs (cache semantics
    can't drift between the two). ``cache=None`` degrades to a plain
    ``collate`` call; stats (when given) count hits/misses only while a
    cache is active.

    Also the ``collate`` fault-injection site (training/resilience.py):
    living here, an injected collation failure exercises the SAME path —
    including pool-worker → consumer re-raise — for the loop and the
    bench."""
    maybe_fail("collate")
    value = cache.get(examples, B, T) if cache is not None else None
    if value is None:
        value = collate(examples, B, T)
        if cache is not None:
            cache.put(examples, B, T, value)
            if stats is not None:
                stats.miss()
    elif stats is not None:
        stats.hit()
    return value


# ----------------------------------------------------------------------
# Ordered worker pool
# ----------------------------------------------------------------------

_DONE = object()


class _RaisedItem:
    __slots__ = ("err",)

    def __init__(self, err: BaseException):
        self.err = err


class OrderedPool:
    """Run ``fn(item)`` over a worker pool, yielding results in exact
    source order.

    A single feeder thread drains the source iterator (corpus/batcher
    state stays single-threaded) and submits work to N workers; the
    consumer pops futures in submission order, so a slow item blocks
    later (already finished) items from being YIELDED but never from
    being COMPUTED — up to ``prefetch`` items run ahead. Exceptions from
    the source or from ``fn`` re-raise at the consumer in order position.

    ``fn`` must be pure host work: the whole point of the pool contract
    is that ``device_put`` and any collectives stay on the consumer's
    single thread (see training/prefetch.py).

    ``close()`` (idempotent; also triggered by ``__del__``) stops the
    feeder, cancels queued work, and drops buffered results.
    """

    def __init__(
        self,
        it: Iterator[Any],
        fn: Callable[[Any], Any],
        workers: int,
        prefetch: Optional[int] = None,
    ):
        from concurrent.futures import ThreadPoolExecutor

        self._fn = fn
        self._it = it
        self._stopped = threading.Event()
        workers = max(int(workers), 1)
        # enough in-flight items to keep every worker busy plus a ready
        # buffer; bounded so a fast feeder can't collate the whole epoch
        self._q: "queue.Queue" = queue.Queue(
            maxsize=int(prefetch) if prefetch else workers * 2
        )
        self._executor = ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="collate-pool"
        )
        self._feeder = threading.Thread(
            target=self._feed, daemon=True, name="collate-pool-feeder"
        )
        self._feeder.start()

    def _call(self, item: Any) -> Any:
        if self._stopped.is_set():
            return _DONE  # cancelled after close: skip the work
        return self._fn(item)

    def _put(self, obj: Any) -> bool:
        while not self._stopped.is_set():
            try:
                self._q.put(obj, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def _feed(self) -> None:
        try:
            for item in self._it:
                if self._stopped.is_set():
                    return
                future = self._executor.submit(self._call, item)
                if not self._put(future):
                    future.cancel()
                    return
        except BaseException as e:  # noqa: BLE001 — re-raised at consumer
            self._put(_RaisedItem(e))
            return
        self._put(_DONE)

    def __iter__(self) -> "OrderedPool":
        return self

    def __next__(self) -> Any:
        if self._stopped.is_set():
            raise StopIteration
        obj = self._q.get()
        if obj is _DONE:
            self.close()
            raise StopIteration
        if isinstance(obj, _RaisedItem):
            self.close()
            raise obj.err
        try:
            result = obj.result()
        except BaseException:
            self.close()
            raise
        if result is _DONE:  # worker saw the stop flag mid-close
            raise StopIteration
        return result

    def close(self) -> None:
        """Stop feeder + workers and drop buffered results. Join the
        feeder BEFORE draining so a mid-put future can't slip into the
        just-drained queue; then close the source iterator (its finally
        blocks may hold resources — e.g. a nested pool)."""
        if self._stopped.is_set():
            return
        self._stopped.set()
        self._feeder.join(timeout=5.0)
        try:
            while True:
                obj = self._q.get_nowait()
                if hasattr(obj, "cancel"):
                    obj.cancel()
        except queue.Empty:
            pass
        self._executor.shutdown(wait=False, cancel_futures=True)
        if not self._feeder.is_alive():
            close = getattr(self._it, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:
                    pass

    def __del__(self):
        self.close()


def ordered_map(
    it: Iterator[Any],
    fn: Callable[[Any], Any],
    workers: int = 1,
    prefetch: Optional[int] = None,
) -> Iterator[Any]:
    """``map(fn, it)`` with ``workers >= 2`` fanned out over an
    :class:`OrderedPool`; below that, a plain inline generator (zero
    threads, zero overhead) — so callers can wire one code path and let
    the ``collate_workers`` knob decide."""
    if workers >= 2:
        return OrderedPool(it, fn, workers, prefetch)

    def inline() -> Iterator[Any]:
        try:
            for item in it:
                yield fn(item)
        finally:
            close = getattr(it, "close", None)
            if close is not None:
                close()

    return inline()
