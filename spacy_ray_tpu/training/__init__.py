"""Training subsystem: loop, batching, corpora, optimizers, checkpointing,
and the resilience layer (preemption, watchdog, retries, fault injection)."""

from . import resilience  # noqa: F401  (shutdown/watchdog/retry/faults)
from . import corpus  # noqa: F401  (registers readers)
from . import batcher  # noqa: F401  (registers batchers/schedules)
from . import optimizers  # noqa: F401  (registers optimizers/schedules)
from . import loggers  # noqa: F401  (registers loggers)
from . import augment  # noqa: F401  (registers augmenters)
