"""Run ledger & regression sentry: the observability plane's memory
ACROSS runs.

``BENCH_SESSION.jsonl`` holds the repo's entire performance trajectory —
every bench record since the seed — but until this module nothing could
read it as history: diffing two records meant hand-reading PERF.md, and
"did this PR slow the trainer down?" had no machine answer. The ledger
normalizes session records (and ``telemetry report`` run directories)
into rows keyed by **(spec name, platform, shape, config labels)** so
that records are only ever compared against their true peers, then
answers three questions:

* ``list``/``show`` — what history exists per key, and is it clean?
* ``diff`` — how do two specific records compare, with the delta judged
  against the measurement's own noise evidence (per-rep dispersion and
  the matmul-reprobe contention stamps), refusing cross-platform
  comparisons outright (a CPU number vs a TPU number is not a delta,
  it's a category error);
* ``regress`` — the sentry: judge a fresh record against the latest
  CLEAN committed baseline for the same key with a noise-aware
  threshold, exiting nonzero only on a CONFIRMED regression. CI's
  ``make bench-gate`` and the future autotuner (ROADMAP item 4) both
  consume this verdict instead of a hand-read markdown table.

Trust rules, inherited from the bench's own discipline (bench.py):

* a record whose post-run matmul reprobe fell below
  ``CLEAN_REPROBE_RATIO`` (or that stamped ``contended``) may not serve
  as a baseline, and a CONTENDED fresh record can never *confirm* a
  regression — contention already explains the drop;
* the noise band for a comparison is the max of a floor, both records'
  per-rep dispersion (``wps_reps`` spread), and both records' reprobe
  slack (1 − reprobe ratio) — a delta inside the band is "within
  noise", never a verdict;
* torn/foreign lines in the session file are counted and skipped,
  never fatal (the file is append-as-you-go by design — a crash
  mid-append must not brick the ledger).

Stdlib-only and jax-free, like every other offline telemetry tool.
"""

from __future__ import annotations

import json
import math
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "LedgerError",
    "CLEAN_REPROBE_RATIO",
    "NOISE_FLOOR",
    "normalize_record",
    "ingest_session",
    "ingest_run_dir",
    "row_key",
    "dispersion",
    "noise_band",
    "diff_rows",
    "latest_clean_baseline",
    "regress",
    "render_rows",
    "render_diff",
    "render_verdicts",
]


class LedgerError(ValueError):
    """A refused comparison (cross-platform, unknown selector) — the
    CLI maps it to exit 2, distinct from a confirmed regression's 1."""


# Mirrors bench.py's CLEAN_REPROBE_RATIO: below this post-run matmul
# reprobe ratio a record may not serve as a cross-run baseline. Kept as
# a local constant because bench.py lives outside the package.
CLEAN_REPROBE_RATIO = 0.94

# The minimum relative band any verdict must clear: bench.py's own
# r5 evidence — clean records reproduce within ~2%, the 0.90-0.94
# reprobe band measured up to ~6% low — so a sub-5% delta between two
# records is never treated as signal without cleaner evidence.
NOISE_FLOOR = 0.05

# Config labels that make two records different ARMS rather than two
# measurements of the same thing (codec, sharding mode, precision,
# quorum topology). Status strings like "active (pallas)" keep only
# their first token — the parenthetical detail varies by host probe.
_LABEL_FIELDS = (
    "grad_compression",
    "param_delta_window",
    "update_sharding",
    "fused_update",
    "param_shadow",
    "flash",
    "precision_label",
    "batching",
    "mode",
    "quorum",
    "max_staleness",
)

_SHAPE_FIELDS = ("B", "T", "devices", "workers", "replicas")


def _norm_label(v: Any) -> Any:
    if isinstance(v, str) and " (" in v:
        return v.split(" (", 1)[0]
    return v


def _label_is_default(key: str, v: Any) -> bool:
    """A knob at its OFF default is the same arm as history that
    predates the knob: older records omit the field entirely, so
    stamping the default into the key would fragment the append-only
    history into spurious before/after arms (the bench-gate smoke would
    forever see "no-baseline"). f32 gradients are "no compression",
    window 0 is "no delta pulls"."""
    if v in (False, "off", "none", "disabled"):
        return True
    if key == "param_delta_window" and not v:
        return True
    if key == "grad_compression" and v == "f32":
        return True
    return False


def normalize_record(
    rec: Dict[str, Any], *, source: str = ""
) -> Optional[Dict[str, Any]]:
    """One session record → one ledger row, or None for rows that carry
    no comparable measurement (skip stubs, records without a value)."""
    if not isinstance(rec, dict) or rec.get("skipped"):
        return None
    name = rec.get("name")
    value = rec.get("value")
    if not name or not isinstance(value, (int, float)):
        return None
    shape = {
        k: rec[k] for k in _SHAPE_FIELDS
        if isinstance(rec.get(k), (int, float))
    }
    labels = {}
    for k in _LABEL_FIELDS:
        if rec.get(k) is None:
            continue
        v = _norm_label(rec[k])
        if not _label_is_default(k, v):
            labels[k] = v
    reps = rec.get("wps_reps")
    return {
        "name": str(name),
        "platform": rec.get("platform"),
        "metric": rec.get("metric"),
        "unit": rec.get("unit"),
        "value": float(value),
        "shape": shape,
        "labels": labels,
        "contended": rec.get("contended"),
        "peak_reprobe_ratio": rec.get("peak_reprobe_ratio"),
        "n_reps": rec.get("n_reps"),
        "reps": [float(r) for r in reps] if isinstance(reps, list) else None,
        "rep_min": rec.get("wps_min"),
        "rep_max": rec.get("wps_max"),
        "host": rec.get("host") if isinstance(rec.get("host"), dict) else None,
        "recorded_at": rec.get("recorded_at"),
        "run_id": rec.get("run_id"),
        "source": source,
    }


def ingest_session(path: Path) -> Tuple[List[Dict[str, Any]], int]:
    """(rows in file order, count of torn/foreign lines skipped)."""
    rows: List[Dict[str, Any]] = []
    skipped = 0
    try:
        text = Path(path).read_text(encoding="utf8")
    except OSError as e:
        raise LedgerError(f"cannot read session file {path}: {e}")
    for i, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            skipped += 1  # torn concurrent append: skip, never abort
            continue
        row = normalize_record(rec, source=f"{path}:{i}")
        if row is None:
            skipped += 1
            continue
        rows.append(row)
    return rows, skipped


def ingest_run_dir(run_dir: Path) -> List[Dict[str, Any]]:
    """A ``telemetry report`` run directory → ledger rows: the fleet's
    aggregate words/s from the per-worker exit ledgers (the same
    arithmetic bench.py commits), or a single-process run's newest eval
    row (wps + step time). Host truth rides along when the run's rows
    carry ``process`` blocks (the PR 18 eval-row export)."""
    from .report import fleet_exit_rows, load_run

    run = load_run(Path(run_dir))
    rows: List[Dict[str, Any]] = []
    workers = run["workers"]
    ledgers = [
        e["ledger"] for e in workers.values() if isinstance(e.get("ledger"), dict)
    ]
    rss_peak = None
    platform = None
    for entry in workers.values():
        for r in entry.get("rows") or []:
            platform = r.get("platform") or platform
            proc = r.get("process")
            if isinstance(proc, dict) and isinstance(
                proc.get("rss_peak_bytes"), (int, float)
            ):
                rss_peak = max(rss_peak or 0, proc["rss_peak_bytes"])
    if ledgers:
        words = sum(float(l.get("words_seen") or 0) for l in ledgers)
        secs = max(float(l.get("seconds") or 0) for l in ledgers)
        if secs > 0:
            rec = {
                "name": "telemetry_run_fleet",
                "metric": f"run-dir words/s ({len(ledgers)} workers)",
                "value": round(words / secs, 1),
                "unit": "words/s",
                "platform": platform,
                "workers": len(ledgers),
                "grad_compression": ledgers[0].get("grad_compression"),
                "quorum": ledgers[0].get("quorum"),
                "host": {"rss_peak_bytes": rss_peak} if rss_peak else None,
            }
            row = normalize_record(rec, source=str(run_dir))
            if row is not None:
                rows.append(row)
        return rows
    for entry in workers.values():
        evals = [
            r for r in (entry.get("rows") or []) if r.get("kind") == "eval"
        ]
        if not evals:
            continue
        last = evals[-1]
        rec = {
            "name": "telemetry_run",
            "metric": "run-dir eval words/s",
            "value": last.get("wps"),
            "unit": "words/s",
            "platform": last.get("platform"),
            "host": {"rss_peak_bytes": rss_peak} if rss_peak else None,
        }
        row = normalize_record(rec, source=str(run_dir))
        if row is not None:
            rows.append(row)
    return rows


def row_key(row: Dict[str, Any]) -> str:
    """The comparability key: records compare only within it."""
    shape = ",".join(f"{k}={row['shape'][k]:g}" for k in sorted(row["shape"]))
    labels = ",".join(f"{k}={row['labels'][k]}" for k in sorted(row["labels"]))
    return "|".join(
        p for p in (
            row["name"], str(row.get("platform") or "?"), shape, labels
        ) if p
    )


def is_clean(row: Dict[str, Any]) -> bool:
    """Baseline-worthy: not contended, and any reprobe stamp at or
    above the clean edge. An unstamped record (no reprobe machinery on
    that spec) counts as clean unless it stamped contended."""
    if row.get("contended"):
        return False
    ratio = row.get("peak_reprobe_ratio")
    return ratio is None or float(ratio) >= CLEAN_REPROBE_RATIO


def dispersion(row: Dict[str, Any]) -> Optional[float]:
    """Relative per-rep spread ((max-min)/value) — the record's own
    run-to-run noise evidence."""
    lo, hi = row.get("rep_min"), row.get("rep_max")
    if (
        isinstance(lo, (int, float)) and isinstance(hi, (int, float))
        and row["value"] > 0
    ):
        return max(float(hi) - float(lo), 0.0) / float(row["value"])
    return None


def _reprobe_slack(row: Dict[str, Any]) -> Optional[float]:
    ratio = row.get("peak_reprobe_ratio")
    if isinstance(ratio, (int, float)):
        return max(1.0 - float(ratio), 0.0)
    return None


def noise_band(
    a: Dict[str, Any], b: Dict[str, Any], *, floor: float = NOISE_FLOOR
) -> float:
    """The relative band a delta must clear to be signal: the max of
    the floor, both records' rep dispersion, and both records' reprobe
    slack (a 0.88 reprobe means the host was ~12% depressed — a 12%
    delta between such records proves nothing)."""
    candidates = [float(floor)]
    for row in (a, b):
        d = dispersion(row)
        if d is not None:
            candidates.append(d)
        s = _reprobe_slack(row)
        if s is not None:
            candidates.append(s)
    return max(candidates)


def _lower_is_better(row: Dict[str, Any]) -> bool:
    unit = str(row.get("unit") or "")
    return "second" in unit or unit.endswith("ms") or unit.startswith("ms")


def diff_rows(
    a: Dict[str, Any], b: Dict[str, Any], *, floor: float = NOISE_FLOOR
) -> Dict[str, Any]:
    """Compare two ledger rows (a = older/baseline, b = newer).
    Raises :class:`LedgerError` on a cross-platform pair; returns the
    delta judged against the pair's noise band, with contended arms and
    key mismatches flagged rather than hidden."""
    if (a.get("platform") or "?") != (b.get("platform") or "?"):
        raise LedgerError(
            f"refusing cross-platform diff: {a['name']} is "
            f"{a.get('platform')!r}, {b['name']} is {b.get('platform')!r} "
            "— a delta across platforms is a category error, not a number"
        )
    warnings: List[str] = []
    if row_key(a) != row_key(b):
        warnings.append(
            f"keys differ ({row_key(a)} vs {row_key(b)}): this is an A/B "
            "across configs, not a history delta"
        )
    for label, row in (("a", a), ("b", b)):
        if row.get("contended"):
            warnings.append(
                f"arm {label} is CONTENDED (reprobe "
                f"{row.get('peak_reprobe_ratio')}): its value is a floor, "
                "not a measurement"
            )
    band = noise_band(a, b, floor=floor)
    delta = (
        (b["value"] - a["value"]) / a["value"] if a["value"] else math.inf
    )
    lower_better = _lower_is_better(a)
    if abs(delta) <= band:
        verdict = "within-noise"
    elif (delta < 0) != lower_better:
        # moved the wrong way for this unit's direction: a drop in a
        # higher-is-better metric, or a rise in seconds/step
        verdict = "regressed"
    else:
        verdict = "improved"
    return {
        "a": {"value": a["value"], "recorded_at": a.get("recorded_at"),
              "source": a.get("source")},
        "b": {"value": b["value"], "recorded_at": b.get("recorded_at"),
              "source": b.get("source")},
        "unit": a.get("unit"),
        "delta_pct": round(delta * 100.0, 2),
        "band_pct": round(band * 100.0, 2),
        "verdict": verdict,
        "warnings": warnings,
    }


def latest_clean_baseline(
    rows: List[Dict[str, Any]], key: str
) -> Optional[Dict[str, Any]]:
    """Newest clean row for ``key`` in file order (the session file is
    append-only, so file order IS time order even when older records
    predate the recorded_at stamp)."""
    for row in reversed(rows):
        if row_key(row) == key and is_clean(row):
            return row
    return None


def regress(
    fresh: List[Dict[str, Any]],
    baseline_rows: List[Dict[str, Any]],
    *,
    floor: float = NOISE_FLOOR,
) -> List[Dict[str, Any]]:
    """The sentry: one verdict per fresh row.

    * ``regression`` — fresh is CLEAN and fell beyond the noise band
      vs the latest clean baseline (the only verdict that exits 1);
    * ``untrusted`` — fresh is contended/dirty: whatever it measured,
      contention already explains it (warn, never block CI on it);
    * ``ok`` / ``improved`` / ``within-noise`` — self-describing;
    * ``no-baseline`` — first record for its key: it BECOMES history.
    """
    verdicts: List[Dict[str, Any]] = []
    for row in fresh:
        key = row_key(row)
        base = latest_clean_baseline(baseline_rows, key)
        entry: Dict[str, Any] = {
            "name": row["name"],
            "key": key,
            "fresh_value": row["value"],
            "unit": row.get("unit"),
            "host": row.get("host"),
        }
        if base is None:
            entry.update(verdict="no-baseline", reason=(
                "no clean committed record for this key — this record "
                "becomes the baseline"
            ))
            verdicts.append(entry)
            continue
        d = diff_rows(base, row, floor=floor)
        entry.update(
            baseline_value=base["value"],
            baseline_recorded_at=base.get("recorded_at"),
            delta_pct=d["delta_pct"],
            band_pct=d["band_pct"],
        )
        if not is_clean(row):
            entry.update(verdict="untrusted", reason=(
                f"fresh record is contended (reprobe "
                f"{row.get('peak_reprobe_ratio')}) — a drop here is "
                "explained by the host, not the code"
            ))
        elif d["verdict"] == "regressed":
            entry.update(verdict="regression", reason=(
                f"clean fresh record fell {abs(d['delta_pct']):.1f}% vs "
                f"the clean baseline, beyond the {d['band_pct']:.1f}% "
                "noise band"
            ))
        elif d["verdict"] == "improved":
            entry.update(verdict="improved", reason=(
                f"{abs(d['delta_pct']):.1f}% better than baseline "
                f"(band {d['band_pct']:.1f}%)"
            ))
        else:
            entry.update(verdict="ok", reason=(
                f"delta {d['delta_pct']:+.1f}% within the "
                f"{d['band_pct']:.1f}% noise band"
            ))
        verdicts.append(entry)
    return verdicts


# -- rendering ---------------------------------------------------------
def render_rows(rows: List[Dict[str, Any]], *, skipped: int = 0) -> str:
    """``ledger list``: one line per key — history depth, clean count,
    latest value."""
    by_key: Dict[str, List[Dict[str, Any]]] = {}
    for row in rows:
        by_key.setdefault(row_key(row), []).append(row)
    lines = [f"run ledger: {len(rows)} records, {len(by_key)} keys"
             + (f" ({skipped} torn/stub lines skipped)" if skipped else "")]
    for key in sorted(by_key):
        hist = by_key[key]
        clean = sum(1 for r in hist if is_clean(r))
        last = hist[-1]
        stamp = last.get("recorded_at") or "-"
        lines.append(
            f"  {key}\n"
            f"    n={len(hist)} clean={clean} latest={last['value']:g} "
            f"{last.get('unit') or ''} @ {stamp}"
        )
    return "\n".join(lines)


def render_history(rows: List[Dict[str, Any]], name: str) -> str:
    """``ledger show NAME``: every record for keys under ``name``, in
    file order, with the trust stamps visible."""
    picked = [r for r in rows if r["name"] == name]
    if not picked:
        return f"no ledger rows named {name!r}"
    lines = [f"history for {name!r}: {len(picked)} record(s)"]
    for r in picked:
        ratio = r.get("peak_reprobe_ratio")
        disp = dispersion(r)
        lines.append(
            f"  {r.get('recorded_at') or '-':22s} {r['value']:>12g} "
            f"{(r.get('unit') or ''):14s} "
            f"reprobe={ratio if ratio is not None else '-':<6} "
            f"disp={f'{disp * 100:.1f}%' if disp is not None else '-':<6} "
            f"{'CONTENDED' if r.get('contended') else 'clean':<9} "
            f"{row_key(r)}"
        )
    return "\n".join(lines)


def render_diff(d: Dict[str, Any]) -> str:
    lines = [
        f"a: {d['a']['value']:g} {d.get('unit') or ''} "
        f"@ {d['a'].get('recorded_at') or '-'}",
        f"b: {d['b']['value']:g} {d.get('unit') or ''} "
        f"@ {d['b'].get('recorded_at') or '-'}",
        f"delta: {d['delta_pct']:+.2f}%  noise band: ±{d['band_pct']:.2f}%  "
        f"verdict: {d['verdict']}",
    ]
    for w in d.get("warnings") or []:
        lines.append(f"warning: {w}")
    return "\n".join(lines)


def render_verdicts(verdicts: List[Dict[str, Any]]) -> str:
    lines: List[str] = []
    for v in verdicts:
        head = f"[{v['verdict'].upper()}] {v['key']}"
        val = f"fresh={v['fresh_value']:g} {v.get('unit') or ''}"
        if v.get("baseline_value") is not None:
            val += (
                f" baseline={v['baseline_value']:g}"
                f" delta={v['delta_pct']:+.1f}%"
                f" band=±{v['band_pct']:.1f}%"
            )
        lines.append(head)
        lines.append(f"  {val}")
        lines.append(f"  {v.get('reason')}")
    n_reg = sum(1 for v in verdicts if v["verdict"] == "regression")
    lines.append(
        f"{len(verdicts)} verdict(s), {n_reg} confirmed regression(s)"
    )
    return "\n".join(lines)
