"""Prometheus text exposition (version 0.0.4) for the repo's metric
registries — the bridge from the bespoke JSON ``/metrics`` payloads to
any off-the-shelf scraper.

The JSON snapshots stay the in-repo contract (the autoscaler, the canary
guard, ``bench.py`` all read them); this module renders the SAME
snapshot dicts as standard exposition text, so ``GET
/metrics?format=prometheus`` on a replica, the router, or the trainer
needs no second bookkeeping path that could drift from the JSON one.

Honesty rules, because exposition semantics are a contract with the
scraper:

* counters render as ``<name>_total`` with ``# TYPE ... counter``;
* gauges with a ``None`` value are OMITTED (an absent series is the
  exposition spelling of "this backend doesn't report that"), never
  rendered as a fake 0;
* histograms WITH cumulative bucket tables (``_Histogram(buckets=...)``)
  render as real Prometheus histograms — ``_bucket{le="..."}`` series
  (cumulative, ``+Inf`` == ``_count``), ``_sum``, ``_count`` — which a
  scraper may sum across replicas exactly;
* histograms WITHOUT buckets render as summaries (``{quantile="..."}``
  from the bounded sample ring) — the honest label for percentiles that
  cannot be aggregated downstream.

Stdlib-only; safe to import in processes that never load jax (the
router, ``telemetry top``).
"""

from __future__ import annotations

import math
import re
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["PromFamilies", "render_snapshot", "metric_name", "EXPOSITION_CONTENT_TYPE"]

EXPOSITION_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_INVALID_NAME_CHARS = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_ESCAPES = {"\\": "\\\\", '"': '\\"', "\n": "\\n"}


def metric_name(prefix: str, name: str) -> str:
    """``<prefix>_<name>`` with every invalid character collapsed to
    ``_`` — registry names are free-form strings; exposition names are
    ``[a-zA-Z_:][a-zA-Z0-9_:]*``."""
    out = _INVALID_NAME_CHARS.sub("_", f"{prefix}_{name}")
    return out if not out[:1].isdigit() else f"_{out}"


def _escape_label(v: Any) -> str:
    return "".join(_LABEL_ESCAPES.get(c, c) for c in str(v))


def _fmt_value(v: Any) -> str:
    f = float(v)
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _fmt_le(bound: float) -> str:
    return "+Inf" if math.isinf(bound) else _fmt_value(bound)


class PromFamilies:
    """Collects samples grouped into metric families, then renders the
    whole exposition in one pass — the grouping is what lets the router
    emit one ``# TYPE`` header above N replicas' labeled series (the
    format forbids repeating it per label set)."""

    def __init__(self) -> None:
        # name -> (type, [(sorted label items, value)])
        self._families: "Dict[str, Tuple[str, List[Tuple[Tuple[Tuple[str, str], ...], str]]]]" = {}
        self._order: List[str] = []

    def add(
        self,
        name: str,
        mtype: str,
        value: Any,
        labels: Optional[Dict[str, Any]] = None,
    ) -> None:
        if value is None:
            return  # absent, not zero — the honest-gauge rule
        if name not in self._families:
            self._families[name] = (mtype, [])
            self._order.append(name)
        family_type, samples = self._families[name]
        if family_type != mtype:
            raise ValueError(
                f"metric family {name!r} registered as {family_type}, "
                f"re-added as {mtype}"
            )
        items = tuple(
            sorted((str(k), _escape_label(v)) for k, v in (labels or {}).items())
        )
        samples.append((items, _fmt_value(value)))

    # -- snapshot ingestion -------------------------------------------
    def add_snapshot(
        self,
        snapshot: Dict[str, Any],
        *,
        prefix: str,
        labels: Optional[Dict[str, Any]] = None,
    ) -> None:
        """Ingest one ``MetricsRegistry.snapshot()``-shaped dict (the
        ``counters``/``gauges``/``histograms`` triple every telemetry
        facade in this repo emits) under ``prefix`` with ``labels`` on
        every series."""
        for key, value in sorted((snapshot.get("counters") or {}).items()):
            if isinstance(value, (int, float)):
                self.add(
                    metric_name(prefix, f"{key}_total"), "counter",
                    value, labels,
                )
        for key, value in sorted((snapshot.get("gauges") or {}).items()):
            if isinstance(value, (int, float)):
                self.add(metric_name(prefix, key), "gauge", value, labels)
        for key, hist in sorted((snapshot.get("histograms") or {}).items()):
            if isinstance(hist, dict):
                self.add_histogram(metric_name(prefix, key), hist, labels)

    def add_histogram(
        self,
        name: str,
        hist: Dict[str, Any],
        labels: Optional[Dict[str, Any]] = None,
    ) -> None:
        """One histogram snapshot: real ``_bucket`` exposition when a
        cumulative bucket table exists, summary quantiles otherwise."""
        count = hist.get("count") or 0
        total = hist.get("sum") or 0.0
        base = dict(labels or {})
        buckets = hist.get("buckets")
        if buckets:
            for le, cum in buckets:
                self.add(
                    f"{name}_bucket", "histogram", cum,
                    {**base, "le": _fmt_le(float(le))},
                )
            self.add(
                f"{name}_bucket", "histogram", count, {**base, "le": "+Inf"}
            )
            self.add(f"{name}_sum", "histogram", total, base)
            self.add(f"{name}_count", "histogram", count, base)
            return
        for q, key in (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99")):
            v = hist.get(key)
            if isinstance(v, (int, float)):
                self.add(name, "summary", v, {**base, "quantile": q})
        self.add(f"{name}_sum", "summary", total, base)
        self.add(f"{name}_count", "summary", count, base)

    # -- rendering -----------------------------------------------------
    def render(self) -> str:
        lines: List[str] = []
        typed: set = set()
        for name in self._order:
            mtype, samples = self._families[name]
            # one TYPE line per family; _bucket/_sum/_count share their
            # parent histogram/summary family's header
            family = re.sub(r"_(bucket|sum|count)$", "", name) if mtype in (
                "histogram", "summary"
            ) else name
            if family not in typed:
                typed.add(family)
                lines.append(f"# TYPE {family} {mtype}")
            for items, value in samples:
                if items:
                    label_s = ",".join(f'{k}="{v}"' for k, v in items)
                    lines.append(f"{name}{{{label_s}}} {value}")
                else:
                    lines.append(f"{name} {value}")
        return "\n".join(lines) + ("\n" if lines else "")


def render_snapshot(
    snapshot: Dict[str, Any],
    *,
    prefix: str,
    labels: Optional[Dict[str, Any]] = None,
) -> str:
    """One registry snapshot → exposition text (the replica/trainer
    case; the router assembles a multi-source :class:`PromFamilies`)."""
    fam = PromFamilies()
    fam.add_snapshot(snapshot, prefix=prefix, labels=labels)
    return fam.render()
