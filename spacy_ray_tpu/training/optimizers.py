"""Optimizers + LR schedules: registered ``@optimizers`` / ``@schedules``.

Capability parity with the thinc Optimizer surface the reference drives
(reference proxies.py:128 ``optimizer(key, param, grad)``;
``step_schedules`` at worker.py/proxies via thinc; FakeOptimizer no-op at
reference worker.py:265-278). Here the optimizer is an optax
GradientTransformation compiled INTO the train step — there is no per-key
optimizer call and no proxy, so the reference's whole stale-gradient /
quorum machinery (proxies.py:111-133) has no equivalent to need.

``Adam.v1`` matches the config-surface of thinc's Adam (learn_rate, betas,
eps, L2, grad_clip, L2_is_weight_decay, use_averages).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Optional, Union

import jax
import optax

from ..registry import registry

ScheduleLike = Union[float, Callable[[int], float], Iterable[float]]


class Schedule:
    """A LR schedule usable both as an optax step->value callable and as an
    iterator (thinc schedules are generators; optax wants step->value).

    ``fn`` MUST be jnp-traceable: inside the jitted train step the optax
    step count is a tracer, so python control flow on it would crash.
    """

    def __init__(self, fn: Callable[[Any], Any]):
        self.fn = fn
        self._step = 0

    def __call__(self, step):
        return self.fn(step)

    def __iter__(self):
        return self

    def __next__(self) -> float:
        val = float(self.fn(self._step))
        self._step += 1
        return val


def as_schedule_fn(value: ScheduleLike) -> Callable[[Any], Any]:
    """Normalize a learn_rate config value to a traceable step->rate fn."""
    import jax.numpy as jnp

    if isinstance(value, Schedule):
        return value.fn
    if isinstance(value, (int, float)):
        return lambda step: jnp.float32(value)
    if callable(value):
        return value
    # A generator/iterable (e.g. compounding.v1 used as LR): materialize a
    # long prefix into a device array and index it — python iteration can't
    # run under jit.
    import itertools

    table = jnp.asarray(
        [float(v) for v in itertools.islice(iter(value), 100_000)], dtype=jnp.float32
    )
    if table.size == 0:
        return lambda step: jnp.float32(0.0)

    def fn(step):
        idx = jnp.minimum(step, table.size - 1)
        return jnp.take(table, idx)

    return fn


@registry.schedules("warmup_linear.v1")
def warmup_linear(initial_rate: float, warmup_steps: int, total_steps: int) -> Schedule:
    """Linear warmup then linear decay — jnp-traceable (runs inside jit)."""
    import jax.numpy as jnp

    warmup = max(int(warmup_steps), 0)
    decay_span = max(int(total_steps) - warmup, 1)

    def fn(step):
        step = jnp.asarray(step, jnp.float32)
        warm = initial_rate * (step + 1.0) / max(warmup, 1)
        frac = (step - warmup) / decay_span
        decayed = jnp.maximum(initial_rate * (1.0 - frac), 0.0)
        if warmup == 0:
            return decayed
        return jnp.where(step < warmup, warm, decayed)

    return Schedule(fn)


@registry.schedules("linear.v1")
def linear(initial_rate: float, final_rate: float, total_steps: int) -> Schedule:
    import jax.numpy as jnp

    span = max(int(total_steps), 1)

    def fn(step):
        frac = jnp.minimum(jnp.asarray(step, jnp.float32) / span, 1.0)
        return initial_rate + (final_rate - initial_rate) * frac

    return Schedule(fn)


@registry.schedules("cosine.v1")
def cosine(initial_rate: float, total_steps: int, final_scale: float = 0.0) -> Schedule:
    import jax.numpy as jnp

    span = max(int(total_steps), 1)

    def fn(step):
        frac = jnp.minimum(jnp.asarray(step, jnp.float32) / span, 1.0)
        return initial_rate * (
            final_scale + (1 - final_scale) * 0.5 * (1 + jnp.cos(jnp.pi * frac))
        )

    return Schedule(fn)


class OptimizerWrapper:
    """optax transformation + framework metadata.

    ``use_averages`` signals the loop to keep a running mean of params and
    evaluate/checkpoint with it (thinc Adam's averages semantics — the
    reference's optimizer is constructed from config with use_averages and
    spacy evaluates under ``use_params(optimizer.averages)``).

    ``fusable`` (set by the Adam.v1 / RAdam.v1 factories) records the
    chain's hyperparameters so :func:`fuse_optimizer` can rebuild it as a
    single fused traversal (ops/fused_update.py — the ``[training]
    fused_update`` knob). ``applies_updates`` marks a wrapper whose
    ``update`` returns NEW PARAMS directly (apply folded in); the train
    step checks it before running its own ``optax.apply_updates``.
    """

    def __init__(self, tx: optax.GradientTransformation, use_averages: bool = False):
        self.tx = tx
        self.use_averages = use_averages
        self.fusable: Optional[dict] = None
        self.applies_updates = False

    def init(self, params):
        return self.tx.init(params)

    def update(self, grads, state, params=None):
        return self.tx.update(grads, state, params)


def fuse_optimizer(tx) -> Optional["OptimizerWrapper"]:
    """Rebuild a fusable optimizer as a single-traversal fused update.

    Returns None when ``tx`` is not fusable — an optimizer other than
    Adam.v1/RAdam.v1, or one wrapped by ``optax.masked`` for frozen
    components (``mask_frozen`` drops the metadata, so frozen runs keep
    the reference chain). The fused state structure is identical to the
    chain's (init delegates), so checkpoints survive knob flips.
    """
    meta = getattr(tx, "fusable", None)
    if not meta:
        return None
    from ..ops import fused_update as _fu

    fused = _fu.make_fused_transformation(reference_tx=tx.tx, **meta)
    out = OptimizerWrapper(fused, use_averages=tx.use_averages)
    out.applies_updates = True
    return out


def mask_frozen(tx, params):
    """Wrap a transformation with optax.masked so leaves under a dict key
    starting with "frozen_" (e.g. static-vector tables) get NO updates, NO
    weight decay, and NO optimizer-state moments."""

    def trainable_tree(tree):
        def rec(node, frozen):
            if isinstance(node, dict):
                return {
                    k: rec(v, frozen or str(k).startswith("frozen_"))
                    for k, v in node.items()
                }
            return not frozen

        return rec(tree, False)

    mask = trainable_tree(params)
    if all(jax.tree_util.tree_leaves(mask)):
        return tx  # nothing frozen: keep the plain transformation
    inner = tx.tx if isinstance(tx, OptimizerWrapper) else tx
    masked = optax.masked(inner, mask)
    if isinstance(tx, OptimizerWrapper):
        return OptimizerWrapper(masked, use_averages=tx.use_averages)
    return masked


@registry.optimizers("Adam.v1")
def Adam(
    learn_rate: ScheduleLike = 0.001,
    beta1: float = 0.9,
    beta2: float = 0.999,
    eps: float = 1e-8,
    L2: float = 0.0,
    grad_clip: float = 1.0,
    L2_is_weight_decay: bool = True,
    use_averages: bool = False,
) -> OptimizerWrapper:
    lr_fn = as_schedule_fn(learn_rate)
    chain = []
    if grad_clip and grad_clip > 0:
        chain.append(optax.clip_by_global_norm(grad_clip))
    if L2 and not L2_is_weight_decay:
        chain.append(optax.add_decayed_weights(L2))  # classic L2 into grads
    adam_idx = len(chain)
    chain.append(optax.scale_by_adam(b1=beta1, b2=beta2, eps=eps))
    if L2 and L2_is_weight_decay:
        chain.append(optax.add_decayed_weights(L2))
    chain.append(optax.scale_by_learning_rate(lr_fn))
    out = OptimizerWrapper(optax.chain(*chain), use_averages=use_averages)
    out.fusable = dict(
        kind="adam", lr_fn=lr_fn, b1=beta1, b2=beta2, eps=eps,
        grad_clip=grad_clip if grad_clip and grad_clip > 0 else 0.0,
        l2_grad=L2 if (L2 and not L2_is_weight_decay) else 0.0,
        l2_decay=L2 if (L2 and L2_is_weight_decay) else 0.0,
        adam_idx=adam_idx, sched_idx=len(chain) - 1,
    )
    return out


@registry.optimizers("SGD.v1")
def SGD(
    learn_rate: ScheduleLike = 0.001, L2: float = 0.0, grad_clip: float = 1.0
) -> optax.GradientTransformation:
    lr_fn = as_schedule_fn(learn_rate)
    chain = []
    if grad_clip and grad_clip > 0:
        chain.append(optax.clip_by_global_norm(grad_clip))
    if L2:
        chain.append(optax.add_decayed_weights(L2))
    chain.append(optax.scale_by_learning_rate(lr_fn))
    return optax.chain(*chain)


@registry.optimizers("RAdam.v1")
def RAdam(
    learn_rate: ScheduleLike = 0.001,
    beta1: float = 0.9,
    beta2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    grad_clip: float = 1.0,
) -> OptimizerWrapper:
    lr_fn = as_schedule_fn(learn_rate)
    chain = []
    if grad_clip and grad_clip > 0:
        chain.append(optax.clip_by_global_norm(grad_clip))
    adam_idx = len(chain)
    chain.append(optax.scale_by_radam(b1=beta1, b2=beta2, eps=eps))
    if weight_decay:
        chain.append(optax.add_decayed_weights(weight_decay))
    chain.append(optax.scale_by_learning_rate(lr_fn))
    out = OptimizerWrapper(optax.chain(*chain))
    out.fusable = dict(
        kind="radam", lr_fn=lr_fn, b1=beta1, b2=beta2, eps=eps,
        grad_clip=grad_clip if grad_clip and grad_clip > 0 else 0.0,
        l2_grad=0.0, l2_decay=weight_decay or 0.0,
        adam_idx=adam_idx, sched_idx=len(chain) - 1,
    )
    return out
