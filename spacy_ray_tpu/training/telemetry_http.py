"""Trainer-side telemetry HTTP endpoint: ``/metrics`` (JSON or
Prometheus exposition), ``/healthz`` (liveness + the monotonic-clock
anchor a cross-process trace collector needs), and ``/trace`` (the live
Chrome-trace buffer).

Serving replicas and the fleet router already answer these on their
listener ports; the training loop has no listener — this module gives it
one, gated behind ``[training] metrics_port`` / ``train --metrics-port``
(0 = off, the default). With it on, the trainer becomes the third
scrape target of the observability plane: ``telemetry top`` polls its
step rate, a Prometheus server scrapes its counters, and ``telemetry
collect-trace`` merges its spans into the fleet timeline — the Ray-style
"one timeline for the whole system" view (PAPERS.md).

The handler thread only READS the telemetry objects (registry snapshot,
trace payload) — it never touches the training loop's state, so the
endpoint adds zero work to the hot path. With telemetry disabled the
server is never constructed at all (the loop's zero-calls contract).
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from .telemetry import Telemetry, sanitize_json

__all__ = [
    "TelemetryHTTPServer",
    "metrics_reply",
    "trace_reply",
    "alerts_reply",
]

logger = logging.getLogger("spacy_ray_tpu.training")


# -- shared reply builders ---------------------------------------------
# The trainer's listener (below) and the trainer-fleet peer server
# (training/fleet/peer.py) expose the SAME telemetry surface; these
# builders are the one definition of what /metrics, /trace and
# /admin/alerts serve, so the two handlers cannot drift — the fleet
# variant only adds a worker label (Prometheus) / worker field (JSON).
# Fleet workers' registries also carry the wire-byte compression ledger
# (telemetry.FLEET_WIRE_COUNTERS -> srt_training_wire_*_bytes_total
# series); it arrives here through the same snapshot, nothing special.


def metrics_reply(
    tel: Any,
    fmt: str,
    *,
    prefix: str = "srt_training",
    labels: Optional[Dict[str, Any]] = None,
    json_extra: Optional[Dict[str, Any]] = None,
) -> Tuple[bytes, str]:
    """``(body, content_type)`` for a trainer-role ``/metrics`` reply:
    the registry snapshot as Prometheus exposition (``labels`` on every
    family — the fleet's per-worker series) or as JSON (``json_extra``
    merged in), alert summary/series appended when an engine exists."""
    alerts = getattr(tel, "alerts", None)
    # host-resource truth: the facade owns the sampler (disabled
    # telemetry = no facade = no /proc reads); srt_process_* is a
    # shared gauge family, NOT a prefixed snapshot key, so the same
    # names line up across trainer/peer/replica/router scrapes
    sampler = getattr(tel, "hoststats", None)
    if fmt == "prometheus":
        from .hoststats import add_process_family
        from .prometheus import EXPOSITION_CONTENT_TYPE, PromFamilies

        fam = PromFamilies()
        fam.add_snapshot(
            tel.registry.snapshot(), prefix=prefix, labels=labels
        )
        if sampler is not None:
            add_process_family(fam, sampler.sample(), labels=labels)
        if alerts is not None:
            alerts.add_prometheus(fam)
        return fam.render().encode("utf8"), EXPOSITION_CONTENT_TYPE
    snap = tel.registry.snapshot()
    if sampler is not None:
        snap["process"] = sampler.sample()
    if json_extra:
        snap.update(json_extra)
    if alerts is not None:
        # the compact block `telemetry top` renders; full per-rule
        # states live on /admin/alerts
        snap["alerts"] = alerts.summary()
    return (
        json.dumps(sanitize_json(snap)).encode("utf8"),
        "application/json",
    )


def trace_reply(tel: Any, role: str) -> Dict[str, Any]:
    """The live Chrome-trace payload + the clock anchor a cross-process
    collector needs to place it on a shared timeline."""
    payload = tel.trace.payload()
    payload["anchor"] = tel.trace.anchor()
    payload["role"] = role
    return payload


def alerts_reply(tel: Any) -> Dict[str, Any]:
    alerts = getattr(tel, "alerts", None)
    if alerts is None:
        return {"alerts": "disabled"}
    return {"alerts": alerts.states()}


class _TelemetryHTTPD(ThreadingHTTPServer):
    daemon_threads = True
    tel: Telemetry
    role: str


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server: _TelemetryHTTPD

    def log_message(self, fmt: str, *args: Any) -> None:
        logger.debug("%s " + fmt, self.address_string(), *args)

    def _reply_json(self, status: int, payload: Dict[str, Any]) -> None:
        body = json.dumps(sanitize_json(payload)).encode("utf8")
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802 (stdlib handler naming)
        tel = self.server.tel
        parsed = urlparse(self.path)
        if parsed.path == "/healthz":
            self._reply_json(
                200,
                {
                    "status": "ok",
                    "role": self.server.role,
                    "anchor": tel.trace.anchor(),
                },
            )
        elif parsed.path == "/metrics":
            fmt = (parse_qs(parsed.query).get("format") or [""])[0]
            body, content_type = metrics_reply(tel, fmt)
            self.send_response(200)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif parsed.path == "/admin/alerts":
            self._reply_json(200, alerts_reply(tel))
        elif parsed.path == "/trace":
            self._reply_json(200, trace_reply(tel, self.server.role))
        else:
            self._reply_json(
                404, {"error": "not_found", "message": parsed.path}
            )


class TelemetryHTTPServer:
    """Lifecycle wrapper: ``start()`` binds and serves on a daemon
    thread, ``stop()`` tears down. Constructed only when telemetry is on
    AND a port is configured."""

    def __init__(
        self,
        telemetry: Telemetry,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        role: str = "trainer",
    ) -> None:
        self.httpd = _TelemetryHTTPD((host, int(port)), _Handler)
        self.httpd.tel = telemetry
        self.httpd.role = role
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> Tuple[str, int]:
        host, port = self.httpd.server_address[:2]
        return str(host), int(port)

    def start(self) -> Tuple[str, int]:
        self._thread = threading.Thread(
            target=self.httpd.serve_forever,
            kwargs={"poll_interval": 0.2},
            name="telemetry-http",
            daemon=True,
        )
        self._thread.start()
        return self.address

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
