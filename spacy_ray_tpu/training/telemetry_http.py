"""Trainer-side telemetry HTTP endpoint: ``/metrics`` (JSON or
Prometheus exposition), ``/healthz`` (liveness + the monotonic-clock
anchor a cross-process trace collector needs), and ``/trace`` (the live
Chrome-trace buffer).

Serving replicas and the fleet router already answer these on their
listener ports; the training loop has no listener — this module gives it
one, gated behind ``[training] metrics_port`` / ``train --metrics-port``
(0 = off, the default). With it on, the trainer becomes the third
scrape target of the observability plane: ``telemetry top`` polls its
step rate, a Prometheus server scrapes its counters, and ``telemetry
collect-trace`` merges its spans into the fleet timeline — the Ray-style
"one timeline for the whole system" view (PAPERS.md).

The handler thread only READS the telemetry objects (registry snapshot,
trace payload) — it never touches the training loop's state, so the
endpoint adds zero work to the hot path. With telemetry disabled the
server is never constructed at all (the loop's zero-calls contract).
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from .telemetry import Telemetry, sanitize_json

__all__ = ["TelemetryHTTPServer"]

logger = logging.getLogger("spacy_ray_tpu.training")


class _TelemetryHTTPD(ThreadingHTTPServer):
    daemon_threads = True
    tel: Telemetry
    role: str


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server: _TelemetryHTTPD

    def log_message(self, fmt: str, *args: Any) -> None:
        logger.debug("%s " + fmt, self.address_string(), *args)

    def _reply_json(self, status: int, payload: Dict[str, Any]) -> None:
        body = json.dumps(sanitize_json(payload)).encode("utf8")
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _reply_text(self, status: int, text: str, content_type: str) -> None:
        body = text.encode("utf8")
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802 (stdlib handler naming)
        tel = self.server.tel
        parsed = urlparse(self.path)
        if parsed.path == "/healthz":
            self._reply_json(
                200,
                {
                    "status": "ok",
                    "role": self.server.role,
                    "anchor": tel.trace.anchor(),
                },
            )
        elif parsed.path == "/metrics":
            fmt = (parse_qs(parsed.query).get("format") or [""])[0]
            alerts = getattr(tel, "alerts", None)
            if fmt == "prometheus":
                from .prometheus import (
                    EXPOSITION_CONTENT_TYPE,
                    PromFamilies,
                )

                fam = PromFamilies()
                fam.add_snapshot(
                    tel.registry.snapshot(), prefix="srt_training"
                )
                if alerts is not None:
                    alerts.add_prometheus(fam)
                self._reply_text(200, fam.render(), EXPOSITION_CONTENT_TYPE)
            else:
                snap = tel.registry.snapshot()
                if alerts is not None:
                    # the compact block `telemetry top` renders; full
                    # per-rule states live on /admin/alerts
                    snap["alerts"] = alerts.summary()
                self._reply_json(200, snap)
        elif parsed.path == "/admin/alerts":
            alerts = getattr(tel, "alerts", None)
            if alerts is None:
                self._reply_json(200, {"alerts": "disabled"})
            else:
                self._reply_json(200, {"alerts": alerts.states()})
        elif parsed.path == "/trace":
            payload = tel.trace.payload()
            payload["anchor"] = tel.trace.anchor()
            payload["role"] = self.server.role
            self._reply_json(200, payload)
        else:
            self._reply_json(
                404, {"error": "not_found", "message": parsed.path}
            )


class TelemetryHTTPServer:
    """Lifecycle wrapper: ``start()`` binds and serves on a daemon
    thread, ``stop()`` tears down. Constructed only when telemetry is on
    AND a port is configured."""

    def __init__(
        self,
        telemetry: Telemetry,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        role: str = "trainer",
    ) -> None:
        self.httpd = _TelemetryHTTPD((host, int(port)), _Handler)
        self.httpd.tel = telemetry
        self.httpd.role = role
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> Tuple[str, int]:
        host, port = self.httpd.server_address[:2]
        return str(host), int(port)

    def start(self) -> Tuple[str, int]:
        self._thread = threading.Thread(
            target=self.httpd.serve_forever,
            kwargs={"poll_interval": 0.2},
            name="telemetry-http",
            daemon=True,
        )
        self._thread.start()
        return self.address

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
