"""The training loop: config-driven train-while-improving on a device mesh.

Capability parity with the reference's L4/L5 training path (reference
worker.py:157-204 ``Worker.train`` driving spacy's
``train_while_improving``; SURVEY.md §3.1/3.2 call stacks), redesigned
synchronous-SPMD:

* one process per host, all hosts execute the same loop (no driver/actor
  split; the reference's is_running polling at train_cli.py:88-91 and the
  Evaluator score-exchange actor at worker.py:281-300 disappear — eval
  scores are replicated by SPMD symmetry, SURVEY.md §5.8);
* the data stream is sharded by host (fixing SURVEY.md §2.4 "No data
  sharding by rank"), and the global batch is sharded over the mesh's
  ``data`` axis inside the compiled step;
* patience / best-model selection / eval_frequency semantics match the
  reference's loop contract (worker.py:176-189);
* checkpointing is wired (best-model + last-model + full resume), unlike
  the reference's unreachable save path (SURVEY.md §2.4).
"""

from __future__ import annotations

import math
import random
import time
from functools import partial
from pathlib import Path
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..pipeline.doc import Example
from ..pipeline.language import Pipeline
from ..registry import registry
from ..parallel.mesh import build_mesh
from ..parallel.step import (
    make_train_step,
    place_batch,
    place_replicated,
    resolve_update_sharding,
    shard_opt_state,
    update_sharding_status,
)
from .batcher import bucket_batch_size, bucket_length, shard_stream
from . import resilience
from .checkpoint import CheckpointCorrupt, TrainCheckpoint
from .resilience import ShutdownCoordinator, Watchdog, log_event, maybe_fail
from . import corpus as _corpus  # noqa: F401  (registers readers)
from . import optimizers as _optimizers  # noqa: F401  (registers optimizers)
from . import loggers as _loggers  # noqa: F401  (registers loggers)


DEFAULT_TRAINING = {
    "seed": 0,
    "dropout": 0.1,
    "accumulate_gradient": 1,
    "patience": 1600,
    "max_epochs": 0,
    "max_steps": 20000,
    "eval_frequency": 200,
    "frozen_components": [],
    "annotating_components": [],
    "dev_corpus": "corpora.dev",
    "train_corpus": "corpora.train",
    "score_weights": {},
    "zero1": False,
    # update-phase sharding over the data axis (parallel/step.py):
    # "replicated" = every replica applies the full optimizer update;
    # "zero1" = optimizer STATE sharded (the old zero1=true, which stays
    # as an accepted alias); "full" = the update COMPUTATION is sharded —
    # each replica updates only its owned param shard and the result is
    # allgathered (arXiv 2004.13336). "auto" = honor the zero1 alias,
    # else arm "full" on accelerators with >1 data rank and stay
    # "replicated" on CPU/single-replica (same gating discipline as
    # fused_update). full == replicated bit-exactly (tested), so the knob
    # can be flipped mid-lineage; checkpoints are mesh-shape portable
    # either way. See TUNING.md §15 for when full loses.
    "update_sharding": "auto",
    "mesh": {},  # {"n_model": .., "n_context": .., "n_pipe": ..} axis sizes
    # batches collated + transferred ahead on a background thread (single-
    # process only; 0/1 disables). Overlaps host work with the device step.
    "prefetch_batches": 2,
    # host-side collation fanned out over N worker threads (single-process,
    # non-annotating runs only; 0/1 keeps the inline path). Batch ORDER is
    # preserved and device_put stays on one thread — see collate_pool.py.
    "collate_workers": 0,
    # byte budget (in MB) for the epoch-level collation cache; 0 disables.
    # Auto-bypassed when augmentation is active (fresh Example copies every
    # epoch can never hit an identity-keyed cache) and in annotating mode
    # (targets depend on per-step predictions).
    "collate_cache_mb": 0,
    # checkpoint generations retained under last-model/ — load() falls back
    # generation-by-generation to the newest INTACT one when a file is
    # torn/truncated/missing (training/checkpoint.py)
    "keep_checkpoints": 2,
    # hung-step watchdog: no completed step/eval within this many seconds
    # dumps all thread stacks + pipeline stats and hard-exits RC_WATCHDOG
    # (a desynced multi-host collective wedges forever otherwise). 0 = off;
    # must comfortably exceed first-step compile time when enabled.
    "watchdog_timeout_s": 0,
    # transient-I/O retry (corpus/DocBin opens, checkpoint writes):
    # attempts beyond the first, and the backoff base (doubles per retry,
    # jittered — training/resilience.py)
    "io_retries": 3,
    "io_retry_base_s": 0.5,
    # jax.profiler capture window [start, stop) in steps RUN THIS PROCESS
    # (steps_run, not global step — resume-safe), active only when
    # train --profile / profile_dir is given
    "profile_window": [5, 15],
    # telemetry (training/telemetry.py): directory for metrics.jsonl +
    # trace.json; "" disables the whole subsystem (the hot loop then
    # makes zero telemetry calls). Written by process 0 only.
    "metrics_dir": "",
    # Chrome-trace span window [start, stop) in steps_run: host-stage and
    # step spans are recorded only inside it (eval/checkpoint/anomaly
    # spans always record) — bounds trace size on long runs
    "trace_steps": [0, 50],
    # trainer-side telemetry HTTP endpoint (training/telemetry_http.py):
    # /metrics (JSON or ?format=prometheus), /healthz (trace clock
    # anchor), /trace — the trainer's leg of the cross-process
    # observability plane (`telemetry top`, `telemetry collect-trace`,
    # any Prometheus scraper). 0 (default) = no listener; requires
    # metrics_dir (the endpoint serves the telemetry objects). Process 0
    # only, like the telemetry files.
    "metrics_port": 0,
    # bind address for the metrics_port listener. The loopback default
    # is the safe posture for a laptop run; a pod trainer scraped by an
    # off-host Prometheus/`telemetry top` sets "0.0.0.0" (or the pod
    # interface) — without this the endpoint only ever answers same-host
    # scrapers.
    "metrics_host": "127.0.0.1",
    # NaN/Inf-loss, loss-spike, step-time-regression, recompile-storm
    # detectors (only active when telemetry is on); they emit through
    # log_event so anomalies land in jsonl logger rows too
    "anomaly_detection": True,
    # in-process alert engine (spacy_ray_tpu/alerting.py, only active
    # when telemetry is on): the default training rule set —
    # training-stalled (step counter unchanged for 300s, the watchdog's
    # signal visible BEFORE the watchdog's hard exit) and anomaly-burst —
    # evaluated on a rate-limited boundary hook PLUS a slow wall-clock
    # ticker thread (a wedged loop stops reaching boundaries; the ticker
    # is what lets the stall rule still fire); transitions land in
    # <metrics_dir>/alerts.jsonl and the /metrics endpoint's alert state
    "alerting": True,
    # flight recorder (spacy_ray_tpu/incidents.py): directory for
    # incident bundles — when an anomaly detector trips or an alert
    # fires, the recent metric-snapshot ring + the live span ring are
    # dumped to <incident_dir>/<utc-stamp>-<source>/ for `telemetry
    # postmortem`. "" (default) = recorder off; requires metrics_dir.
    "incident_dir": "",
    # fused optimizer update (ops/fused_update.py): the whole Adam/RAdam
    # chain + apply_updates as ONE traversal (pallas kernel on TPU when
    # the startup probe passes). "auto" = fuse on accelerators when the
    # optimizer is fusable (Adam.v1/RAdam.v1, no frozen components) and
    # keep the reference chain on CPU (measured parity there — PERF.md
    # round 7); "on" = require it anywhere, "off" = never. State
    # structure is identical either way — checkpoints survive knob flips.
    "fused_update": "auto",
    # bf16 parameter shadow: keep a persistently maintained bfloat16 copy
    # of the transformer trunk's matmul weights next to the f32 masters,
    # refreshed inside the jitted update — the per-step (and per-remat-
    # backward) 124M-weight cast disappears. "auto" = on when the trunk's
    # compute dtype resolves to bfloat16 (accelerators; compute_dtype
    # semantics unchanged), "on" = require that, "off" = never.
    "bf16_shadow": "auto",
    # run K train steps per host round-trip (lax.scan over K pre-staged
    # device batches). Default 1 = exactly the old behavior; raised, the
    # dispatch is capped so eval/max_steps boundaries still land exactly,
    # and results are bit-identical to K=1 (tested). Auto-bypassed (K=1)
    # for annotating runs, before_update callbacks, and use_averages —
    # each needs the host between consecutive steps. See TUNING.md §11
    # for when NOT to raise it (watchdog granularity, preemption latency).
    "steps_per_dispatch": 1,
    # trainer-fleet peer connection deadlines (fleet mode only; plain
    # runs ignore them). fleet_peer_timeout_s bounds every step-traffic
    # exchange (grad push, param pull); fleet_probe_timeout_s bounds the
    # liveness/membership/watch probes — probes must fail FAST so the
    # lease verdict reflects reality, while step traffic gets room for a
    # big frame on a loaded box. The /checkpoint exchange has its own
    # (much longer) checkpoint_timeout_s on the worker entry point.
    "fleet_peer_timeout_s": 10.0,
    "fleet_probe_timeout_s": 5.0,
}

# Sub-blocks resolved through the registry rather than read as plain values.
# Together with DEFAULT_TRAINING these are the FULL key surface of
# [training] — anything else is rejected (the role of the reference's
# pydantic ConfigSchemaTraining validation, reference worker.py:93
# registry.resolve(config["training"], schema=ConfigSchemaTraining)).
_TRAINING_BLOCK_KEYS = {"optimizer", "batcher", "logger", "before_update"}

# What each registry sub-block resolves to when the config omits it — the
# single source for fill-config (writes them out) and debug-diff-config
# (classifies against them).
DEFAULT_TRAINING_BLOCKS = {
    "optimizer": {"@optimizers": "Adam.v1", "learn_rate": 0.001},
    "batcher": {"@batchers": "spacy.batch_by_words.v1", "size": 1000,
                "tolerance": 0.2},
    "logger": {"@loggers": "spacy_ray_tpu.ConsoleLogger.v1"},
}

# value validators: (predicate, description) — intentionally permissive
# (ints where floats are fine etc.), strict on category errors
_TRAINING_TYPES: Dict[str, Tuple[Callable[[Any], bool], str]] = {
    "seed": (lambda v: isinstance(v, int) and not isinstance(v, bool), "an int"),
    "dropout": (
        lambda v: isinstance(v, (int, float))
        and not isinstance(v, bool)
        and 0.0 <= float(v) < 1.0,
        "a float in [0, 1)",
    ),
    "accumulate_gradient": (
        lambda v: isinstance(v, int) and not isinstance(v, bool) and v >= 1,
        "an int >= 1",
    ),
    "patience": (lambda v: isinstance(v, int) and not isinstance(v, bool) and v >= 0, "an int >= 0"),
    "max_epochs": (
        lambda v: isinstance(v, int) and not isinstance(v, bool) and v >= -1,
        "an int >= -1",
    ),
    "max_steps": (lambda v: isinstance(v, int) and not isinstance(v, bool) and v >= 0, "an int >= 0"),
    "eval_frequency": (
        lambda v: isinstance(v, int) and not isinstance(v, bool) and v >= 1,
        "an int >= 1",
    ),
    "frozen_components": (
        lambda v: isinstance(v, (list, tuple)) and all(isinstance(x, str) for x in v),
        "a list of component names",
    ),
    "annotating_components": (
        lambda v: isinstance(v, (list, tuple)) and all(isinstance(x, str) for x in v),
        "a list of component names",
    ),
    "dev_corpus": (lambda v: isinstance(v, str), "a dotted corpus name"),
    "train_corpus": (lambda v: isinstance(v, str), "a dotted corpus name"),
    "score_weights": (lambda v: isinstance(v, dict), "a mapping of score -> weight"),
    "zero1": (lambda v: isinstance(v, bool), "a bool"),
    "update_sharding": (
        lambda v: v in ("auto", "replicated", "zero1", "full"),
        'one of "auto", "replicated", "zero1", "full"',
    ),
    "mesh": (lambda v: isinstance(v, dict), "a mapping of mesh axis sizes"),
    "prefetch_batches": (
        lambda v: isinstance(v, int) and not isinstance(v, bool) and v >= 0,
        "an int >= 0",
    ),
    "collate_workers": (
        lambda v: isinstance(v, int) and not isinstance(v, bool) and v >= 0,
        "an int >= 0",
    ),
    "collate_cache_mb": (
        lambda v: isinstance(v, int) and not isinstance(v, bool) and v >= 0,
        "an int >= 0",
    ),
    "keep_checkpoints": (
        lambda v: isinstance(v, int) and not isinstance(v, bool) and v >= 1,
        "an int >= 1",
    ),
    "watchdog_timeout_s": (
        lambda v: isinstance(v, (int, float)) and not isinstance(v, bool) and v >= 0,
        "a number of seconds >= 0 (0 disables the watchdog)",
    ),
    "io_retries": (
        lambda v: isinstance(v, int) and not isinstance(v, bool) and v >= 0,
        "an int >= 0",
    ),
    "io_retry_base_s": (
        lambda v: isinstance(v, (int, float)) and not isinstance(v, bool) and v > 0,
        "a number of seconds > 0",
    ),
    "profile_window": (
        lambda v: _is_step_window(v),
        "a [start, stop] pair of ints with 0 <= start <= stop",
    ),
    "metrics_dir": (
        lambda v: isinstance(v, str),
        "a directory path string (empty string disables telemetry)",
    ),
    "trace_steps": (
        lambda v: _is_step_window(v),
        "a [start, stop] pair of ints with 0 <= start <= stop",
    ),
    "anomaly_detection": (lambda v: isinstance(v, bool), "a bool"),
    "alerting": (lambda v: isinstance(v, bool), "a bool"),
    "incident_dir": (
        lambda v: isinstance(v, str),
        "a directory path string (empty string disables the flight "
        "recorder)",
    ),
    "metrics_port": (
        lambda v: isinstance(v, int) and not isinstance(v, bool)
        and 0 <= v <= 65535,
        "a TCP port int in [0, 65535] (0 disables the endpoint)",
    ),
    "metrics_host": (
        lambda v: isinstance(v, str) and bool(v),
        "a non-empty bind address string",
    ),
    "fused_update": (
        lambda v: v in ("auto", "on", "off"),
        'one of "auto", "on", "off"',
    ),
    "bf16_shadow": (
        lambda v: v in ("auto", "on", "off"),
        'one of "auto", "on", "off"',
    ),
    "steps_per_dispatch": (
        lambda v: isinstance(v, int) and not isinstance(v, bool) and v >= 1,
        "an int >= 1",
    ),
    "fleet_peer_timeout_s": (
        lambda v: isinstance(v, (int, float)) and not isinstance(v, bool)
        and v > 0,
        "a number of seconds > 0",
    ),
    "fleet_probe_timeout_s": (
        lambda v: isinstance(v, (int, float)) and not isinstance(v, bool)
        and v > 0,
        "a number of seconds > 0",
    ),
}


def _is_step_window(v: Any) -> bool:
    return (
        isinstance(v, (list, tuple))
        and len(v) == 2
        and all(isinstance(x, int) and not isinstance(x, bool) for x in v)
        and 0 <= v[0] <= v[1]
    )


def _ms(seconds: Optional[float]) -> Optional[float]:
    """Seconds -> rounded milliseconds (None passes through)."""
    return round(seconds * 1000.0, 3) if seconds is not None else None


def _group_shape_sig(group: Dict[str, Any]) -> Tuple:
    """Shape/dtype signature of one staged batch group — steps_per_dispatch
    stacks only groups in the SAME padding bucket (a lax.scan needs
    homogeneous xs); a bucket change flushes the run and the odd group
    leads the next dispatch."""
    return tuple(
        (x.shape, str(x.dtype))
        for x in jax.tree_util.tree_leaves((group["tokens"], group["targets"]))
    )


@partial(jax.jit, donate_argnums=(0,))
def _avg_step(avg, params, t):
    """One running-mean step for use_averages. The ``avg`` accumulator is
    DONATED: before this fix every eval-window step allocated a fresh
    full-size param tree here — a second silent O(n_params) traversal's
    worth of memory churn per step (donation-audit test pins this)."""
    t = jnp.float32(t)
    return jax.tree_util.tree_map(lambda a, p: a + (p - a) / t, avg, params)


def _unknown_name_error(what: str, name: str, allowed) -> ValueError:
    """Uniform unknown-name error with a did-you-mean hint."""
    import difflib

    allowed = sorted(allowed)
    close = difflib.get_close_matches(name, allowed, n=1)
    hint = f" — did you mean {close[0]!r}?" if close else ""
    return ValueError(
        f"{what} {name!r}{hint} (known: {', '.join(allowed)})"
    )


def validate_training(raw: Dict[str, Any]) -> None:
    """Reject unknown / mistyped [training] keys loudly, with a
    did-you-mean hint — a typo'd ``patiance`` silently training with the
    default patience is a silent-wrong-training bug (the reference
    validates via pydantic at worker.py:93; VERDICT r2 weak #4)."""
    allowed = set(DEFAULT_TRAINING) | _TRAINING_BLOCK_KEYS
    for key, value in raw.items():
        if key not in allowed:
            raise _unknown_name_error("[training] has unknown key", key, allowed)
        if key in _TRAINING_BLOCK_KEYS:
            if not isinstance(value, dict):
                raise ValueError(
                    f"[training.{key}] must be a registry block "
                    f"(a [training.{key}] section), got {type(value).__name__}"
                )
            continue
        pred, desc = _TRAINING_TYPES[key]
        if not pred(value):
            raise ValueError(
                f"[training] {key} must be {desc}, got {value!r} "
                f"({type(value).__name__})"
            )


def resolve_training(config: Config) -> Dict[str, Any]:
    raw = config.get("training", {})
    validate_training(raw)
    t = dict(DEFAULT_TRAINING)
    t.update(raw)
    return t


def resolve_dot_name(config: Config, resolved_corpora: Dict[str, Any], dot_name: str):
    """'corpora.train' -> resolved reader (reference worker.py:94-95
    ``resolve_dot_names``)."""
    parts = dot_name.split(".")
    if parts[0] != "corpora" or len(parts) != 2:
        raise ValueError(f"Unsupported dot name {dot_name!r}")
    if parts[1] not in resolved_corpora:
        raise ValueError(f"No [corpora.{parts[1]}] block in config")
    return resolved_corpora[parts[1]]


class TrainResult:
    def __init__(self):
        self.best_score: float = -1.0
        self.best_step: int = -1
        self.final_step: int = 0
        self.epoch: int = 0
        self.history: List[Dict[str, Any]] = []
        self.words_seen: int = 0
        self.seconds: float = 0.0
        # True when the run stopped on a shutdown signal (preemption):
        # a step-boundary checkpoint was written and the CLI exits with
        # resilience.RC_PREEMPTED so supervisors can tell "resume me"
        # from "done"
        self.interrupted: bool = False

    @property
    def wps(self) -> float:
        return self.words_seen / self.seconds if self.seconds > 0 else 0.0


def default_pipeline_score_weights(nlp: Pipeline) -> Dict[str, float]:
    """Combine the pipeline components' declared ``default_score_weights``
    and normalize the positive weights to sum 1 — spaCy's
    ``util.combine_score_weights`` semantics for the default [training]
    score_weights (each factory declares its metadata; the reference
    inherits this through spaCy's init_nlp, reference worker.py:91)."""
    combined: Dict[str, float] = {}
    for name in nlp.pipe_names:
        comp_weights = getattr(nlp.components[name], "default_score_weights", None)
        for key, value in (comp_weights or {}).items():
            combined[key] = float(value)  # later components override
    total = sum(v for v in combined.values() if v > 0)
    if total > 0:
        combined = {k: (v / total if v > 0 else 0.0) for k, v in combined.items()}
    return combined


def weighted_score(scores: Dict[str, float], weights: Dict[str, float]) -> float:
    """spaCy final-score semantics: None scores (no gold annotation for
    that metric) are EXCLUDED rather than counted as 0."""
    if not weights:
        # last-resort fallback (pipeline declared NO score metadata at
        # all): mean of all numeric scores (None / nested excluded)
        vals = [
            v
            for v in scores.values()
            if isinstance(v, (int, float)) and not isinstance(v, bool)
        ]
        return float(np.mean(vals)) if vals else 0.0
    total = 0.0
    for key, weight in weights.items():
        if weight in (None, 0.0):
            continue
        value = scores.get(key)
        if value is None:
            continue
        total += float(value) * float(weight)
    return total


def train(
    config: Config,
    output_path: Optional[Path] = None,
    *,
    n_workers: Optional[int] = None,
    resume: bool = False,
    max_steps_override: Optional[int] = None,
    stdout_log: bool = True,
    profile_dir: Optional[Path] = None,
    metrics_dir: Optional[Path] = None,
    metrics_port: Optional[int] = None,
    fleet: Optional[Dict[str, Any]] = None,
) -> Tuple[Pipeline, TrainResult]:
    """Run config-driven training. Returns (pipeline, result).

    ``n_workers`` maps to the mesh's data-axis size (the reference's
    ``--n-workers`` actor count, train_cli.py:27); default = all devices.

    ``profile_dir``: capture a jax.profiler trace of the
    ``[training] profile_window`` steps (default 5-15; first-class
    tracing — the reference's Timer scaffolding is unwired, SURVEY.md §5.1).

    ``metrics_dir``: override for ``[training] metrics_dir`` — enables the
    telemetry subsystem (metrics.jsonl + Chrome trace + anomaly
    detectors, training/telemetry.py).

    ``fleet``: asynchronous trainer-fleet worker mode (training/fleet/ —
    the paper's cross-process parameter-ownership scheme). A dict of
    :func:`~.fleet.worker.train_fleet_worker` keywords (at least
    ``worker_id`` and ``n_workers``); this process becomes ONE fleet
    worker exchanging gradients/params with its peers over HTTP instead
    of running the in-mesh synchronous loop. Mutually exclusive with
    multi-host jax and ``profile_dir``.
    """
    if fleet:
        if profile_dir is not None:
            raise ValueError(
                "fleet mode does not support --profile (profile one "
                "worker via its own telemetry trace instead)"
            )
        from .fleet.worker import train_fleet_worker

        return train_fleet_worker(
            config,
            output_path,
            resume=resume,
            stdout_log=stdout_log,
            metrics_dir=metrics_dir,
            metrics_port=metrics_port,
            max_steps_override=max_steps_override,
            **fleet,
        )
    config = config.interpolate()
    T = resolve_training(config)
    seed = int(T.get("seed") or 0)
    random.seed(seed)
    np.random.seed(seed)

    # ---- resilience setup ----
    # fault plan from the environment (a supervisor-relaunched child reads
    # its own copy), transient-I/O retry policy from the config, and the
    # SIGTERM/SIGINT flag the loop polls at step boundaries
    resilience.activate_env_fault_plan()
    # a previous run in this process may have queued events no logger
    # drained (console logger path) — they must not leak into THIS run's
    # first jsonl row
    resilience.drain_events()
    resilience.set_default_retry_policy(
        resilience.RetryPolicy(
            max_retries=int(T.get("io_retries", 3) or 0),
            base_delay=float(T.get("io_retry_base_s", 0.5) or 0.5),
        )
    )
    # created now, installed right before the main loop (whose finally is
    # the only place that restores handlers — a setup-phase failure must
    # not leak a handler pointing at an abandoned run)
    shutdown = ShutdownCoordinator()

    # ---- telemetry (training/telemetry.py) ----
    # Process 0 owns the files (every rank's loop is replica-identical, so
    # rank 0's timeline IS the pod's); disabled = `tel is None` and the
    # hot loop makes ZERO telemetry calls — every use below is guarded.
    from contextlib import nullcontext

    tel = None
    tel_http = None
    tel_dir = str(metrics_dir) if metrics_dir is not None else str(
        T.get("metrics_dir") or ""
    )
    if not tel_dir and (
        metrics_port or T.get("metrics_port")
    ) and jax.process_index() == 0:
        # the endpoint serves the telemetry objects — with telemetry off
        # there is nothing to serve, and silently dropping an explicit
        # --metrics-port would leave the operator's scraper getting
        # connection-refused with no hint why
        log_event(
            "telemetry-endpoint-skipped",
            "--metrics-port/[training] metrics_port is set but telemetry "
            "is disabled (no metrics_dir) — no endpoint started; set "
            "--metrics-dir/[training] metrics_dir to enable it",
        )
    if tel_dir and jax.process_index() == 0:
        from .telemetry import Telemetry, program_flops

        trace_steps = T.get("trace_steps") or [0, 50]
        tel = Telemetry(
            Path(tel_dir),
            trace_steps=(int(trace_steps[0]), int(trace_steps[1])),
            anomaly_detection=bool(T.get("anomaly_detection", True)),
            process_index=jax.process_index(),
            alerting=bool(T.get("alerting", True)),
            incident_dir=(
                Path(str(T.get("incident_dir")))
                if T.get("incident_dir") else None
            ),
        )
        # trainer-side scrape endpoint ([training] metrics_port /
        # train --metrics-port): /metrics (+?format=prometheus),
        # /healthz clock anchor, /trace — the trainer's leg of the
        # cross-process observability plane
        tel_port = int(
            metrics_port if metrics_port is not None
            else T.get("metrics_port") or 0
        )
        if tel_port > 0:
            import logging as _logging

            from .telemetry_http import TelemetryHTTPServer

            tel_http = TelemetryHTTPServer(
                tel,
                host=str(T.get("metrics_host") or "127.0.0.1"),
                port=tel_port,
            )
            host, bound = tel_http.start()
            log_event(
                "telemetry-endpoint",
                f"trainer telemetry on http://{host}:{bound} "
                "(/metrics, /healthz, /trace)",
                level=_logging.INFO,
                port=bound,
            )

    def _tspan(name: str, **args: Any):
        """Span context when telemetry is on, else a free nullcontext."""
        if tel is None:
            return nullcontext()
        return tel.trace.span(name, cat="loop", **args)

    # ---- corpora ----
    corpora_cfg = config.get("corpora", {})
    resolved_corpora = {name: registry.resolve(block) for name, block in corpora_cfg.items()}
    train_corpus = resolve_dot_name(config, resolved_corpora, T["train_corpus"])
    dev_corpus = resolve_dot_name(config, resolved_corpora, T["dev_corpus"])

    # ---- pipeline ----
    nlp = Pipeline.from_config(config)
    nlp.initialize(train_corpus, seed=seed)

    # Multi-host startup assertion: every host must have built the IDENTICAL
    # param tree (same paths, same label sets) — the SPMD-era replacement for
    # the reference's unchecked reliance on identical model construction
    # order (SURVEY.md §2.4 "Key identity is fragile", §5.2 race detection).
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        from ..models.core import param_paths
        from ..ops.hashing import hash_string_u64

        signature = "|".join(param_paths(nlp.params)) + "||" + "|".join(
            f"{n}:{','.join(nlp.components[n].labels)}" for n in nlp.pipe_names
        )
        digest = np.array([hash_string_u64(signature) % (2 ** 31)], np.int32)
        digests = multihost_utils.process_allgather(digest)
        if int(np.min(digests)) != int(np.max(digests)):
            raise RuntimeError(
                "Parameter-tree/label mismatch across hosts: all processes "
                "must resolve the same config over the same training data "
                f"(digests: {digests.tolist()})"
            )

    # ---- mesh / optimizer / step ----
    mesh_cfg = dict(T.get("mesh") or {})
    mesh = build_mesh(
        n_data=n_workers if n_workers is not None else mesh_cfg.get("n_data"),
        n_model=int(mesh_cfg.get("n_model", 1)),
        n_context=int(mesh_cfg.get("n_context", 1)),
        n_pipe=int(mesh_cfg.get("n_pipe", 1)),
    )
    n_data = mesh.shape["data"]
    # [training] update_sharding, resolved against THIS run's mesh/backend
    # (the zero1 bool stays as an accepted alias — parallel/step.py)
    zero1 = bool(T.get("zero1"))
    update_sharding = resolve_update_sharding(
        str(T.get("update_sharding", "auto")), zero1=zero1, n_data=int(n_data)
    )
    if update_sharding != "replicated":
        import logging as _logging

        log_event(
            "update-sharding",
            f"update phase: {update_sharding_status(update_sharding, mesh)}",
            level=_logging.INFO,
            mode=update_sharding,
            n_data=int(n_data),
        )
    tx = registry.resolve(T.get("optimizer") or {"@optimizers": "Adam.v1"})
    tx = _optimizers.mask_frozen(tx, nlp.params)  # skip frozen_ leaves entirely
    # [training] fused_update: rebuild a fusable chain as one traversal
    # (ops/fused_update.py). State structure is identical, so resume works
    # across knob flips; "auto" silently keeps the reference chain for
    # unfusable optimizers (masked/frozen, custom registrations) AND on
    # CPU, where the round-7 A/B measured the mega-fusion at parity-to-
    # slightly-slower vs XLA's own chain fusion (PERF.md "Fixed-cost
    # floor"; the same platform-gating precedent as compute_dtype="auto").
    fused_mode = str(T.get("fused_update", "auto"))
    if fused_mode == "on" or (
        fused_mode == "auto"
        and (
            jax.default_backend() != "cpu"
            # full update-sharding prefers the fused transformation even on
            # CPU: its partitioner-proof global norm (stable_global_norm)
            # is what guarantees full == replicated to EQUALITY; the optax
            # chain's in-chain clip norm is at the partitioner's mercy
            or (update_sharding == "full" and int(n_data) > 1)
        )
    ):
        fused_tx = _optimizers.fuse_optimizer(tx)
        if fused_tx is not None:
            tx = fused_tx
        elif fused_mode == "on":
            raise ValueError(
                '[training] fused_update = "on" needs a fusable optimizer '
                "(Adam.v1 / RAdam.v1 with no frozen_ param leaves); use "
                '"auto" to fall back to the reference chain silently'
            )
    batcher = registry.resolve(
        T.get("batcher")
        or {"@batchers": "spacy.batch_by_words.v1", "size": 1000, "tolerance": 0.2}
    )
    accum = max(int(T.get("accumulate_gradient") or 1), 1)

    params = place_replicated(nlp.params, mesh)
    opt_state = tx.init(params)
    opt_state = shard_opt_state(opt_state, mesh, update_sharding)

    rng = jax.random.PRNGKey(seed)
    step = 0
    epoch = 0
    best_score = -1.0
    best_step = -1

    # ---- resume ----
    resume_skip = 0  # batches already consumed in the checkpointed epoch
    if resume and output_path is not None:
        try:
            with _tspan("checkpoint_load"):
                ckpt = TrainCheckpoint.load(Path(output_path) / "last-model")
        except CheckpointCorrupt as e:
            # every retained generation is torn: warn and train from
            # scratch rather than crash — the data survives, the run
            # restarts (and log_event lands the anomaly in jsonl logs)
            log_event(
                "resume-failed",
                f"--resume found no intact checkpoint generation ({e}); "
                "starting from scratch",
            )
            ckpt = None
        if jax.process_count() > 1:
            # generation fallback is a PER-RANK decision over possibly-flaky
            # shared storage: if one rank fell back to an older generation
            # (or to scratch) while the others resumed the newest, the ranks
            # hold different step counters and every later collective
            # desyncs — fail loudly at startup instead of wedging the pod
            from jax.experimental import multihost_utils

            steps = multihost_utils.process_allgather(
                np.array([ckpt["step"] if ckpt is not None else -1], np.int64)
            )
            if int(np.min(steps)) != int(np.max(steps)):
                raise RuntimeError(
                    "--resume loaded different checkpoint generations across "
                    f"hosts (per-rank steps: {steps.ravel().tolist()}); fix or "
                    "remove the torn generation so every rank resumes the "
                    "same state"
                )
        if ckpt is not None:
            # elastic resume: the checkpoint's canonical unsharded state is
            # re-sharded under THIS run's mesh — the save-time mesh shape
            # (recorded in extra) does not constrain the resume shape
            saved_mesh = (ckpt.get("extra") or {}).get("mesh") or {}
            saved_n_data = saved_mesh.get("n_data")
            if saved_n_data is not None and int(saved_n_data) != int(n_data):
                log_event(
                    "elastic-resume",
                    f"checkpoint was written on a {saved_n_data}-replica "
                    f"data axis; re-sharding to this run's {int(n_data)} "
                    f"(update_sharding={update_sharding})",
                    saved_n_data=int(saved_n_data),
                    n_data=int(n_data),
                )
            params = place_replicated(ckpt["params"], mesh)
            opt_state = shard_opt_state(ckpt["opt_state"], mesh, update_sharding)
            step = ckpt["step"]
            epoch = ckpt["epoch"]
            rng = ckpt["rng"]
            best_score = ckpt["best_score"]
            best_step = ckpt["best_step"]
            # exact data-position resume: reproduce the checkpointed epoch's
            # shuffle order (restore the corpus's own epoch counter — it may
            # be offset from the loop's epoch by initialize() passes), then
            # fast-forward past the batches already consumed. On multi-host,
            # the checkpoint carries EVERY rank's (epoch, batches_in_epoch,
            # corpus_epoch) — per-host epoch boundaries drift when shards
            # are unequal, so each rank fast-forwards to its OWN position
            # (VERDICT r3 next #4; rank-0 scalars kept for old checkpoints).
            resume_skip = int(ckpt["extra"].get("batches_in_epoch", 0))
            corpus_epoch = ckpt["extra"].get("corpus_epoch")
            per_rank = ckpt["extra"].get("per_rank_positions")
            if per_rank is not None:
                if len(per_rank) == jax.process_count():
                    my_epoch, my_skip, my_corpus_epoch = per_rank[jax.process_index()]
                    epoch = int(my_epoch)
                    resume_skip = int(my_skip)
                    corpus_epoch = int(my_corpus_epoch)
                else:
                    log_event(
                        "resume-rank-mismatch",
                        f"checkpoint was written by {len(per_rank)} "
                        f"processes but this run has {jax.process_count()}; "
                        "data position restored from rank 0's scalars "
                        "(approximate — the stream sharding changed)",
                        checkpoint_processes=len(per_rank),
                        run_processes=jax.process_count(),
                    )
            if corpus_epoch is not None and hasattr(train_corpus, "_epoch"):
                train_corpus._epoch = int(corpus_epoch)
            import logging as _logging

            log_event(
                "resume",
                f"resumed from checkpoint step {step} (epoch {epoch}, "
                f"best {best_score:.4f} @ step {best_step})",
                level=_logging.INFO,
                step=step,
                epoch=epoch,
            )
        else:
            log_event(
                "resume-empty",
                f"--resume requested but {Path(output_path) / 'last-model'} "
                "holds no checkpoint; starting from scratch",
            )

    # [training] annotating_components: validated against the pipeline, then
    # each batch is annotated with the CURRENT model's predictions before
    # collation so downstream components train on upstream predictions
    # (reference worker.py:187 threads the list into train_while_improving)
    annotating = list(T.get("annotating_components") or [])
    for comp_name in annotating:
        if comp_name not in nlp.pipe_names:
            raise _unknown_name_error(
                "[training] annotating_components names", comp_name, nlp.pipe_names
            )
    for comp_name in T.get("frozen_components") or []:
        if comp_name not in nlp.pipe_names:
            raise _unknown_name_error(
                "[training] frozen_components names", comp_name, nlp.pipe_names
            )
    # Multi-host annotation runs HOST-LOCALLY (see device_groups): each host
    # device_gets the replicated trunk + annotating-head params once per
    # update group and predicts on its local devices with no mesh, so
    # per-host batch divergence can't launch mismatched global programs.
    # (The reference supports annotating_components at N worker processes
    # trivially — each Ray worker threads the list into its own loop,
    # reference worker.py:187; VERDICT r3 next #2.)
    # A component that trains on predicted upstream annotations
    # (use_gold_ents = false) learns NOTHING unless some annotating
    # component actually writes those annotations — catch the silent
    # zero-mention configuration here rather than training a no-op.
    for comp_name in nlp.pipe_names:
        comp = nlp.components[comp_name]
        if getattr(comp, "use_gold_ents", True):
            continue
        writers = [n for n in annotating if nlp.components[n].sets_ents]
        if not writers:
            raise ValueError(
                f"[components.{comp_name}] sets use_gold_ents = false, so its "
                "training mentions come from predicted doc.ents — but no "
                "[training] annotating_components entry writes entities. Add "
                "an entity-setting component (ner / entity_ruler) to "
                "annotating_components, or set use_gold_ents = true"
            )

    # [training.before_update] callback slot (spaCy semantics: called with
    # (nlp, {"step": ..., "epoch": ...}) before every optimizer update —
    # reference worker.py:188 passes it into train_while_improving)
    before_update: Optional[Callable] = None
    if T.get("before_update"):
        before_update = registry.resolve(T["before_update"])
        if not callable(before_update):
            raise ValueError(
                "[training.before_update] must resolve to a callable — add "
                "an @callbacks line to the block (got "
                f"{type(before_update).__name__})"
            )

    # Parameter averaging (thinc Adam use_averages semantics): running mean
    # of params, used for eval + best-model checkpoints.
    use_averages = bool(getattr(tx, "use_averages", False))
    # copy: params buffers are donated to the jitted update, so an alias
    # would dereference deleted buffers at the first _avg_step on TPU
    avg_params = (
        jax.tree_util.tree_map(jnp.copy, params) if use_averages else None
    )
    avg_count = 0

    # [training] bf16_shadow: persistent bf16 copies of the trunk's matmul
    # weights, built AFTER resume (from the final params) and maintained
    # incrementally inside the jitted update. "auto" resolves through the
    # trunk's compute dtype so CPU runs (f32 compute) change nothing.
    shadow_mode = str(T.get("bf16_shadow", "auto"))
    shadow = None
    if shadow_mode in ("auto", "on"):
        from ..models.transformer import build_param_shadow, pipeline_shadow_dtype

        shadow_dtype = pipeline_shadow_dtype(nlp)
        if shadow_dtype is not None:
            shadow = build_param_shadow(params, shadow_dtype)
        if shadow is None and shadow_mode == "on":
            raise ValueError(
                '[training] bf16_shadow = "on" needs a transformer trunk '
                "whose compute dtype resolves to bfloat16 (compute_dtype = "
                '"bfloat16", or "auto" on an accelerator); use "auto" to '
                "disable the shadow silently where it cannot help"
            )

    # [training] steps_per_dispatch: K compiled steps per host round-trip.
    # Modes that need the host between consecutive steps bypass to 1.
    steps_per_dispatch = max(int(T.get("steps_per_dispatch", 1) or 1), 1)
    if steps_per_dispatch > 1 and (
        annotating or before_update is not None or use_averages
    ):
        log_event(
            "steps-per-dispatch-bypass",
            "steps_per_dispatch > 1 needs the host between steps for "
            "annotating_components / before_update / use_averages; "
            "running with K=1",
        )
        steps_per_dispatch = 1

    loss_fn = nlp.make_loss_fn(dropout=float(T["dropout"]))
    update = make_train_step(
        loss_fn, tx, mesh, accumulate_gradient=accum,
        update_sharding=update_sharding,
        opt_state_template=opt_state, shadow=shadow is not None,
    )
    update_multi = (
        make_train_step(
            loss_fn, tx, mesh, accumulate_gradient=accum,
            update_sharding=update_sharding,
            opt_state_template=opt_state, shadow=shadow is not None,
            multi_dispatch=True,
        )
        if steps_per_dispatch > 1
        else None
    )

    # ---- logger ----
    logger_cfg = T.get("logger") or {"@loggers": "spacy_ray_tpu.ConsoleLogger.v1"}
    logger_setup = registry.resolve(logger_cfg)
    import io as _io
    import sys as _sys

    log_stdout = _sys.stdout if stdout_log else _io.StringIO()
    log_step, log_finalize = logger_setup(nlp, log_stdout, _sys.stderr)

    # ---- dev set (materialized once) ----
    dev_examples = list(dev_corpus())

    # empty [training.score_weights] falls back to the components' declared
    # defaults (normalized), NOT a blind mean over every numeric score —
    # mixing accuracies with AUCs silently was VERDICT r3 weak #6
    score_weights = dict(T.get("score_weights") or {})
    if not score_weights:
        score_weights = default_pipeline_score_weights(nlp)

    max_steps = int(max_steps_override or T["max_steps"] or 0)
    max_epochs = int(T["max_epochs"] or 0)
    eval_frequency = int(T["eval_frequency"] or 200)
    patience = int(T["patience"] or 0)

    result = TrainResult()
    process_rank = jax.process_index()
    process_count = jax.process_count()

    batches_in_epoch = 0  # data position within the current epoch
    stream_corpus_epoch = 0  # corpus._epoch as of the current stream

    def batches_forever() -> Iterator[Tuple[int, List[Example]]]:
        nonlocal epoch, batches_in_epoch, stream_corpus_epoch
        skip = resume_skip
        while True:
            stream_corpus_epoch = getattr(train_corpus, "_epoch", 0)
            stream = train_corpus()
            if process_count > 1:
                stream = shard_stream(stream, process_rank, process_count)
            got_any = False
            for b in batcher(stream):
                got_any = True
                # batches_in_epoch is the position from the EPOCH START, so
                # fast-forwarded batches count too — otherwise a checkpoint
                # written after a resume would record a position relative to
                # the resume point and a second resume would be inexact
                batches_in_epoch += 1
                if skip > 0:  # resume fast-forward within the first epoch
                    skip -= 1
                    continue
                yield epoch, b
            if not got_any:
                raise ValueError("Training corpus is empty")
            skip = 0
            epoch += 1
            batches_in_epoch = 0
            if max_epochs and epoch >= max_epochs:
                return

    start_time = time.perf_counter()
    loss_accum: Dict[str, float] = {}
    pending_metrics: List[Tuple[Dict[str, Any], bool]] = []
    words_since_log = 0
    last_log_time = start_time
    stop = False
    steps_run = 0  # steps executed THIS run (profiling window is resume-safe)
    profile_active = False
    # configurable jax.profiler window (was hardcoded 5-15): counted in
    # steps_run, not global step, so a resumed run still profiles its own
    # warm steps rather than an arbitrary slice of the step counter
    profile_window = T.get("profile_window") or [5, 15]
    profile_start, profile_stop = int(profile_window[0]), int(profile_window[1])

    def drain_metrics() -> None:
        """Materialize queued device metrics into loss_accum (sync point).

        A step poisoned by a ``nan`` fault rule gets its loss overwritten
        HERE, on the host — poisoning on device would dispatch fresh XLA
        ops whose compile the recompile-storm detector would (correctly,
        but spuriously for the drill) flag."""
        for m, poisoned in pending_metrics:
            host = jax.device_get(m)
            for key, value in host.items():
                if key.startswith("loss_"):
                    v = float("nan") if poisoned else float(value)
                    loss_accum[key[5:]] = loss_accum.get(key[5:], 0.0) + v
        pending_metrics.clear()

    # ---- staged input pipeline (read -> collate -> transfer) ----
    # Stage split exists so collation can fan out over worker threads while
    # the read stage (corpus/batcher state) and the transfer stage
    # (device_put + all multi-host collectives) each stay on ONE thread —
    # the ordering constraint documented in prefetch.py / collate_pool.py.
    from .collate_pool import (
        CollateCache,
        PipelineStats,
        cached_collate,
        ordered_map,
    )

    pipe_stats = PipelineStats()
    if tel is not None:
        # stage timings double as Chrome-trace spans — emitted identically
        # whether collation runs inline or on pool workers (each worker
        # thread gets its own trace track)
        pipe_stats.attach_trace(tel.trace)
    collate_workers = int(T.get("collate_workers", 0) or 0)
    collate_cache_mb = int(T.get("collate_cache_mb", 0) or 0)
    # the pool runs only where the prefetch thread may: single-process,
    # non-annotating (annotation must predict with the step's own params)
    use_pool = collate_workers >= 2 and process_count == 1 and not annotating
    pipe_stats.workers = collate_workers if use_pool else 1
    # identity-keyed cache: only meaningful when epochs re-yield the SAME
    # Example objects in the SAME batches. Auto-bypass when the corpus says
    # batches can't recur (augmentation = fresh copies per epoch; shuffle =
    # different batch membership per epoch; Corpus.stable_identity) and in
    # annotating mode (targets depend on per-step predictions). Readers
    # that don't declare either flag get the cache as configured — the
    # byte-capped LRU bounds the damage if their batches never recur.
    corpus_augmented = bool(getattr(train_corpus, "augmented", False))
    cache_stable = bool(
        getattr(train_corpus, "stable_identity", not corpus_augmented)
    )
    collate_cache: Optional[CollateCache] = None
    if collate_cache_mb > 0 and not annotating and cache_stable:
        collate_cache = CollateCache(collate_cache_mb * 1024 * 1024)
        pipe_stats.cache_enabled = True

    def gather_groups() -> Iterator[Dict[str, Any]]:
        """Read stage: one update's worth of RAW batches + position tags.

        Each record carries its own data-position tags (batches_in_epoch /
        corpus_epoch snapshots) so the consumer checkpoints the position of
        the group it actually trained on — exact resume stays exact even
        when this generator runs ahead on the prefetch thread or the
        collation pool. Multi-host shape/termination allgathers live here,
        on the one thread that iterates this generator (the pool never
        wraps the multi-host path).
        """
        batch_iter = batches_forever()
        while True:
            # gather `accum` raw batches (stacked microbatches per update)
            t_read = time.perf_counter()
            raw_batches: List[List[Example]] = []
            cur_epoch = epoch
            try:
                for _ in range(accum):
                    cur_epoch, b = next(batch_iter)
                    raw_batches.append(b)
                have_group = True
            except StopIteration:
                # end of data: an incomplete accumulation group would under-
                # scale the mean gradient (scan still divides by `accum`)
                have_group = False
            pipe_stats.add("read", time.perf_counter() - t_read, t0=t_read)
            if process_count > 1:
                # loop termination must be COLLECTIVE: if any host ran out
                # of data, all hosts stop this step, else the continuing
                # hosts enter the update collectives alone and deadlock
                from jax.experimental import multihost_utils

                flags = multihost_utils.process_allgather(
                    np.array([1 if have_group else 0], np.int32)
                )
                if int(np.min(flags)) == 0:
                    return
            elif not have_group:
                return
            if annotating:
                # annotate each batch with the CURRENT model before target
                # construction, so downstream components (e.g. an
                # entity_linker with use_gold_ents = false) train on
                # upstream predictions — spaCy's annotating_components
                # semantics (reference worker.py:187). Runs inline (this
                # mode disables the prefetch thread): the predictions come
                # from the same pre-update params spaCy would use.
                current = params_cell["params"]
                if process_count > 1:
                    # host-local annotation: restrict to the trunk + the
                    # annotating heads (the only subtrees the annotation
                    # forward reads) and predict with no mesh — a purely
                    # local program per host. Replicated leaves stay ON
                    # DEVICE: the local shard of a fully-replicated array
                    # IS the full value, so handing it to the host-local
                    # jit program costs zero transfers (round-4 advisor:
                    # the previous device_get here was a full trunk
                    # host round-trip per accumulation group — material
                    # for a flagship-size trf trunk on a real pod).
                    needed = set(annotating)
                    if nlp.tok2vec_name is not None:
                        needed.add(nlp.tok2vec_name)

                    def _local_view(a):
                        if (
                            isinstance(a, jax.Array)
                            and a.sharding.is_fully_replicated
                        ):
                            return a.addressable_data(0)
                        return jax.device_get(a)  # sharded: host assemble

                    current = {
                        name: jax.tree_util.tree_map(_local_view, current[name])
                        for name in needed
                        if name in current
                    }
                    ann_mesh = None
                else:
                    ann_mesh = mesh
                for b in raw_batches:
                    shells = [eg.reference.copy_shell() for eg in b]
                    nlp.predict_docs(
                        shells, params=current, mesh=ann_mesh, annotate=annotating
                    )
                    for eg, shell in zip(b, shells):
                        eg.predicted = shell
            # bucketed padded shapes, computed in the read stage: the
            # multi-host shape sync below is a collective and must stay on
            # this (single) thread, never inside a pool worker
            max_len = max(max(len(eg) for eg in b) for b in raw_batches)
            max_b = max(len(b) for b in raw_batches)
            T_pad = bucket_length(max_len, nlp.length_buckets)
            # B must divide evenly over the mesh data axis for P("data")
            B_pad = max(bucket_batch_size(max_b), n_data)
            B_pad = ((B_pad + n_data - 1) // n_data) * n_data
            n_words: Optional[int] = None  # single-process: counted at collate
            if process_count > 1:
                # multi-controller SPMD: every host must launch the same
                # program — sync padded shapes to the all-host max. The same
                # allgather carries each host's word count: the global batch
                # is the concatenation of all hosts' rows (place_batch), so
                # the words consumed this step are the sum over hosts, not
                # local × P.
                from jax.experimental import multihost_utils

                local_words = sum(len(eg) for b in raw_batches for eg in b)
                dims = multihost_utils.process_allgather(
                    np.array([T_pad, B_pad, local_words], np.int32)
                ).reshape(-1, 3)
                T_pad = int(dims[:, 0].max())
                B_pad = int(dims[:, 1].max())
                n_words = int(dims[:, 2].sum())
            yield {
                "raw_batches": raw_batches,
                "B_pad": B_pad,
                "T_pad": T_pad,
                "n_words": n_words,
                "cur_epoch": cur_epoch,
                "batches_in_epoch": batches_in_epoch,
                "corpus_epoch": stream_corpus_epoch,
            }

    def collate_group(item: Dict[str, Any]) -> Dict[str, Any]:
        """Tokenize+hash+collate stage: raw batches -> stacked HOST arrays.

        Pure host work (no device_put, no collectives) so the pool may run
        it on any worker thread. Collated host batches are cached per
        (batch identity, bucket shape) when the cache is enabled — a
        steady-state epoch then reduces to cache lookups + device_put.
        """
        t_collate = time.perf_counter()
        raw_batches = item["raw_batches"]
        B_pad, T_pad = item["B_pad"], item["T_pad"]
        collated = [
            cached_collate(
                collate_cache,
                b,
                B_pad,
                T_pad,
                lambda b_, B_, T_: nlp.collate(
                    b_, pad_batch_to=B_, pad_len_to=T_, host=True
                ),
                pipe_stats,
            )
            for b in raw_batches
        ]
        n_words = item["n_words"]
        if n_words is None:  # single-process: no dims allgather happened
            n_words = sum(c["n_words"] for c in collated)
        if accum == 1:
            tokens, targets = collated[0]["tokens"], collated[0]["targets"]
        else:
            # host-side stack: one contiguous array per leaf so the transfer
            # stage pays a single device_put (multi-host place_batch
            # re-assembles on the host anyway)
            tokens = jax.tree_util.tree_map(
                lambda *xs: np.stack(xs), *[c["tokens"] for c in collated]
            )
            targets = jax.tree_util.tree_map(
                lambda *xs: np.stack(xs), *[c["targets"] for c in collated]
            )
        pipe_stats.add(
            "collate", time.perf_counter() - t_collate, t0=t_collate
        )
        return {
            "tokens": tokens,
            "targets": targets,
            "n_words": n_words,
            "cur_epoch": item["cur_epoch"],
            "batches_in_epoch": item["batches_in_epoch"],
            "corpus_epoch": item["corpus_epoch"],
        }

    def device_groups() -> Iterator[Dict[str, Any]]:
        """Consumer composition: read -> (pooled) collate -> transfer.

        Whatever single thread iterates THIS generator (the main loop, or
        the prefetch producer) is the only thread that calls device_put —
        pool workers stop at host arrays.
        """
        collated_iter = ordered_map(
            gather_groups(),
            collate_group,
            workers=collate_workers if use_pool else 1,
        )
        try:
            for group in collated_iter:
                t_put = time.perf_counter()
                group["tokens"] = place_batch(group["tokens"], mesh, accum=accum > 1)
                group["targets"] = place_batch(group["targets"], mesh, accum=accum > 1)
                pipe_stats.add(
                    "transfer", time.perf_counter() - t_put, t0=t_put
                )
                yield group
        finally:
            close = getattr(collated_iter, "close", None)
            if close is not None:
                close()

    # ---- resilience wiring: watchdog + step-boundary checkpoint ----
    watchdog_timeout = float(T.get("watchdog_timeout_s", 0) or 0)
    watchdog: Optional[Watchdog] = None
    if watchdog_timeout > 0:
        watchdog_stats = pipe_stats.snapshot
        if tel is not None:
            def watchdog_stats():
                # the watchdog hard-exits (os._exit) right after the dump:
                # flush the metric rows + trace buffer NOW so the wedged
                # run's timeline survives for the post-mortem
                tel.emergency_flush()
                return pipe_stats.snapshot()
        watchdog = Watchdog(watchdog_timeout, stats_fn=watchdog_stats)
    keep_checkpoints = int(T.get("keep_checkpoints", 2) or 1)
    last_saved_step = -1

    def save_last(group: Dict[str, Any]) -> None:
        """Write the full-resume checkpoint for the CONSUMED group's step.

        Shared by the eval path and the preemption path so both write the
        identical state shape. The opt-state gather and the data-position
        allgather are COLLECTIVES on multi-host — every rank runs them at
        the same step boundary (rank 0 then writes the files), which is
        why the shutdown flag itself is allgathered first.
        """
        nonlocal last_saved_step
        if output_path is None or step == last_saved_step:
            return
        # every rank's data position, gathered on EVERY process (a
        # collective — all ranks reach this in lockstep); saved by rank 0
        # so each rank can fast-forward to its own exact position on resume
        per_rank_pos = None
        if process_count > 1:
            from jax.experimental import multihost_utils

            per_rank_pos = (
                multihost_utils.process_allgather(
                    np.array(
                        [
                            group["cur_epoch"],
                            group["batches_in_epoch"],
                            group["corpus_epoch"],
                        ],
                        np.int64,
                    )
                )
                .reshape(-1, 3)
                .tolist()
            )
        # called on EVERY rank: with a sharded opt state each rank writes
        # its OWN owner-shard part files (no allgather of the full state
        # through any host — checkpoint.py format v2); rank gating for the
        # params/meta/pointer writes is internal to save()
        TrainCheckpoint.save(
            Path(output_path) / "last-model",
            params=params,  # raw (not averaged): resume state
            opt_state=opt_state,
            step=step,
            epoch=group["cur_epoch"],
            # post-split rng, NOT this step's subkey: resume must
            # continue the exact rng chain the uninterrupted run
            # would have used
            rng=rng,
            best_score=best_score,
            best_step=best_step,
            extra={
                # the CONSUMED group's position tags, not the (possibly
                # prefetched-ahead) producer counters
                "batches_in_epoch": group["batches_in_epoch"],
                "corpus_epoch": group["corpus_epoch"],
                # save-time mesh shape + resolved sharding mode: purely
                # informational (elastic resume re-shards to the CURRENT
                # mesh), logged when the shapes differ
                "mesh": {
                    "n_data": int(n_data),
                    "update_sharding": update_sharding,
                },
                **(
                    {"per_rank_positions": per_rank_pos}
                    if per_rank_pos is not None
                    else {}
                ),
            },
            keep=keep_checkpoints,
        )
        last_saved_step = step  # on every rank: the skip must stay aligned

    last_consumed_epoch = epoch
    dispatch_pushback: Optional[Dict[str, Any]] = None  # bucket-change carry
    params_cell = {"params": params}  # read by the annotation pass
    groups: Iterator[Dict[str, Any]] = device_groups()
    prefetch_n = int(T.get("prefetch_batches", 2) or 0)
    if process_count == 1 and not annotating:
        # overlap collation + host->device transfer with the running step
        # (multi-host keeps the inline path: the producer's allgathers must
        # stay ordered with the update collectives — see prefetch.py).
        # Annotating mode stays inline too: the producer must predict with
        # the params of the step it feeds (and the update donates the old
        # param buffers, so a run-ahead producer would read freed memory).
        from .prefetch import prefetch_iter

        groups = prefetch_iter(groups, prefetch_n)

    # armed HERE, torn down in the finally below — the watchdog's first
    # window covers the first step's compile, so its timeout must exceed
    # compile time (documented at the knob)
    shutdown.install()
    if watchdog is not None:
        watchdog.start()
    if tel is not None:
        tel.loop_start()
    try:
        while not stop:
            # queue-wait: how long the consumer stalled for its next group.
            # With prefetch/pool active this is the residual the input
            # pipeline failed to hide; inline it equals the whole host-side
            # pipeline time (read+collate+transfer happen in this call).
            if dispatch_pushback is not None:
                # bucket-change leftover from the previous gather leads
                # this dispatch (no queue wait — it is already staged)
                group = dispatch_pushback
                dispatch_pushback = None
            else:
                t_wait = time.perf_counter()
                try:
                    group = next(groups)
                except StopIteration:
                    break
                finally:
                    pipe_stats.add(
                        "queue_wait", time.perf_counter() - t_wait, t0=t_wait
                    )
            # multi-step dispatch: pull up to K groups, CAPPED so the
            # dispatch lands exactly on the next eval/max_steps/patience
            # boundary — those paths then run identically to K=1 (the
            # "force K=1 at the boundary step" contract)
            k_this = 1
            if update_multi is not None:
                k_this = min(
                    steps_per_dispatch,
                    eval_frequency - (step % eval_frequency),
                )
                if max_steps:
                    k_this = min(k_this, max_steps - step)
                if patience and best_step >= 0:
                    k_this = min(k_this, max(patience - (step - best_step), 1))
                if profile_dir is not None and profile_start < profile_stop:
                    # land a dispatch exactly on each window edge, else a
                    # window strictly inside one K-stride is never seen
                    # (start is only checked at dispatch boundaries) and an
                    # active trace would overshoot the stop by up to K-1
                    if steps_run < profile_start:
                        k_this = min(k_this, profile_start - steps_run)
                    elif steps_run < profile_stop:
                        k_this = min(k_this, profile_stop - steps_run)
                k_this = max(k_this, 1)
            dispatch_groups = [group]
            if k_this > 1:
                # stack only groups in the SAME padding bucket (the scan
                # needs homogeneous shapes): a bucket change flushes this
                # dispatch and the odd group leads the next one
                sig0 = _group_shape_sig(group)
                while len(dispatch_groups) < k_this:
                    t_wait = time.perf_counter()
                    try:
                        g = next(groups)
                    except StopIteration:
                        # stream ran dry mid-gather: dispatch what we have
                        break
                    finally:
                        pipe_stats.add(
                            "queue_wait",
                            time.perf_counter() - t_wait,
                            t0=t_wait,
                        )
                    if _group_shape_sig(g) != sig0:
                        dispatch_pushback = g
                        break
                    dispatch_groups.append(g)
            k_this = len(dispatch_groups)
            # the LAST group's data-position tags are the consumed position
            # (save_last checkpoints the boundary after all k inner steps)
            group = dispatch_groups[-1]
            tokens, targets = group["tokens"], group["targets"]
            n_words = sum(g["n_words"] for g in dispatch_groups)
            cur_epoch = last_consumed_epoch = group["cur_epoch"]
            if (
                profile_dir is not None
                and not profile_active
                and profile_start < profile_stop  # [start, stop): empty = off
                and profile_start <= steps_run < profile_stop
            ):
                jax.profiler.start_trace(str(profile_dir))
                profile_active = True
            if before_update is not None:
                before_update(nlp, {"step": step, "epoch": cur_epoch})
            # fault-injection site "step": a `sigterm` rule here exercises
            # the preemption path at an exact step; an error rule, the
            # supervisor's crash/restart path; a `nan` rule poisons this
            # step's reported loss (telemetry NaN-detector drill). One
            # probe per INNER step so rule call-counts stay step-aligned
            # when steps_per_dispatch > 1.
            poisons = []
            for _ in range(k_this):
                maybe_fail("step")
                poisons.append(resilience.consume_poison("step"))
            if k_this == 1:
                rng, sub = jax.random.split(rng)
                if shadow is not None:
                    params, opt_state, shadow, loss, metrics = update(
                        params, opt_state, shadow, tokens, targets, sub
                    )
                else:
                    params, opt_state, loss, metrics = update(
                        params, opt_state, tokens, targets, sub
                    )
                step_metrics = [(metrics, poisons[0])]
            else:
                # ONE host round-trip for k_this steps: stack the staged
                # device batches with a leading [k] dim and scan the
                # update over them (bit-identical to k singles — the rng
                # split chain continues inside the program)
                def _stack(groups_, key):
                    return jax.tree_util.tree_map(
                        lambda *xs: jnp.stack(xs), *[g[key] for g in groups_]
                    )

                s_tokens = _stack(dispatch_groups, "tokens")
                s_targets = _stack(dispatch_groups, "targets")
                if shadow is not None:
                    params, opt_state, shadow, rng, losses, metricses = (
                        update_multi(
                            params, opt_state, shadow, s_tokens, s_targets, rng
                        )
                    )
                else:
                    params, opt_state, rng, losses, metricses = update_multi(
                        params, opt_state, s_tokens, s_targets, rng
                    )
                loss = losses[-1]

                def _inner(tree, i):
                    return jax.tree_util.tree_map(lambda x: x[i], tree)

                step_metrics = [
                    (_inner(metricses, i), poisons[i]) for i in range(k_this)
                ]
            params_cell["params"] = params
            step += k_this
            steps_run += k_this
            if profile_active and steps_run >= profile_stop:
                jax.block_until_ready(loss)
                jax.profiler.stop_trace()
                profile_active = False
            if use_averages:
                # steps_per_dispatch is bypassed to 1 under use_averages,
                # so the running mean still sees every step's params
                avg_count += 1
                avg_params = _avg_step(avg_params, params, avg_count)
            result.words_seen += n_words
            words_since_log += n_words

            # keep metrics as device arrays — float() here would synchronize the
            # host with the device EVERY step and kill host/device overlap; the
            # accumulated scalars are only materialized at eval/log time
            # (tagged with each step's nan-poison flag for drain_metrics)
            pending_metrics.extend(step_metrics)
            if tel is not None:
                # ONE clock stamp per dispatch: the boundary fans out into
                # k_this per-inner-step histogram observations / rows /
                # spans (elapsed/k each), so detectors and percentiles
                # still see every step
                tel.step_boundary(
                    step=step, epoch=cur_epoch, n_words=n_words,
                    steps_run=steps_run, inner_steps=k_this,
                    words_each=(
                        [g["n_words"] for g in dispatch_groups]
                        if k_this > 1
                        else None
                    ),
                )

            info: Optional[Dict[str, Any]] = None
            if step % eval_frequency == 0:
                drain_metrics()
                # eval (and best-model save) uses averaged params when enabled.
                # Params stay ON DEVICE through prediction — gathering the full
                # tree to host every eval (then re-uploading it per dev chunk)
                # costs two full-model transfers for nothing.
                eval_src = avg_params if use_averages else params
                eval_t0 = time.perf_counter()
                scores = nlp.evaluate(dev_examples, eval_src, mesh=mesh)
                eval_seconds = time.perf_counter() - eval_t0
                score = weighted_score(scores, score_weights)
                now = time.perf_counter()
                wps = words_since_log / max(now - last_log_time, 1e-9)
                last_log_time = now
                words_since_log = 0
                info = {
                    "epoch": cur_epoch,
                    "step": step,
                    "words": result.words_seen,
                    "losses": dict(loss_accum),
                    "other_scores": scores,
                    "score": score,
                    "wps": wps,
                    "eval_seconds": eval_seconds,
                    # cumulative per-stage input-pipeline seconds + cache
                    # counters (read / tokenize+collate / transfer /
                    # queue-wait) — the host-side account of where batch
                    # preparation time went (collate_pool.py)
                    "input_pipeline": pipe_stats.snapshot(),
                }
                if tel is not None:
                    tel.trace.add_span(
                        "eval", eval_t0, eval_seconds, cat="loop",
                        args={"step": step}, force=True,
                    )
                    info["telemetry"] = tel.eval_boundary(
                        step=step,
                        epoch=cur_epoch,
                        steps_run=steps_run,
                        losses=dict(loss_accum),
                        score=score,
                        eval_seconds=eval_seconds,
                        input_pipeline=info["input_pipeline"],
                        # one-shot XLA cost analysis (a trace, not a
                        # compile) — bench.py's MFU numerator path; always
                        # the SINGLE-step program (per-step flops), with
                        # the shadow argument when the update takes one
                        flops_fn=lambda: program_flops(
                            update,
                            *(
                                (params, opt_state, shadow)
                                if shadow is not None
                                else (params, opt_state)
                            ),
                            tokens,
                            targets,
                            rng,
                        ),
                        wps=wps,
                    )
                    info["step_ms_p50"] = _ms(
                        info["telemetry"]["step_seconds_p50"]
                    )
                    info["step_ms_p95"] = _ms(
                        info["telemetry"]["step_seconds_p95"]
                    )
                result.history.append(info)
                loss_accum = {}
                if score > best_score:
                    best_score = score
                    best_step = step
                    if output_path is not None and jax.process_index() == 0:
                        nlp.params = jax.device_get(eval_src)
                        with _tspan("checkpoint_save", kind="best", step=step):
                            nlp.to_disk(Path(output_path) / "best-model")
                with _tspan("checkpoint_save", kind="last", step=step):
                    save_last(group)
                if tel is not None:
                    # eval + checkpoint time must not count against the
                    # NEXT step's measured step time
                    tel.rearm_step_clock()
            log_step(info)
            if watchdog is not None:
                watchdog.beat()

            if max_steps and step >= max_steps:
                stop = True
            if patience and best_step >= 0 and (step - best_step) >= patience:
                stop = True
            # preemption poll, AFTER the step completed: on multi-host the
            # flag is allgathered so every rank agrees to checkpoint THIS
            # step (stop conditions above are replica-identical, so the
            # poll itself stays collective-aligned)
            if not stop and shutdown.coordinated_stop(process_count):
                with _tspan("preemption_drain", step=step):
                    drain_metrics()
                    save_last(group)
                result.interrupted = True
                log_event(
                    "preempted",
                    f"shutdown signal at step {step} — checkpoint written at "
                    "the step boundary; resume with --resume",
                    step=step,
                )
                stop = True

    finally:
        # stop the prefetch producer and drop its buffered (on-device)
        # batches even when a step/eval raises — train() may be called
        # again in the same process
        if hasattr(groups, "close"):
            groups.close()
        if watchdog is not None:
            watchdog.stop()
        shutdown.restore()
        if tel_http is not None:
            tel_http.stop()
        if tel is not None:
            # flush metric rows + trace even when a step/eval raised — a
            # crashed run's timeline is exactly the one worth reading
            tel.finalize()
    if profile_active:  # loop ended inside the window: still write the trace
        jax.profiler.stop_trace()
        profile_active = False
    result.seconds = time.perf_counter() - start_time
    result.best_score = best_score
    result.best_step = best_step
    result.final_step = step
    # the producer may have run ahead under prefetch: report the epoch count
    # as of the last CONSUMED group (matching the no-prefetch behavior of
    # "completed epochs" when the stream ran dry, else the current epoch)
    result.epoch = epoch if not stop else last_consumed_epoch
    nlp.params = jax.device_get(params)
    if output_path is not None and jax.process_index() == 0:
        nlp.to_disk(Path(output_path) / "last-model")
    log_finalize()
    return nlp, result
