"""Unified telemetry: metrics registry, Chrome-trace span emitter, device
sampling, and anomaly detection for the training loop.

The reference's whole value proposition is keeping N workers productively
busy, which cannot be verified without per-stage visibility — SURVEY.md
§5.5 calls for step-time and words/sec/chip metrics as first-class
citizens, and Ray (Moritz et al., arXiv:1712.05889) ships system-wide
timeline tracing as a core primitive precisely because distributed
training stalls are invisible in aggregate throughput numbers. This
module is the one layer a dashboard, a bench run, or a post-mortem
consumes; every later perf PR reports through it.

Four pieces, individually inert and composable:

* :class:`MetricsRegistry` — thread-safe counters / gauges / histograms
  with explicit clock injection. The hot loop takes ONE wall-clock stamp
  per step (``Telemetry.step_boundary``); everything else derives from
  stamps the loop already takes. When telemetry is disabled the loop
  holds no registry at all — the disabled path makes zero registry calls
  (guarded by a test).
* :class:`TraceBuffer` — bounded Chrome trace-event buffer
  (Perfetto-loadable JSON): host stages (read / collate / transfer /
  queue-wait, emitted through :class:`~.collate_pool.PipelineStats`),
  eval, checkpoint save/load, preemption drains, and device-step
  boundaries. ``bench.py --input-pipeline`` attaches the same emitter —
  bench spans and training spans can never drift apart.
* device sampling (:func:`sample_device_telemetry`) at eval boundaries:
  HBM usage via ``device.memory_stats()`` (None off-TPU), live-buffer
  counts, and a cumulative compile counter fed by a ``jax.monitoring``
  listener (:func:`install_compile_hook`) — the recompilation-storm
  signal. :func:`program_flops` is the XLA cost-analysis path bench.py's
  MFU accounting reuses.
* :class:`AnomalyDetectors` — NaN/Inf loss, loss spike vs rolling
  median, step-time regression vs rolling p50, recompile-after-warmup.
  Every firing goes through ``resilience.log_event`` (so it lands in the
  jsonl training log) AND a ``kind: "anomaly"`` row in ``metrics.jsonl``
  (so ``telemetry summarize`` digests it offline).

``metrics.jsonl`` row kinds: ``step`` (per-step step-time + words, and
per-step ``loss`` on the trainer-fleet path), ``eval`` (gauges: HBM,
compile count, live buffers, step-time p50/p95, MFU estimate, per-stage
seconds), ``anomaly``, ``serving`` (a serve run's snapshot), ``fleet``
(a trainer-fleet worker's exit row: counters, phase ledger, dynamics-
histogram snapshots). Rows buffer in memory and flush at eval
boundaries / finalize / watchdog fire — never per-step file I/O in the
hot loop.
"""

from __future__ import annotations

import json
import math
import threading
import time
from bisect import bisect_left
from collections import deque
from pathlib import Path
from typing import Any, Callable, Dict, IO, List, Optional, Sequence, Tuple

__all__ = [
    "MetricsRegistry",
    "TraceBuffer",
    "AnomalyDetectors",
    "FleetDivergenceDetector",
    "Telemetry",
    "TPU_PEAK_BF16",
    "LATENCY_BUCKETS",
    "STEP_SECONDS_BUCKETS",
    "OCCUPANCY_BUCKETS",
    "STALENESS_BUCKETS",
    "FLEET_DYNAMICS_HISTOGRAMS",
    "FLEET_WIRE_COUNTERS",
    "install_compile_hook",
    "compile_count",
    "sample_device_telemetry",
    "program_flops",
    "device_peak_flops",
    "sanitize_json",
    "summarize_metrics",
    "merge_serving_snapshots",
]


# Shared Prometheus-style bucket tables (upper bounds, seconds unless
# noted). ONE table per quantity kind, used by every registry in the
# repo, so the cross-process exposition (replica, router, trainer) is
# mergeable by any scraper — summing `_bucket` series only means
# something when the boundaries agree.
LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0,
)
STEP_SECONDS_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
    10.0, 30.0, 60.0, 120.0,
)
OCCUPANCY_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0)
# version lag (in shard versions, not seconds) of each ACCEPTED gradient
# push — the trainer fleet's bounded-staleness evidence. le=0 is the
# in-round bucket; anything past max_staleness is discarded before it
# could be observed, so the +Inf bin staying empty is itself a proof
# the discard gate holds.
STALENESS_BUCKETS = (0.0, 1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 16.0)

# the trainer fleet's dynamics families (docs/OBSERVABILITY.md "Training
# fleet") and the shared bucket table each uses — ONE definition so the
# owner side (peer.py), the worker side (worker.py), the run report, and
# the golden-grammar tests can never disagree on which registry names
# make up the fleet surface. Keys are registry names (the Prometheus
# exposition renders them under srt_training_* with a worker label).
FLEET_DYNAMICS_HISTOGRAMS = {
    "staleness": STALENESS_BUCKETS,
    "quorum_wait_seconds": LATENCY_BUCKETS,
    "apply_seconds": LATENCY_BUCKETS,
    "phase_data_seconds": STEP_SECONDS_BUCKETS,
    "phase_pull_seconds": STEP_SECONDS_BUCKETS,
    "phase_grad_seconds": STEP_SECONDS_BUCKETS,
    "phase_push_seconds": STEP_SECONDS_BUCKETS,
    "phase_apply_wait_seconds": STEP_SECONDS_BUCKETS,
}

# the fleet's wire-byte counter families (the compression ledger —
# fleet/peer.py COUNTER_NAMES mirrors these into every worker's
# registry, Prometheus renders them as srt_training_<name>_total with a
# worker label). The _uncompressed twins count what the SAME payloads
# would have cost as f32 full frames, so compression ratio is
# (uncompressed / actual) computable from any two scrapes — `telemetry
# top`'s wire column and the run report's wire table both divide these.
FLEET_WIRE_COUNTERS = (
    "wire_push_bytes",
    "wire_push_bytes_uncompressed",
    "wire_pull_bytes",
    "wire_pull_bytes_uncompressed",
)


# ----------------------------------------------------------------------
# Metrics registry
# ----------------------------------------------------------------------


def _nearest_rank(sorted_samples: List[float], q: float) -> Optional[float]:
    """Nearest-rank percentile over an ascending list (None when empty) —
    the ONE percentile convention, shared by the online histogram and the
    offline ``summarize_metrics`` so their p50/p95 can never diverge."""
    if not sorted_samples:
        return None
    idx = min(int(q * len(sorted_samples)), len(sorted_samples) - 1)
    return sorted_samples[idx]


def sanitize_json(obj: Any) -> Any:
    """Replace non-finite floats with their string names ("nan"/"inf") —
    ``json.dumps`` would otherwise emit bare ``NaN`` tokens, which are
    invalid JSON and break every non-Python consumer of the
    'machine-readable' jsonl files exactly when the NaN anomaly the files
    exist to capture occurs."""
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else str(obj)
    if isinstance(obj, dict):
        return {k: sanitize_json(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [sanitize_json(v) for v in obj]
    return obj


def _merge_weighted(
    out: Dict[str, Any], key: str, pairs: List[Tuple[float, float]]
) -> None:
    """The one fleet-percentile merge rule (``merge_serving_snapshots``
    uses it for histogram percentiles, the ``slo`` block, and the
    ``slo_window`` block): a fleet p99 is not derivable from per-replica
    p99s, so report the weight-weighted mean under ``key`` AND the worst
    replica under ``key_worst`` — the honest bound an SLO check should
    use. Zero total weight (all-idle replicas) falls back to the
    unweighted mean; no values at all writes None for both."""
    if not pairs:
        out[key] = out[f"{key}_worst"] = None
        return
    total_w = sum(w for _, w in pairs)
    out[key] = (
        sum(v * w for v, w in pairs) / total_w
        if total_w > 0
        else sum(v for v, _ in pairs) / len(pairs)
    )
    out[f"{key}_worst"] = max(v for v, _ in pairs)


def merge_serving_snapshots(
    snaps: List[Dict[str, Any]], *, _tag_generations: bool = True
) -> Dict[str, Any]:
    """Merge per-replica ``ServingTelemetry.snapshot()`` payloads into
    one fleet view (the router's ``/metrics``) — one scrape instead of N.

    Merge rules, stated honestly:

    * **counters** — summed: counts of events are exactly additive.
    * **gauges** — reported as ``{sum, max, mean}`` per key: which
      aggregate is meaningful depends on the gauge (total queue depth is
      the ``sum``; a worst-replica occupancy is the ``max``) — the fleet
      view carries all three rather than guessing.
    * **histograms** — ``count``/``sum``/``min``/``max`` merge exactly.
      Percentiles do NOT: a fleet p99 cannot be derived from per-replica
      p99s (the underlying samples are gone). The merged view reports
      the count-weighted mean (``p50``/``p95``/``p99`` — a reasonable
      center) and the worst replica (``p99_worst`` etc.) — the honest
      bound an SLO check should use.
    * the ``slo`` block follows the histogram rule (weighted by the
      replica's latency sample count, worst alongside).
    * the ``slo_window`` block (sliding-window percentiles — recent
      load, not run lifetime) merges the same way, weighted by each
      replica's IN-WINDOW sample count, so the fleet view reacts to a
      spike as fast as the freshest replica does.
    * **generations** — when any snapshot carries a ``generation`` stamp
      (live serving: the checkpoint generation that replica's dispatch
      thread is running), the merged view adds ``by_generation``: the
      SAME merge re-run per generation group, so the slo_window
      percentiles (and error/request counters) are splittable by
      generation — the canary guard's entire signal. Replicas serving
      the model as loaded from disk (generation null) group under
      ``"none"``.
    * **models** — when any snapshot carries a ``models`` block
      (multi-model serving: model name → that engine's own snapshot,
      stamped per model by the replica), the merged view adds
      ``by_model``: the SAME merge re-run over each model's per-engine
      snapshots gathered across replicas — the per-model window p99 the
      placement policy and the per-class SLO story read.
    """
    merged: Dict[str, Any] = {
        "replicas": len(snaps),
        "counters": {},
        "gauges": {},
        "histograms": {},
        "slo": {},
    }
    if not snaps:
        return merged
    counters: Dict[str, float] = {}
    for snap in snaps:
        for k, v in (snap.get("counters") or {}).items():
            if isinstance(v, (int, float)):
                counters[k] = counters.get(k, 0) + v
    merged["counters"] = counters
    gauges: Dict[str, List[float]] = {}
    for snap in snaps:
        for k, v in (snap.get("gauges") or {}).items():
            if isinstance(v, (int, float)):
                gauges.setdefault(k, []).append(float(v))
    merged["gauges"] = {
        k: {
            "sum": sum(vs),
            "max": max(vs),
            "mean": sum(vs) / len(vs),
        }
        for k, vs in gauges.items()
    }

    def _weight(snap: Dict[str, Any], hist_key: str) -> float:
        h = (snap.get("histograms") or {}).get(hist_key) or {}
        c = h.get("count")
        return float(c) if isinstance(c, (int, float)) and c > 0 else 0.0

    hist_keys = {
        k for snap in snaps for k in (snap.get("histograms") or {})
    }
    for key in sorted(hist_keys):
        entries = [
            (snap.get("histograms") or {}).get(key) or {} for snap in snaps
        ]
        counts = [
            e.get("count") for e in entries
            if isinstance(e.get("count"), (int, float))
        ]
        sums = [
            e.get("sum") for e in entries
            if isinstance(e.get("sum"), (int, float))
        ]
        mins = [
            e.get("min") for e in entries
            if isinstance(e.get("min"), (int, float))
        ]
        maxs = [
            e.get("max") for e in entries
            if isinstance(e.get("max"), (int, float))
        ]
        out: Dict[str, Any] = {
            "count": sum(counts) if counts else 0,
            "sum": sum(sums) if sums else 0.0,
            "min": min(mins) if mins else None,
            "max": max(maxs) if maxs else None,
        }
        for q in ("p50", "p95", "p99"):
            _merge_weighted(out, q, [
                (float(e[q]), float(e.get("count") or 0))
                for e in entries
                if isinstance(e.get(q), (int, float))
            ])
        # cumulative buckets merge EXACTLY (counts are additive) — the
        # one fleet histogram aggregate with no approximation caveat —
        # but only when every replica counted against the same bounds;
        # mismatched tables are dropped rather than summed dishonestly
        bucketed = [e.get("buckets") for e in entries if e.get("buckets")]
        if bucketed and len(bucketed) == len(
            [e for e in entries if e.get("count") is not None]
        ):
            bounds = [tuple(float(b[0]) for b in bs) for bs in bucketed]
            if all(b == bounds[0] for b in bounds):
                out["buckets"] = [
                    [le, sum(float(bs[i][1]) for bs in bucketed)]
                    for i, le in enumerate(bounds[0])
                ]
        merged["histograms"][key] = out

    slo_keys = {k for snap in snaps for k in (snap.get("slo") or {})}
    for key in sorted(slo_keys):
        hist_key = (
            "batch_occupancy" if "occupancy" in key
            else "request_latency_seconds"
        )
        _merge_weighted(merged["slo"], key, [
            (float((snap.get("slo") or {})[key]), _weight(snap, hist_key))
            for snap in snaps
            if isinstance((snap.get("slo") or {}).get(key), (int, float))
        ])

    window_snaps = [
        snap.get("slo_window") for snap in snaps
        if isinstance(snap.get("slo_window"), dict)
    ]
    if window_snaps:
        win: Dict[str, Any] = {
            "window_s": max(
                float(w.get("window_s") or 0.0) for w in window_snaps
            ),
            "samples": sum(int(w.get("samples") or 0) for w in window_snaps),
        }
        win_keys = {
            k for w in window_snaps for k in w
            if k not in ("window_s", "samples")
        }
        for key in sorted(win_keys):
            _merge_weighted(win, key, [
                (float(w[key]), float(w.get("samples") or 0))
                for w in window_snaps
                if isinstance(w.get(key), (int, float))
            ])
        merged["slo_window"] = win

    if _tag_generations:
        gens = {snap.get("generation") for snap in snaps}
        if any(g is not None for g in gens):
            by_gen: Dict[str, Any] = {}
            for g in sorted(gens, key=lambda x: (x is None, x)):
                subset = [s for s in snaps if s.get("generation") == g]
                sub = merge_serving_snapshots(
                    subset, _tag_generations=False
                )
                sub["generation"] = g
                by_gen["none" if g is None else str(g)] = sub
            merged["by_generation"] = by_gen
        model_groups: Dict[str, List[Dict[str, Any]]] = {}
        for snap in snaps:
            models = snap.get("models")
            if not isinstance(models, dict):
                continue
            for name, msnap in models.items():
                if isinstance(msnap, dict):
                    model_groups.setdefault(str(name), []).append(msnap)
        if model_groups:
            by_model: Dict[str, Any] = {}
            for name in sorted(model_groups):
                sub = merge_serving_snapshots(
                    model_groups[name], _tag_generations=False
                )
                sub["model"] = name
                by_model[name] = sub
            merged["by_model"] = by_model
    return merged


class _Counter:
    __slots__ = ("_lock", "value")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n


class _Gauge:
    __slots__ = ("_lock", "value")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self.value: Optional[float] = None

    def set(self, v: Optional[float]) -> None:
        with self._lock:
            self.value = v


class _Histogram:
    """Running count/sum plus a bounded sample ring for percentiles.

    The ring doubles as the ROLLING window (rolling p50 for the
    step-time regression detector): percentiles describe the last
    ``max_samples`` observations, count/sum describe the whole run.

    ``window_s`` additionally keeps TIME-stamped samples so
    :meth:`window_snapshot` can answer "what do the last T seconds look
    like" — the count-based ring dilutes a fresh load spike among
    thousands of older samples exactly when a control loop (the fleet
    autoscaler) needs to see it. The timed buffer is hard-capped at
    8 × ``max_samples`` entries as a memory bound; at rates that
    overflow the cap within the window, the window percentiles describe
    the most recent cap-sized slice (still the freshest data).

    ``buckets`` (optional ascending upper bounds) arms Prometheus-style
    cumulative bucket counting over the WHOLE run (unlike the bounded
    percentile ring, bucket counts never forget) — the exact thing the
    text exposition's ``_bucket`` series needs, and the one histogram
    aggregate that merges exactly across replicas (counts are additive;
    percentiles are not).
    """

    __slots__ = (
        "_lock", "_samples", "count", "sum", "max", "min",
        "window_s", "_clock", "_timed", "buckets", "_bucket_counts",
    )

    def __init__(
        self,
        lock: threading.Lock,
        max_samples: int = 512,
        window_s: Optional[float] = None,
        clock: Callable[[], float] = time.perf_counter,
        buckets: Optional[Sequence[float]] = None,
    ):
        self._lock = lock
        self._samples: "deque[float]" = deque(maxlen=max_samples)
        self.count = 0
        self.sum = 0.0
        self.max: Optional[float] = None
        self.min: Optional[float] = None
        self.window_s = float(window_s) if window_s else None
        self._clock = clock
        self._timed: "deque[Tuple[float, float]]" = deque(
            maxlen=8 * max_samples
        )
        self.buckets: Optional[Tuple[float, ...]] = (
            tuple(sorted(float(b) for b in buckets)) if buckets else None
        )
        # one bin per bound plus the +Inf overflow bin; cumulated at
        # snapshot time so observe() stays a single increment
        self._bucket_counts: Optional[List[int]] = (
            [0] * (len(self.buckets) + 1) if self.buckets else None
        )

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self._samples.append(v)
            self.count += 1
            self.sum += v
            self.max = v if self.max is None else max(self.max, v)
            self.min = v if self.min is None else min(self.min, v)
            if self._bucket_counts is not None:
                # first bound >= v (le is inclusive); beyond the last
                # bound lands in the +Inf bin
                self._bucket_counts[
                    bisect_left(self.buckets, v)
                ] += 1
            if self.window_s is not None:
                now = self._clock()
                self._timed.append((now, v))
                self._prune(now)

    def _prune(self, now: float) -> None:
        """Drop timed samples older than the window (caller holds lock)."""
        cutoff = now - (self.window_s or 0.0)
        while self._timed and self._timed[0][0] < cutoff:
            self._timed.popleft()

    def window_snapshot(self) -> Optional[Dict[str, Any]]:
        """p50/p95/p99 over the last ``window_s`` seconds only (None when
        the histogram has no time window configured). Pruning happens at
        read time too, so a quiet period empties the window instead of
        freezing its last busy picture."""
        if self.window_s is None:
            return None
        with self._lock:
            self._prune(self._clock())
            samples = sorted(v for _, v in self._timed)
        return {
            "window_s": self.window_s,
            "samples": len(samples),
            "p50": _nearest_rank(samples, 0.5),
            "p95": _nearest_rank(samples, 0.95),
            "p99": _nearest_rank(samples, 0.99),
        }

    def percentile(self, q: float) -> Optional[float]:
        """q in [0, 1] over the rolling sample window (nearest-rank)."""
        with self._lock:
            samples = sorted(self._samples)
        return _nearest_rank(samples, q)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            samples = sorted(self._samples)
            count, total = self.count, self.sum
            mx, mn = self.max, self.min
            bins = (
                list(self._bucket_counts)
                if self._bucket_counts is not None else None
            )
        snap = {
            "count": count,
            "sum": round(total, 6),
            "min": mn,
            "max": mx,
            "p50": _nearest_rank(samples, 0.5),
            "p95": _nearest_rank(samples, 0.95),
            # tail percentile the serving SLO surface reads; same rolling
            # window and nearest-rank convention as p50/p95
            "p99": _nearest_rank(samples, 0.99),
        }
        if bins is not None:
            # cumulative [le, count] pairs, Prometheus convention; the
            # +Inf bin is implicit (== count) so JSON stays finite
            cum, pairs = 0, []
            for le, n in zip(self.buckets, bins):
                cum += n
                pairs.append([le, cum])
            snap["buckets"] = pairs
        return snap


class MetricsRegistry:
    """Named counters/gauges/histograms behind one lock.

    Get-or-create by name; hold instrument references on the hot path
    (the per-step cost is then one lock acquire per observation, and
    nothing at all when telemetry is disabled — the loop simply has no
    registry to call).
    """

    def __init__(self, clock: Callable[[], float] = time.perf_counter):
        self._lock = threading.Lock()
        self._clock = clock
        self._counters: Dict[str, _Counter] = {}
        self._gauges: Dict[str, _Gauge] = {}
        self._histograms: Dict[str, _Histogram] = {}

    def counter(self, name: str) -> _Counter:
        with self._lock:
            if name not in self._counters:
                self._counters[name] = _Counter(self._lock)
            return self._counters[name]

    def gauge(self, name: str) -> _Gauge:
        with self._lock:
            if name not in self._gauges:
                self._gauges[name] = _Gauge(self._lock)
            return self._gauges[name]

    def histogram(
        self,
        name: str,
        max_samples: int = 512,
        window_s: Optional[float] = None,
        buckets: Optional[Sequence[float]] = None,
    ) -> _Histogram:
        with self._lock:
            if name not in self._histograms:
                self._histograms[name] = _Histogram(
                    self._lock, max_samples, window_s=window_s,
                    clock=self._clock, buckets=buckets,
                )
            return self._histograms[name]

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            histograms = dict(self._histograms)
        return {
            "counters": {k: c.value for k, c in counters.items()},
            "gauges": {k: g.value for k, g in gauges.items()},
            "histograms": {k: h.snapshot() for k, h in histograms.items()},
        }


# ----------------------------------------------------------------------
# Chrome trace-event span emitter
# ----------------------------------------------------------------------


class TraceBuffer:
    """Bounded, thread-safe Chrome trace-event buffer.

    Events use the complete-event form (``ph: "X"``) with microsecond
    timestamps relative to the buffer's construction; ``flush()`` writes
    a ``{"traceEvents": [...]}`` JSON object that chrome://tracing and
    ui.perfetto.dev load directly. Worker threads get their own ``tid``
    (with ``thread_name`` metadata rows) so pooled collation spans render
    as parallel tracks.

    ``set_recording(False)`` drops non-forced spans — the training loop
    gates the per-step/host-stage firehose to the ``trace_steps`` window
    while rare events (eval, checkpoints, anomalies) pass ``force=True``.
    ``flush()`` is re-entrant and atomic (tmp + replace): the watchdog
    flushes mid-run before a hard exit, finalize flushes again.
    """

    MAX_EVENTS = 200_000

    def __init__(
        self,
        clock: Callable[[], float] = time.perf_counter,
        pid: int = 0,
        max_events: int = MAX_EVENTS,
    ):
        self._clock = clock
        self._origin = clock()
        self._pid = int(pid)
        self._lock = threading.Lock()
        self._events: "deque[Dict[str, Any]]" = deque(maxlen=max_events)
        self._tids: Dict[int, int] = {}
        self._tid_names: Dict[int, str] = {}
        self._recording = True
        self.dropped = 0

    def _tid(self) -> int:
        t = threading.current_thread()
        ident = t.ident or 0
        with self._lock:
            if ident not in self._tids:
                self._tids[ident] = len(self._tids)
                self._tid_names[self._tids[ident]] = t.name
            return self._tids[ident]

    def set_recording(self, on: bool) -> None:
        self._recording = bool(on)

    @property
    def recording(self) -> bool:
        return self._recording

    def now(self) -> float:
        """Clock read for callers that stamp their own t0."""
        return self._clock()

    def add_span(
        self,
        name: str,
        t0: float,
        dur: float,
        *,
        cat: str = "host",
        args: Optional[Dict[str, Any]] = None,
        force: bool = False,
    ) -> None:
        """One complete span: ``t0`` is a clock() stamp, ``dur`` seconds."""
        if not self._recording and not force:
            return
        ev = {
            "name": name,
            "ph": "X",
            "cat": cat,
            "ts": round((t0 - self._origin) * 1e6, 1),
            "dur": round(max(dur, 0.0) * 1e6, 1),
            "pid": self._pid,
            "tid": self._tid(),
        }
        if args:
            ev["args"] = args
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self.dropped += 1
            self._events.append(ev)

    def add_instant(
        self,
        name: str,
        *,
        cat: str = "anomaly",
        args: Optional[Dict[str, Any]] = None,
    ) -> None:
        """A point-in-time marker (``ph: "i"``) — anomalies, signals."""
        ev = {
            "name": name,
            "ph": "i",
            "s": "g",  # global scope: draw the marker across all tracks
            "cat": cat,
            "ts": round((self._clock() - self._origin) * 1e6, 1),
            "pid": self._pid,
            "tid": self._tid(),
        }
        if args:
            ev["args"] = args
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self.dropped += 1
            self._events.append(ev)

    class _Span:
        __slots__ = ("_buf", "_name", "_cat", "_args", "_force", "_t0")

        def __init__(self, buf, name, cat, args, force):
            self._buf, self._name = buf, name
            self._cat, self._args, self._force = cat, args, force

        def __enter__(self):
            self._t0 = self._buf._clock()
            return self

        def __exit__(self, *exc: Any) -> None:
            self._buf.add_span(
                self._name,
                self._t0,
                self._buf._clock() - self._t0,
                cat=self._cat,
                args=self._args,
                force=self._force,
            )

    def span(
        self,
        name: str,
        *,
        cat: str = "host",
        force: bool = True,
        **args: Any,
    ) -> "TraceBuffer._Span":
        """Context manager emitting one span (forced by default — used for
        rare events like checkpoints that must outlive the step window)."""
        return TraceBuffer._Span(self, name, cat, args or None, force)

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def anchor(self) -> Dict[str, float]:
        """The clock anchor a cross-process trace collector needs to put
        this buffer's events on a shared timeline: event timestamps are
        microseconds relative to ``origin`` on the buffer's own monotonic
        clock, and ``(clock_now, unix_now)`` is one simultaneous reading
        of that clock against the wall — enough to map any event to wall
        time without the processes sharing a clock. Exposed on each
        process's ``/healthz`` and ``/trace``."""
        return {
            "origin": self._origin,
            "clock_now": self._clock(),
            "unix_now": time.time(),
        }

    def payload(self) -> Dict[str, Any]:
        """The Chrome trace JSON object (thread_name metadata + events)
        — what ``flush`` writes and what the ``/trace`` endpoints serve."""
        with self._lock:
            events = list(self._events)
            names = dict(self._tid_names)
        meta = [
            {
                "name": "thread_name",
                "ph": "M",
                "pid": self._pid,
                "tid": tid,
                "args": {"name": tname},
            }
            for tid, tname in sorted(names.items())
        ]
        return {
            "traceEvents": meta + events,
            "displayTimeUnit": "ms",
        }

    def flush(self, path: Path) -> int:
        """Write the buffer as Chrome trace JSON; returns events written."""
        payload = self.payload()
        # meta rows don't count toward the caller-visible event total
        n_events = sum(
            1 for e in payload["traceEvents"] if e.get("ph") != "M"
        )
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        tmp = path.with_name(path.name + ".tmp")
        tmp.write_text(json.dumps(payload), encoding="utf8")
        tmp.replace(path)
        return n_events


# ----------------------------------------------------------------------
# Device-side sampling
# ----------------------------------------------------------------------

# Dense bf16 peak per chip from public datasheets, substring-matched
# against device_kind (order matters: v5p before v5). The single source —
# bench.py imports this table for its MFU denominators.
TPU_PEAK_BF16 = [
    ("v6", 918e12),  # Trillium / v6e
    ("v5p", 459e12),
    ("v5", 197e12),  # v5e reports device_kind "TPU v5 lite"
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 46e12),
]

_COMPILE_LOCK = threading.Lock()
_COMPILE_COUNT = 0
_HOOK_INSTALLED = False


def install_compile_hook() -> bool:
    """Register a ``jax.monitoring`` listener counting backend compiles.

    Idempotent (jax offers no deregistration, so exactly one process-wide
    listener is ever installed). Every XLA compile — including bucket
    recompiles after warmup, the storm signal — emits a
    ``/jax/core/compile/backend_compile_duration`` duration event; we
    count those. Returns False when the monitoring API is unavailable.
    """
    global _HOOK_INSTALLED
    # the lock spans check-and-register: two racing first callers must not
    # both register (every compile would count twice forever after)
    with _COMPILE_LOCK:
        if _HOOK_INSTALLED:
            return True
        try:
            import jax.monitoring

            def _on_duration(name: str, dur: float, **kw: Any) -> None:
                if name.endswith("backend_compile_duration") or name.endswith(
                    "backend_compile_time"
                ):
                    global _COMPILE_COUNT
                    with _COMPILE_LOCK:
                        _COMPILE_COUNT += 1

            jax.monitoring.register_event_duration_secs_listener(_on_duration)
        except Exception:
            return False
        _HOOK_INSTALLED = True
    return True


def compile_count() -> int:
    """Cumulative backend compiles observed since the hook was installed."""
    with _COMPILE_LOCK:
        return _COMPILE_COUNT


def sample_device_telemetry() -> Dict[str, Any]:
    """One gauge sample of device 0: HBM, live buffers, compile count.

    ``memory_stats()`` is backend-dependent (None on CPU; TPU/GPU report
    ``bytes_in_use`` / ``peak_bytes_in_use`` / ``bytes_limit``) — absent
    keys surface as None rather than fake zeros, so a dashboard can tell
    "no HBM accounting on this backend" from "zero bytes used".
    """
    out: Dict[str, Any] = {
        "platform": None,
        "hbm_bytes_in_use": None,
        "hbm_peak_bytes": None,
        "hbm_bytes_limit": None,
        "live_buffers": None,
        "compile_count": compile_count(),
    }
    try:
        import jax

        dev = jax.devices()[0]
        out["platform"] = dev.platform
        try:
            stats = dev.memory_stats()
        except Exception:
            stats = None
        if stats:
            out["hbm_bytes_in_use"] = stats.get("bytes_in_use")
            out["hbm_peak_bytes"] = stats.get("peak_bytes_in_use")
            out["hbm_bytes_limit"] = stats.get("bytes_limit")
        try:
            out["live_buffers"] = len(jax.live_arrays())
        except Exception:
            pass
    except Exception:
        pass
    return out


def program_flops(
    jit_fn: Any,
    *args: Any,
    on_error: Optional[Callable[[str], None]] = None,
) -> Optional[float]:
    """FLOPs of one compiled step from XLA cost analysis of the lowered
    program (a trace, not a compile). None when the backend can't say —
    callers (bench.py's ``_program_flops``, the eval-boundary MFU gauge)
    choose their own fallback/labeling; ``on_error`` receives the failure
    reason so a missing-MFU record stays debuggable."""
    try:
        cost = jit_fn.lower(*args).cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        flops = float(cost.get("flops", 0.0))
        return flops if flops > 0 else None
    except Exception as e:
        if on_error is not None:
            on_error(f"{type(e).__name__}: {e}")
        return None


def device_peak_flops() -> Tuple[Optional[float], str]:
    """(datasheet peak FLOP/s per chip, provenance) — None off-TPU.

    Deliberately datasheet-only: the training loop must never run
    bench.py's matmul microbench mid-run (it would steal the very step
    time being measured). Without a datasheet number the MFU gauge stays
    None — an honest absence, not a made-up denominator.
    """
    try:
        import jax

        dev = jax.devices()[0]
        if dev.platform != "tpu":
            return None, f"no datasheet peak for {dev.platform}"
        lk = dev.device_kind.lower()
        for sub, peak in TPU_PEAK_BF16:
            if sub in lk:
                return peak, f"datasheet bf16 ({dev.device_kind})"
        return None, f"unknown TPU kind {dev.device_kind!r}"
    except Exception as e:
        return None, f"device query failed: {type(e).__name__}"


# ----------------------------------------------------------------------
# Update-phase attribution (grad-reduce / apply / allgather)
# ----------------------------------------------------------------------

# the three phases the weight-update step decomposes into under
# cross-replica update sharding (arXiv 2004.13336): sum the per-replica
# partial gradients, apply the optimizer to the owned shard, gather the
# updated params back to the replicated layout
UPDATE_PHASES = ("grad_reduce", "apply", "allgather")


def update_phase_block(
    grad_reduce_s: Optional[float],
    apply_s: Optional[float],
    allgather_s: Optional[float],
    *,
    trace: Optional["TraceBuffer"] = None,
    t0: Optional[float] = None,
) -> Dict[str, Any]:
    """The canonical update-phase attribution block bench records carry.

    HONESTY CONTRACT: inside the fused one-program train step the three
    phases are not separately host-observable (XLA overlaps them); these
    numbers come from separately-jitted phase programs (bench.py
    ``--update-only --sharded``), so they are an attribution of where a
    mode's time CAN go, measured in isolation — the one-program
    ``update_seconds`` on the same record is the end-to-end truth. A
    ``None`` phase means the mode has no such phase (e.g. no allgather
    under replicated) and stays None rather than a fake zero.

    When ``trace``/``t0`` are given, each phase is also emitted as a
    back-to-back Chrome-trace span so a Perfetto view can show the split.
    """
    secs = {
        "grad_reduce": grad_reduce_s,
        "apply": apply_s,
        "allgather": allgather_s,
    }
    block: Dict[str, Any] = {
        f"{name}_s": (round(float(v), 6) if v is not None else None)
        for name, v in secs.items()
    }
    total = sum(float(v) for v in secs.values() if v is not None)
    block["total_s"] = round(total, 6)
    if total > 0:
        block["apply_share"] = round(float(secs["apply"] or 0.0) / total, 4)
    if trace is not None and t0 is not None:
        at = t0
        for name in UPDATE_PHASES:
            v = secs[name]
            if v is None:
                continue
            trace.add_span(
                f"update_{name}", at, float(v), cat="update", force=True
            )
            at += float(v)
    return block


# ----------------------------------------------------------------------
# Anomaly detection
# ----------------------------------------------------------------------


def _is_bad(v: float) -> bool:
    return math.isnan(v) or math.isinf(v)


class AnomalyDetectors:
    """Rolling-statistic anomaly checks over host-side scalars.

    Pure host arithmetic on values the loop already materializes (drained
    losses at eval boundaries, the per-step boundary stamp) — never a
    device sync. Each firing calls ``emit(event, message, **fields)``
    once; the default emit path is wired by :class:`Telemetry` to
    ``resilience.log_event`` + a metrics.jsonl anomaly row + a trace
    instant, so one firing is visible in all three surfaces.

    Thresholds and the clock are injectable; tests drive every detector
    deterministically with synthetic series and a fake clock.
    """

    def __init__(
        self,
        emit: Callable[..., Any],
        *,
        clock: Callable[[], float] = time.perf_counter,
        spike_factor: float = 4.0,
        spike_min_history: int = 3,
        loss_window: int = 32,
        step_factor: float = 2.5,
        step_warmup: int = 20,
        step_window: int = 128,
        recompile_warmup_steps: int = 50,
    ):
        self.emit = emit
        self.clock = clock
        self.spike_factor = float(spike_factor)
        self.spike_min_history = int(spike_min_history)
        self.step_factor = float(step_factor)
        self.step_warmup = int(step_warmup)
        self.recompile_warmup_steps = int(recompile_warmup_steps)
        self._loss_history: "deque[float]" = deque(maxlen=int(loss_window))
        self._step_times: "deque[float]" = deque(maxlen=int(step_window))
        self._steps_observed = 0
        self._last_compile_count: Optional[int] = None
        self.fired: Dict[str, int] = {}

    def _fire(self, event: str, message: str, **fields: Any) -> None:
        self.fired[event] = self.fired.get(event, 0) + 1
        fields.setdefault("t", round(self.clock(), 6))
        self.emit(event, message, **fields)

    # -- loss ---------------------------------------------------------
    def check_loss(self, step: int, loss: float) -> None:
        """NaN/Inf, then spike vs the rolling median of finite history."""
        loss = float(loss)
        if _is_bad(loss):
            self._fire(
                "nan-loss",
                f"non-finite loss {loss!r} at step {step}",
                step=step,
                loss=str(loss),
            )
            return  # a NaN must not enter (and poison) the history
        history = sorted(self._loss_history)
        if len(history) >= self.spike_min_history:
            median = history[len(history) // 2]
            if median > 0 and loss > self.spike_factor * median:
                self._fire(
                    "loss-spike",
                    f"loss {loss:.4g} at step {step} is "
                    f"{loss / median:.1f}x the rolling median {median:.4g}",
                    step=step,
                    loss=loss,
                    median=median,
                )
        self._loss_history.append(loss)

    # -- step time ----------------------------------------------------
    def check_step_time(self, step: int, seconds: float) -> None:
        """Regression vs rolling p50, after a warmup (compiles dominate
        the first steps by design and must not count as regressions)."""
        seconds = float(seconds)
        self._steps_observed += 1
        if self._steps_observed > self.step_warmup and self._step_times:
            samples = sorted(self._step_times)
            p50 = samples[len(samples) // 2]
            if p50 > 0 and seconds > self.step_factor * p50:
                self._fire(
                    "step-time-regression",
                    f"step {step} took {seconds * 1e3:.1f}ms — "
                    f"{seconds / p50:.1f}x the rolling p50 "
                    f"{p50 * 1e3:.1f}ms",
                    step=step,
                    seconds=seconds,
                    p50=p50,
                )
        self._step_times.append(seconds)

    # -- recompiles ---------------------------------------------------
    def check_compiles(self, steps_run: int, count: int) -> None:
        """Fire when the cumulative compile count grows after warmup —
        steady state must reuse cached programs; late compiles mean a
        shape leak (an unbucketed batch dimension) or a storm."""
        prev = self._last_compile_count
        self._last_compile_count = int(count)
        if prev is None:
            return
        if count > prev and steps_run > self.recompile_warmup_steps:
            self._fire(
                "recompile-after-warmup",
                f"{count - prev} new XLA compile(s) after step "
                f"{steps_run} (cumulative {count}) — check shape "
                "bucketing",
                steps_run=steps_run,
                new_compiles=count - prev,
                compile_count=count,
            )


class FleetDivergenceDetector:
    """Cross-worker convergence watch for the trainer fleet — the
    fleet-LEVEL twin of :class:`AnomalyDetectors` (which only sees one
    process's series). The lead worker polls every peer's ``/metrics``
    and feeds one ``observe(stats)`` call per poll; the detector flags a
    worker whose behavior diverges from the REST of the fleet:

    * ``nan`` — the worker's ``loss_nonfinite`` counter moved: it is
      training on NaN/Inf losses right now. Fires immediately (a NaN is
      unambiguous; no fleet comparison needed).
    * ``loss-outlier`` — the worker's recent-median loss exceeds
      ``spike_factor`` × the median of its PEERS' recent medians for
      ``confirm_polls`` consecutive polls. Comparing against peers (not
      history) is what keeps a uniformly-slow/uniformly-hot fleet quiet:
      when every worker's loss rises together the peer median rises with
      it and no one is an outlier. When the polled stats carry ``steps``
      the comparison is PACE-GATED: a worker is only judged once it has
      run ``min_steps`` (its loss ring must mean something), and only
      against peers within 2× of its step count — early training's
      steep loss decay makes rings at different step counts
      incomparable, and a worker merely running BEHIND is the slow-peer
      signal's business (push-stall, phase histograms), not a
      divergence.
    * ``discard-outlier`` — the share of gradients ARRIVING at this
      worker (it is the owner; discards are owner-side) that were
      discarded as stale since the last poll exceeds ``discard_rate``
      while the peer median share stays below half of it: ONE worker's
      shard version is outrunning its peers' pulls (a speed/placement
      outlier), not a fleet-wide knob problem (that is the
      fleet-discard-burn alert's job).

    No-signal discipline: a worker is only judged once it has been seen
    in ``min_polls`` polls (a just-joined/just-restarted worker's first
    samples are warmup, not divergence), loss modes need a finite loss
    median on BOTH sides, and each (worker, mode) pair re-arms only
    after ``rearm_s`` so a persistently-diverged worker emits a beat,
    not a storm. Pure host arithmetic with an injected clock — the test
    matrix drives it deterministically.
    """

    def __init__(
        self,
        emit: Callable[..., Any],
        *,
        spike_factor: float = 3.0,
        discard_rate: float = 0.5,
        min_polls: int = 3,
        confirm_polls: int = 2,
        min_received_delta: int = 4,
        min_steps: int = 8,
        pace_factor: float = 2.0,
        rearm_s: float = 120.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.emit = emit
        self.spike_factor = float(spike_factor)
        self.discard_rate = float(discard_rate)
        self.min_polls = int(min_polls)
        self.confirm_polls = int(confirm_polls)
        self.min_received_delta = int(min_received_delta)
        self.min_steps = int(min_steps)
        self.pace_factor = float(pace_factor)
        self.rearm_s = float(rearm_s)
        self.clock = clock
        self._polls: Dict[int, int] = {}
        self._prev: Dict[int, Dict[str, float]] = {}
        self._loss_strikes: Dict[int, int] = {}
        self._disc_strikes: Dict[int, int] = {}
        self._last_fire: Dict[Tuple[int, str], float] = {}
        self.fired: Dict[str, int] = {}

    def _fire(
        self, worker: int, mode: str, message: str, **fields: Any
    ) -> bool:
        now = self.clock()
        last = self._last_fire.get((worker, mode))
        if last is not None and now - last < self.rearm_s:
            return False
        self._last_fire[(worker, mode)] = now
        self.fired[mode] = self.fired.get(mode, 0) + 1
        self.emit(
            "fleet-divergence",
            message,
            worker=int(worker),
            mode=mode,
            **fields,
        )
        return True

    @staticmethod
    def _median(values: List[float]) -> Optional[float]:
        if not values:
            return None
        s = sorted(values)
        return s[len(s) // 2]

    def observe(self, stats: Dict[int, Dict[str, Any]]) -> List[str]:
        """One fleet poll: ``stats[worker]`` carries whatever that
        worker's ``/metrics`` exposed — ``loss`` (recent median, may be
        None), ``received``/``discarded``/``loss_nonfinite`` counter
        values. Returns the modes fired this poll."""
        fired: List[str] = []
        deltas: Dict[int, Dict[str, float]] = {}
        for w, row in stats.items():
            self._polls[w] = self._polls.get(w, 0) + 1
            prev = self._prev.get(w) or {}
            cur = {
                k: float(row.get(k) or 0.0)
                for k in ("received", "discarded", "loss_nonfinite")
            }
            deltas[w] = {
                k: max(cur[k] - float(prev.get(k) or 0.0), 0.0) for k in cur
            }
            self._prev[w] = cur
            # first poll: the counter's CURRENT value is the delta — a
            # worker whose NaNs all landed before the watch's first
            # scrape of it (fast fault inside the first poll interval)
            # must not have them baselined away forever
            nan_delta = (
                deltas[w]["loss_nonfinite"] if prev
                else cur["loss_nonfinite"]
            )
            if nan_delta > 0:
                if self._fire(
                    w,
                    "nan",
                    f"fleet worker {w} is training on non-finite losses "
                    f"({int(nan_delta)} NaN/Inf step(s) since the last "
                    "poll)",
                    nonfinite=int(nan_delta),
                ):
                    fired.append("nan")

        def judgeable(w: int) -> bool:
            return self._polls.get(w, 0) >= self.min_polls

        finite_loss = {
            w: float(row["loss"])
            for w, row in stats.items()
            if isinstance(row.get("loss"), (int, float))
            and math.isfinite(float(row["loss"]))
        }
        steps_of = {
            w: float(row["steps"])
            for w, row in stats.items()
            if isinstance(row.get("steps"), (int, float))
        }

        def pace_ok(w: int, pw: int) -> bool:
            """Loss rings are only comparable between workers at a
            similar point in training (absent step counts, compare
            unconditionally — the unit-test/bare-ledger shape)."""
            sw, sp = steps_of.get(w), steps_of.get(pw)
            if sw is None or sp is None:
                return True
            hi, lo = max(sw, sp), min(sw, sp)
            return lo > 0 and hi / lo <= self.pace_factor

        for w in sorted(stats):
            loss = finite_loss.get(w)
            if loss is not None and steps_of.get(w) is not None and (
                steps_of[w] < self.min_steps
            ):
                loss = None  # ring too young to mean anything
            peers = [v for pw, v in finite_loss.items()
                     if pw != w and judgeable(pw) and pace_ok(w, pw)]
            peer_median = self._median(peers)
            outlier = (
                judgeable(w)
                and loss is not None
                and peer_median is not None
                and peer_median > 0
                and loss > self.spike_factor * peer_median
            )
            self._loss_strikes[w] = (
                self._loss_strikes.get(w, 0) + 1 if outlier else 0
            )
            if self._loss_strikes[w] >= self.confirm_polls:
                if self._fire(
                    w,
                    "loss-outlier",
                    f"fleet worker {w} loss {loss:.4g} is "
                    f"{loss / peer_median:.1f}x the peer median "
                    f"{peer_median:.4g} ({self._loss_strikes[w]} "
                    "consecutive polls)",
                    loss=loss,
                    peer_median=peer_median,
                ):
                    fired.append("loss-outlier")

        disc_share: Dict[int, float] = {}
        for w, d in deltas.items():
            if d["received"] >= self.min_received_delta:
                disc_share[w] = d["discarded"] / d["received"]
        for w in sorted(stats):
            share = disc_share.get(w)
            peers = [v for pw, v in disc_share.items()
                     if pw != w and judgeable(pw)]
            peer_median = self._median(peers)
            outlier = (
                judgeable(w)
                and share is not None
                and peer_median is not None
                and share >= self.discard_rate
                and peer_median < self.discard_rate / 2
            )
            self._disc_strikes[w] = (
                self._disc_strikes.get(w, 0) + 1 if outlier else 0
            )
            if self._disc_strikes[w] >= self.confirm_polls:
                if self._fire(
                    w,
                    "discard-outlier",
                    f"fleet worker {w}: {share * 100:.0f}% of the "
                    "gradients arriving at it were discarded as stale "
                    f"since the last poll (peer median "
                    f"{peer_median * 100:.0f}%) — its shard version is "
                    "outrunning its peers",
                    discard_share=share,
                    peer_median=peer_median,
                ):
                    fired.append("discard-outlier")
        return fired


# ----------------------------------------------------------------------
# Telemetry facade (what the training loop holds)
# ----------------------------------------------------------------------


class Telemetry:
    """Everything the training loop needs behind one nullable handle.

    The loop guards every call with ``if tel is not None`` — the
    disabled path constructs nothing and calls nothing (asserted by a
    test that makes construction raise). One wall-clock stamp per step
    (``step_boundary``); device sampling, percentile math, anomaly
    checks, and file I/O all happen at eval boundaries.
    """

    def __init__(
        self,
        metrics_dir: Path,
        *,
        trace_steps: Tuple[int, int] = (0, 50),
        anomaly_detection: bool = True,
        clock: Callable[[], float] = time.perf_counter,
        process_index: int = 0,
        detector_kwargs: Optional[Dict[str, Any]] = None,
        alerting: bool = True,
        alert_rules: Optional[List[Any]] = None,
        alert_interval_s: float = 5.0,
        incident_dir: Optional[Path] = None,
        process_name: str = "trainer",
    ):
        self.metrics_dir = Path(metrics_dir)
        self.metrics_dir.mkdir(parents=True, exist_ok=True)
        self.metrics_path = self.metrics_dir / "metrics.jsonl"
        self.trace_path = self.metrics_dir / "trace.json"
        self.clock = clock
        self.trace_steps = (int(trace_steps[0]), int(trace_steps[1]))
        self.registry = MetricsRegistry(clock=clock)
        self.trace = TraceBuffer(clock=clock, pid=int(process_index))
        # host-resource truth (docs/OBSERVABILITY.md "Host resources &
        # the run ledger"): lives INSIDE the facade so the disabled
        # path constructs no sampler and reads no /proc (zero-telemetry
        # contract). Internally rate-limited — the alert ticker and
        # every /metrics scrape share one cached sample, no new thread.
        from .hoststats import ProcessSampler

        self.hoststats = ProcessSampler(clock=clock)
        self.detectors: Optional[AnomalyDetectors] = None
        if anomaly_detection:
            self.detectors = AnomalyDetectors(
                self._emit_anomaly, clock=clock, **(detector_kwargs or {})
            )
        # diagnosis layer (docs/OBSERVABILITY.md "Alerting & incidents"):
        # the trainer's alert engine runs on a rate-limited boundary hook
        # (no extra thread, one comparison per step), and an optional
        # flight recorder dumps an incident bundle when an anomaly
        # detector trips or an alert fires. Both live INSIDE Telemetry:
        # with telemetry disabled neither exists (the zero-calls
        # contract the disabled-path guard test enforces).
        self.recorder = None
        if incident_dir:
            from ..incidents import FlightRecorder

            self.recorder = FlightRecorder(
                incident_dir=Path(incident_dir),
                # "fleet-worker-K" for trainer-fleet workers: a fleet-wide
                # incidents dir gets bundles whose flight files and
                # postmortem timeline tracks name the worker that wrote
                # them, not N identical "trainer" rows
                process_name=str(process_name),
                clock=clock,
            )
        self.alerts = None
        self.alert_interval_s = float(alert_interval_s)
        self._last_alert_eval: Optional[float] = None
        if alerting:
            from ..alerting import AlertEngine, default_training_rules

            self.alerts = AlertEngine(
                alert_rules
                if alert_rules is not None
                else default_training_rules(),
                clock=clock,
                sink_path=self.metrics_dir / "alerts.jsonl",
                on_firing=(
                    self.recorder.alert_hook()
                    if self.recorder is not None
                    else None
                ),
                source="trainer",
            )
        if self.recorder is not None:
            self.recorder.attach(
                trace=self.trace,
                alerts_fn=(
                    self.alerts.states if self.alerts is not None else None
                ),
            )
        # the boundary hook alone cannot page on a WEDGED loop: a hung
        # step never reaches the next boundary, and every boundary that
        # does run has just moved the steps counter — so the
        # training-stalled AbsenceRule would be unreachable exactly in
        # the failure mode it exists for. A slow daemon ticker keeps
        # evaluating on wall time while the loop is stuck (the firing
        # lands in the log + alerts.jsonl BEFORE the watchdog's
        # os._exit); it shares the boundary hook's rate limit, so it
        # adds nothing while the loop is healthy and fake-clock tests
        # stay deterministic (an unadvanced clock rate-limits it out).
        self._alert_stop = threading.Event()
        self._alert_ticker: Optional[threading.Thread] = None
        install_compile_hook()
        self._compiles_at_start = compile_count()
        # hot-path instruments, resolved once
        self._step_hist = self.registry.histogram(
            "step_seconds", buckets=STEP_SECONDS_BUCKETS
        )
        self._words = self.registry.counter("words")
        self._steps = self.registry.counter("steps")
        self._anomalies = self.registry.counter("anomalies")
        # per-step loss streaming (trainer-fleet convergence watch):
        # created lazily on the first step_boundary(loss=...) so surfaces
        # that never stream a loss keep their exposition unchanged. The
        # small ring makes snapshot p50 a RECENT median — the fleet
        # divergence detector's per-worker signal.
        self._loss_hist: Optional[_Histogram] = None
        self._loss_nonfinite: Optional[_Counter] = None
        self._rows: List[Dict[str, Any]] = []
        self._rows_lock = threading.Lock()
        self._last_boundary: Optional[float] = None
        self._t0 = clock()
        self.flops_per_step: Optional[float] = None
        self._flops_probed = False
        self._peak: Optional[float] = None
        self._peak_kind: Optional[str] = None
        self._handle: Optional[IO[str]] = None
        self._finalized = False
        # ticker starts LAST: it snapshots the registry, so every
        # instrument above must exist before its first pass
        if self.alerts is not None:
            self._alert_ticker = threading.Thread(
                target=self._alert_tick_loop,
                name="telemetry-alerts",
                daemon=True,
            )
            self._alert_ticker.start()

    def _alert_tick_loop(self) -> None:
        import logging

        logger = logging.getLogger("spacy_ray_tpu.training")
        while not self._alert_stop.wait(self.alert_interval_s):
            try:
                self.maybe_evaluate_alerts()
            except Exception:
                # survive anything, but LOUDLY: a silently-dead ticker
                # means the stall rule — whose whole purpose is the
                # wedged-loop case only this thread can catch — is gone
                # with zero operator-visible evidence
                logger.exception("telemetry alert ticker pass failed")

    # -- emit plumbing -------------------------------------------------
    def _emit_anomaly(self, event: str, message: str, **fields: Any) -> None:
        from .resilience import log_event

        log_event(event, message, **fields)
        self._anomalies.inc()
        with self._rows_lock:
            self._rows.append(
                {"kind": "anomaly", "anomaly": event, "message": message, **fields}
            )
        self.trace.add_instant(event, args={"message": message})
        if self.recorder is not None:
            # retroactive forensics: a detector firing is exactly the
            # moment the last N seconds are worth keeping (rate-limited
            # inside the recorder — a NaN storm writes ONE bundle).
            # worker/mode ride into incident.json so a fleet-divergence
            # bundle NAMES the diverging worker, not just the event.
            self.recorder.trip(
                f"anomaly-{event}",
                message,
                **{
                    k: fields[k]
                    for k in ("step", "worker", "mode")
                    if fields.get(k) is not None
                },
            )

    def maybe_evaluate_alerts(self, *, force: bool = False) -> None:
        """Rate-limited alert pass: at most one rule evaluation per
        ``alert_interval_s`` no matter how fast steps complete (the hot
        path pays one clock compare), plus a forced pass at eval
        boundaries. The background ticker calls this too — its passes
        share the same rate limit, and it is what keeps the stall rule
        evaluating when the loop stops reaching boundaries at all. Also
        feeds the flight-recorder snapshot ring at the same cadence."""
        if self.alerts is None and self.recorder is None:
            return
        now = self.clock()
        if (
            not force
            and self._last_alert_eval is not None
            and now - self._last_alert_eval < self.alert_interval_s
        ):
            return
        self._last_alert_eval = now
        snap = self.registry.snapshot()
        # host truth rides the same cadence: the leak/fd rules read
        # dotted paths under "process", and the flight-recorder ring
        # keeps RSS history for postmortems
        snap["process"] = self.hoststats.sample()
        if self.recorder is not None:
            self.recorder.record(snap)
        if self.alerts is not None:
            self.alerts.evaluate(snap)

    def _append_row(self, row: Dict[str, Any]) -> None:
        with self._rows_lock:
            self._rows.append(row)

    def append_row(self, row: Dict[str, Any]) -> None:
        """Buffer one extra ``metrics.jsonl`` row (flushed with the
        regular eval/finalize cadence) — the trainer-fleet worker's
        ``kind: "fleet"`` exit row rides this."""
        self._append_row(dict(row))

    def _flush_rows(self) -> None:
        with self._rows_lock:
            rows, self._rows = self._rows, []
        if not rows:
            return
        if self._handle is None:
            self._handle = open(self.metrics_path, "a", encoding="utf8")
        for row in rows:
            # sanitize_json: a NaN loss row must stay VALID json (the NaN
            # anomaly is exactly when these files get read by tooling)
            self._handle.write(
                json.dumps(sanitize_json(row), default=float) + "\n"
            )
        self._handle.flush()

    # -- loop hooks ----------------------------------------------------
    def loop_start(self) -> None:
        """Arm the per-step stamp right before the first iteration."""
        self._last_boundary = self.clock()
        self.trace.set_recording(self.trace_steps[0] <= 0 < self.trace_steps[1])

    def step_boundary(
        self,
        *,
        step: int,
        epoch: int,
        n_words: int,
        steps_run: int,
        inner_steps: int = 1,
        words_each: Optional[List[int]] = None,
        loss: Optional[float] = None,
    ) -> None:
        """THE one hot-path hook: a single clock stamp, one histogram
        observation, one buffered row, and the trace-window gate.

        ``loss`` (the trainer-fleet path): this step's scalar loss —
        finite values feed the ``loss`` histogram's recent-median ring
        (the cross-worker convergence-watch signal) and land on the step
        row; non-finite values are COUNTED (``loss_nonfinite``) instead
        of observed, so one NaN cannot poison the median the fleet
        comparison reads. Applies to the last inner step when
        ``inner_steps > 1``.

        ``inner_steps > 1`` (a ``steps_per_dispatch`` dispatch): the one
        wall-clock window fans out into per-inner-step observations of
        ``elapsed / k`` each — histograms, rows, spans, and the step-time
        regression detector still see EVERY step (the device executed k
        steps; only the host-side boundary is coarser). ``step`` is the
        LAST inner step's index; ``words_each`` carries per-step word
        counts (falls back to an even split)."""
        now = self.clock()
        prev = self._last_boundary
        self._last_boundary = now
        k = max(int(inner_steps), 1)
        self._steps.inc(k)
        self._words.inc(n_words)
        if prev is not None:
            total = now - prev
            dur = total / k
            for i in range(k):
                step_i = step - k + 1 + i
                words_i = (
                    int(words_each[i]) if words_each is not None
                    else n_words // k
                )
                self._step_hist.observe(dur)
                args: Dict[str, Any] = {"step": step_i, "words": words_i}
                row: Dict[str, Any] = {
                    "kind": "step",
                    "step": step_i,
                    "epoch": epoch,
                    "t": round(prev + (i + 1) * dur - self._t0, 6),
                    "step_seconds": round(dur, 6),
                    "words": words_i,
                }
                if k > 1:
                    args["dispatch_k"] = k
                    row["dispatch_k"] = k
                if loss is not None and i == k - 1:
                    loss_f = float(loss)
                    row["loss"] = loss_f
                    if math.isfinite(loss_f):
                        if self._loss_hist is None:
                            self._loss_hist = self.registry.histogram(
                                "loss", max_samples=64
                            )
                        self._loss_hist.observe(loss_f)
                    else:
                        if self._loss_nonfinite is None:
                            self._loss_nonfinite = self.registry.counter(
                                "loss_nonfinite"
                            )
                        self._loss_nonfinite.inc()
                self.trace.add_span(
                    "step", prev + i * dur, dur, cat="step", args=args
                )
                self._append_row(row)
                if self.detectors is not None:
                    self.detectors.check_step_time(step_i, dur)
        self.maybe_evaluate_alerts()
        # gate the span firehose to the configured step window (rare
        # events — eval/checkpoint/anomaly — bypass with force=True).
        # Ordering matters: the step span ABOVE was gated by the flag set
        # at the PREVIOUS boundary — i.e. by the completed step's own
        # index — so [start, stop) captures exactly step indices
        # start..stop-1; this set_recording gates the NEXT step (index
        # == the incremented steps_run).
        start, stop = self.trace_steps
        self.trace.set_recording(start <= steps_run < stop)

    def eval_boundary(
        self,
        *,
        step: int,
        epoch: int,
        steps_run: int,
        losses: Dict[str, float],
        score: Optional[float],
        eval_seconds: float,
        input_pipeline: Optional[Dict[str, Any]] = None,
        flops_fn: Optional[Callable[[], Optional[float]]] = None,
        wps: Optional[float] = None,
    ) -> Dict[str, Any]:
        """Sample gauges, run detectors, flush rows; returns the snapshot
        the logger embeds in its row."""
        device = sample_device_telemetry()
        reg = self.registry
        if device["hbm_peak_bytes"] is not None:
            reg.gauge("hbm_peak_bytes").set(device["hbm_peak_bytes"])
        if device["hbm_bytes_in_use"] is not None:
            reg.gauge("hbm_bytes_in_use").set(device["hbm_bytes_in_use"])
        if device["live_buffers"] is not None:
            reg.gauge("live_buffers").set(device["live_buffers"])
        compiles = device["compile_count"] - self._compiles_at_start
        reg.gauge("compile_count").set(compiles)
        # one-shot cost model: lowering is a trace (no compile), but not
        # free — probe on the first eval only
        if not self._flops_probed and flops_fn is not None:
            self._flops_probed = True
            try:
                self.flops_per_step = flops_fn()
            except Exception:
                self.flops_per_step = None
            self._peak, self._peak_kind = device_peak_flops()
        hist = self._step_hist
        p50 = hist.percentile(0.5)
        p95 = hist.percentile(0.95)
        mfu = None
        if self.flops_per_step and self._peak and p50:
            try:
                import jax

                n_chips = len(jax.devices())
            except Exception:
                n_chips = 1
            # e2e MFU: the denominator is wall step time (host work
            # included) — chip utilization of the whole pipeline, same
            # convention as bench.py's e2e records
            mfu = self.flops_per_step / p50 / (self._peak * n_chips)
        loss_total = sum(float(v) for v in losses.values()) if losses else None
        if self.detectors is not None:
            if loss_total is not None:
                self.detectors.check_loss(step, loss_total)
            if score is not None and _is_bad(float(score)):
                self.detectors._fire(
                    "nan-score",
                    f"non-finite eval score {score!r} at step {step}",
                    step=step,
                )
            self.detectors.check_compiles(steps_run, compiles)
        row: Dict[str, Any] = {
            "kind": "eval",
            "step": step,
            "epoch": epoch,
            "t": round(self.clock() - self._t0, 6),
            "loss_total": loss_total,
            "losses": dict(losses),
            "score": score,
            "eval_seconds": round(eval_seconds, 6),
            "wps": wps,
            "step_seconds_p50": p50,
            "step_seconds_p95": p95,
            "hbm_bytes_in_use": device["hbm_bytes_in_use"],
            "hbm_peak_bytes": device["hbm_peak_bytes"],
            "hbm_bytes_limit": device["hbm_bytes_limit"],
            "live_buffers": device["live_buffers"],
            "compile_count": compiles,
            "flops_per_step": self.flops_per_step,
            "mfu": round(mfu, 5) if mfu is not None else None,
            "platform": device["platform"],
        }
        if input_pipeline is not None:
            row["input_pipeline"] = input_pipeline
        # host truth in the run record: the report's host-resource
        # section and the run ledger's run-dir ingest both read this
        row["process"] = self.hoststats.sample()
        self._append_row(row)
        self._flush_rows()
        self.maybe_evaluate_alerts(force=True)
        snapshot = {
            "step_seconds_p50": p50,
            "step_seconds_p95": p95,
            "hbm_peak_bytes": device["hbm_peak_bytes"],
            "live_buffers": device["live_buffers"],
            "compile_count": compiles,
            "mfu": row["mfu"],
            "trace_events": len(self.trace),
        }
        return snapshot

    def rearm_step_clock(self) -> None:
        """Re-stamp the step boundary after off-step work (eval +
        checkpoint save) — without this, the step AFTER every eval would
        absorb the whole eval duration into its measured step time,
        skewing p95 and firing a spurious step-time regression at every
        eval boundary."""
        self._last_boundary = self.clock()

    # -- flush / teardown ---------------------------------------------
    def emergency_flush(self) -> None:
        """Best-effort full flush for hard-exit paths (the watchdog fires
        ``os._exit`` — no finally blocks will run after this)."""
        try:
            self._flush_rows()
        except Exception:
            pass
        try:
            self.trace.flush(self.trace_path)
        except Exception:
            pass

    def finalize(self) -> None:
        if self._finalized:
            return
        self._finalized = True
        self._alert_stop.set()
        if self._alert_ticker is not None:
            self._alert_ticker.join(timeout=2.0)
            self._alert_ticker = None
        self._flush_rows()
        self.trace.flush(self.trace_path)
        if self._handle is not None:
            self._handle.close()
            self._handle = None


# ----------------------------------------------------------------------
# Offline summary (`telemetry summarize metrics.jsonl`)
# ----------------------------------------------------------------------


def _fmt_bytes(n: Optional[float]) -> str:
    if n is None:
        return "-"
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if n < 1024 or unit == "TiB":
            return f"{n:.1f}{unit}"
        n /= 1024
    return f"{n:.1f}TiB"


def _fmt_ms(v: Any) -> str:
    return f"{float(v) * 1e3:.1f}ms" if isinstance(v, (int, float)) else "-"


def _summarize_serving_rows(servings: List[Dict[str, Any]]) -> List[str]:
    """The serving section of ``telemetry summarize``: built from the
    LAST ``kind: "serving"`` row (each row is a cumulative snapshot, so
    the newest supersedes the rest) — request/reject totals, the SLO
    percentiles (lifetime ring AND sliding window), and per-generation
    rows when the snapshot carries a ``by_generation`` split."""
    last = servings[-1]
    counters = last.get("counters") or {}
    lines: List[str] = []
    reqs = int(counters.get("requests") or 0)
    rejects = {
        k: int(counters.get(k) or 0)
        for k in (
            "rejected_queue_full", "rejected_draining",
            "deadline_exceeded", "errors",
        )
        if counters.get(k)
    }
    line = (
        f"serving: requests {reqs:,}  docs {int(counters.get('docs') or 0):,}"
        f"  batches {int(counters.get('batches') or 0):,}"
    )
    if counters.get("swaps"):
        line += f"  swaps {int(counters['swaps'])}"
    gen = last.get("generation")
    if gen is not None:
        line += f"  generation {gen}"
    lines.append(line)
    if rejects:
        lines.append(
            "  rejects: "
            + "  ".join(f"{k} {v}" for k, v in sorted(rejects.items()))
        )
    else:
        lines.append("  rejects: none")
    slo = last.get("slo") or {}
    if slo:
        lines.append(
            "  latency (lifetime ring): "
            f"p50 {_fmt_ms(slo.get('request_latency_p50'))}  "
            f"p95 {_fmt_ms(slo.get('request_latency_p95'))}  "
            f"p99 {_fmt_ms(slo.get('request_latency_p99'))}"
        )
    win = last.get("slo_window")
    if isinstance(win, dict):
        lines.append(
            f"  latency (last {float(win.get('window_s') or 0):.0f}s, "
            f"{int(win.get('samples') or 0)} sample(s)): "
            f"p50 {_fmt_ms(win.get('request_latency_p50'))}  "
            f"p99 {_fmt_ms(win.get('request_latency_p99'))}"
        )
    by_gen = last.get("by_generation")
    if isinstance(by_gen, dict) and by_gen:
        lines.append("  by generation:")
        for key in sorted(by_gen):
            sub = by_gen[key] or {}
            sub_counters = sub.get("counters") or {}
            sub_win = sub.get("slo_window") or {}
            lines.append(
                f"    gen {key:>6s}: requests "
                f"{int(sub_counters.get('requests') or 0):,}  window p99 "
                f"{_fmt_ms(sub_win.get('request_latency_p99'))}"
            )
    return lines


def _summarize_fleet_rows(fleet_rows: List[Dict[str, Any]]) -> List[str]:
    """The trainer-fleet section of ``telemetry summarize``: built from
    the ``kind: "fleet"`` exit row each fleet worker appends at finalize
    (the newest per worker wins) — per-worker version/counters, the
    phase-share split, and the dynamics-histogram digest (staleness,
    quorum wait, apply)."""
    by_worker: Dict[int, Dict[str, Any]] = {}
    for row in fleet_rows:
        w = row.get("worker")
        if isinstance(w, int):
            by_worker[w] = row
    if not by_worker:
        return []
    any_row = next(iter(by_worker.values()))
    lines = [
        f"trainer fleet: {any_row.get('n_workers')} worker(s)  "
        f"quorum {any_row.get('quorum')}  "
        f"max_staleness {any_row.get('max_staleness')}"
    ]
    for w in sorted(by_worker):
        row = by_worker[w]
        c = row.get("counters") or {}
        hists = row.get("histograms") or {}
        phases = row.get("phases") or {}
        total = sum(float(v) for v in phases.values()) or 1.0
        share = "  ".join(
            f"{p} {100 * float(phases.get(p, 0.0)) / total:.0f}%"
            for p in ("data", "pull", "grad", "push", "apply_wait")
            if p in phases
        )
        lines.append(
            f"  worker {w}: version {row.get('version')}  "
            f"pushed {int(c.get('grad_pushed') or 0)}  "
            f"received {int(c.get('grad_received') or 0)}  "
            f"applied {int(c.get('grad_applied') or 0)}  "
            f"discarded {int(c.get('grad_discarded') or 0)}  "
            f"push-failed {int(c.get('push_failed') or 0)}"
        )
        if share:
            lines.append(f"    phases: {share}")
        st = hists.get("staleness") or {}
        if st.get("count"):
            buckets = st.get("buckets") or []
            bl = "  ".join(
                f"<={int(le)}: {int(cum)}" for le, cum in buckets
                if cum
            )
            lines.append(
                f"    staleness (accepted pushes): n={st['count']}  "
                f"max {st.get('max')}  {bl}"
            )
        qw, ap = hists.get("quorum_wait_seconds") or {}, hists.get(
            "apply_seconds"
        ) or {}
        if qw.get("count") or ap.get("count"):
            lines.append(
                f"    quorum-wait p50 {_fmt_ms(qw.get('p50'))} "
                f"p99 {_fmt_ms(qw.get('p99'))}  "
                f"apply p50 {_fmt_ms(ap.get('p50'))} "
                f"p99 {_fmt_ms(ap.get('p99'))}"
            )
    return lines


def _summarize_run_dir(run_dir: Path) -> str:
    """``telemetry summarize <run-dir>``: a trainer-fleet run directory
    (``fleet-worker-*.json`` ledgers + ``metrics/fleet-worker-*/
    metrics.jsonl``) gets a fleet digest; a plain run directory holding
    one ``metrics.jsonl`` falls through to the file summary. Discovery
    is :func:`~.report.load_run` — the ONE definition of the run-dir
    layout, shared with ``telemetry report`` and the bench harness."""
    from .report import load_run

    run_dir = Path(run_dir)
    run = load_run(run_dir)  # ValueError when not a run directory
    workers = run["workers"]
    ledgers = {
        w: e["ledger"] for w, e in workers.items() if "ledger" in e
    }
    metrics_paths = [
        workers[w]["metrics_path"]
        for w in sorted(workers)
        if workers[w].get("metrics_path")
    ]
    if not ledgers and len(metrics_paths) == 1:
        # a plain single-process run: the file summary IS the digest
        return summarize_metrics(metrics_paths[0])
    lines: List[str] = [f"telemetry summary (fleet run dir): {run_dir}"]
    if ledgers:
        rows = [ledgers[w] for w in sorted(ledgers)]
        total_words = sum(int(r.get("words_seen") or 0) for r in rows)
        slowest = max(float(r.get("seconds") or 0.0) for r in rows)
        lines.append(
            f"workers: {len(rows)}  total words {total_words:,}  "
            f"slowest worker {slowest:.1f}s"
            + (
                f"  ({total_words / slowest:,.0f} words/s fleet-wide)"
                if slowest > 0
                else ""
            )
        )
        for r in rows:
            c = r.get("counters") or {}
            phases = r.get("phases") or {}
            total = sum(float(v) for v in phases.values()) or 1.0
            wait_pct = 100 * float(phases.get("apply_wait") or 0.0) / total
            lines.append(
                f"  worker {r.get('worker')}: steps {r.get('steps')}  "
                f"words {int(r.get('words_seen') or 0):,}  "
                f"version {r.get('version')}  "
                f"discarded {int(c.get('grad_discarded') or 0)}  "
                f"push-failed {int(c.get('push_failed') or 0)}  "
                f"apply-wait {wait_pct:.0f}%"
                + ("  [interrupted]" if r.get("interrupted") else "")
            )
    for mp in metrics_paths:
        try:
            lines.append("")
            lines.append(summarize_metrics(mp))
        except (OSError, ValueError) as e:
            lines.append(f"  ({Path(mp).parent.name}: {e})")
    return "\n".join(lines)


def summarize_metrics(path: Path) -> str:
    """Digest a ``metrics.jsonl``: training rows (per-stage time
    breakdown, step-time percentiles, device gauges), serving rows
    (``kind: "serving"`` snapshots: SLO window, rejects, by-generation
    split), trainer-fleet rows (``kind: "fleet"`` exit rows: counters,
    phase share, staleness/quorum-wait/apply digest), plus the anomaly
    digest. Given a DIRECTORY, digests a fleet run dir (per-worker
    ledgers + metrics files) or its single ``metrics.jsonl``. Pure
    file-in/text-out so the CLI subcommand and the round-trip test share
    one implementation.

    Raises ValueError when the target holds no telemetry rows (a wrong
    path must not print an empty-but-plausible report)."""
    path = Path(path)
    if path.is_dir():
        return _summarize_run_dir(path)
    steps: List[Dict[str, Any]] = []
    evals: List[Dict[str, Any]] = []
    anomalies: List[Dict[str, Any]] = []
    servings: List[Dict[str, Any]] = []
    fleet_rows: List[Dict[str, Any]] = []
    with open(path, encoding="utf8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError:
                continue  # torn concurrent write: skip, don't abort
            kind = row.get("kind")
            if kind == "step":
                steps.append(row)
            elif kind == "eval":
                evals.append(row)
            elif kind == "anomaly":
                anomalies.append(row)
            elif kind == "serving":
                servings.append(row)
            elif kind == "fleet":
                fleet_rows.append(row)
    if (
        not steps and not evals and not anomalies and not servings
        and not fleet_rows
    ):
        raise ValueError(f"{path} contains no telemetry rows")

    lines: List[str] = [f"telemetry summary: {path}"]
    if servings:
        lines.extend(_summarize_serving_rows(servings))
    if fleet_rows:
        lines.extend(_summarize_fleet_rows(fleet_rows))
    if steps:
        durs = sorted(float(s["step_seconds"]) for s in steps)
        words = sum(int(s.get("words") or 0) for s in steps)
        total = sum(durs)
        line = (
            f"steps: {len(durs)}  words: {words:,}  "
            f"step-time p50 {_nearest_rank(durs, 0.5) * 1e3:.1f}ms  "
            f"p95 {_nearest_rank(durs, 0.95) * 1e3:.1f}ms  "
            f"max {durs[-1] * 1e3:.1f}ms"
        )
        if total > 0:
            line += f"  ({words / total:,.0f} words/s overall)"
        lines.append(line)
    if evals:
        last = evals[-1]
        stages = (last.get("input_pipeline") or {}).get("stage_seconds") or {}
        if stages:
            stage_total = sum(stages.values()) or 1.0
            lines.append("host input-pipeline breakdown (cumulative seconds):")
            for stage, seconds in stages.items():
                lines.append(
                    f"  {stage:12s} {seconds:10.3f}s  "
                    f"{100 * seconds / stage_total:5.1f}%"
                )
        lines.append(
            f"device: platform={last.get('platform')}  "
            f"hbm_peak={_fmt_bytes(last.get('hbm_peak_bytes'))}  "
            f"live_buffers={last.get('live_buffers')}  "
            f"compiles={last.get('compile_count')}"
        )
        if isinstance(last.get("mfu"), (int, float)):
            lines.append(f"mfu (e2e, p50 step): {last['mfu']:.4f}")
        # sanitize_json stores a NaN score as the string "nan" — keep only
        # finite numerics, or the digest of a NaN run (the headline use
        # case) would crash on the format specifier
        scores = [
            e.get("score")
            for e in evals
            if isinstance(e.get("score"), (int, float))
            and math.isfinite(float(e["score"]))
        ]
        if scores:
            lines.append(
                f"evals: {len(evals)}  last score {scores[-1]:.4f}  "
                f"best {max(scores):.4f}"
            )
    by_kind: Dict[str, List[Dict[str, Any]]] = {}
    for a in anomalies:
        by_kind.setdefault(str(a.get("anomaly")), []).append(a)
    if by_kind:
        lines.append(f"anomalies: {len(anomalies)}")
        for name in sorted(by_kind):
            rows = by_kind[name]
            anom_steps = [r.get("step") for r in rows if r.get("step") is not None]
            where = (
                f" (steps {min(anom_steps)}..{max(anom_steps)})"
                if anom_steps
                else ""
            )
            lines.append(f"  {name:24s} x{len(rows)}{where}")
    else:
        lines.append("anomalies: none")
    return "\n".join(lines)
