"""Batchers + size schedules: registered ``@batchers`` / ``@schedules``.

Capability parity with the batching surface the reference's loop consumes
(reference worker.py:170-175 ``create_train_batches`` over the config's
``[training.batcher]``, typically ``spacy.batch_by_words.v1`` with a
``compounding.v1`` size schedule).

TPU addition: **shape bucketing**. Under jit, every distinct (B, T) pair is a
recompile, so batches are padded to bucketed sequence lengths (powers-of-two
progression) and padded up to fixed batch sizes per bucket — bounded compile
count, static shapes (SURVEY.md §7 hard part "Ragged/variable-length
batching under jit").
"""

from __future__ import annotations

import itertools
import random
from typing import Callable, Iterable, Iterator, List, Optional, Sequence

from ..registry import registry
from ..pipeline.doc import Example

SizeSchedule = Iterator[float]


@registry.schedules("compounding.v1")
def compounding(start: float, stop: float, compound: float) -> Iterable[float]:
    def gen():
        curr = float(start)
        while True:
            yield curr
            curr = min(curr * compound, stop) if compound >= 1.0 else max(curr * compound, stop)

    return gen()


@registry.schedules("constant.v1")
def constant(rate: float) -> Iterable[float]:
    return itertools.repeat(float(rate))


def _as_schedule(size) -> Iterator[float]:
    if isinstance(size, (int, float)):
        return itertools.repeat(float(size))
    return iter(size)


class _Batcher:
    def __init__(self, fn: Callable[[Iterable[Example]], Iterator[List[Example]]]):
        self._fn = fn

    def __call__(self, examples: Iterable[Example]) -> Iterator[List[Example]]:
        return self._fn(examples)


@registry.batchers("spacy.batch_by_words.v1")
def batch_by_words(
    size,
    tolerance: float = 0.2,
    discard_oversize: bool = False,
    get_length: Optional[Callable] = None,
) -> _Batcher:
    """Group examples into batches of ~`size` total words (size may be a
    schedule). Oversize docs become singleton batches unless discarded."""

    def fn(examples: Iterable[Example]) -> Iterator[List[Example]]:
        sched = _as_schedule(size)
        target = next(sched)
        batch: List[Example] = []
        count = 0
        for eg in examples:
            n = len(eg) if get_length is None else get_length(eg)
            if n > target * (1.0 + tolerance):
                if discard_oversize:
                    continue
                if batch:
                    yield batch
                    target = next(sched)
                    batch, count = [], 0
                yield [eg]
                target = next(sched)
                continue
            if count + n > target * (1.0 + tolerance) and batch:
                yield batch
                target = next(sched)
                batch, count = [], 0
            batch.append(eg)
            count += n
        if batch:
            yield batch

    return _Batcher(fn)


@registry.batchers("spacy.batch_by_sequence.v1")
def batch_by_sequence(size, get_length: Optional[Callable] = None) -> _Batcher:
    def fn(examples: Iterable[Example]) -> Iterator[List[Example]]:
        sched = _as_schedule(size)
        target = int(next(sched))
        batch: List[Example] = []
        for eg in examples:
            batch.append(eg)
            if len(batch) >= target:
                yield batch
                batch = []
                target = int(next(sched))
        if batch:
            yield batch

    return _Batcher(fn)


@registry.batchers("spacy.batch_by_padded.v1")
def batch_by_padded(
    size, buffer: int = 256, discard_oversize: bool = False, get_length=None
) -> _Batcher:
    """Batch by padded size (batch_len * max_len), sorting within a buffer to
    reduce padding waste."""

    def fn(examples: Iterable[Example]) -> Iterator[List[Example]]:
        sched = _as_schedule(size)
        it = iter(examples)
        while True:
            buf = list(itertools.islice(it, buffer))
            if not buf:
                return
            buf.sort(key=len)
            target = next(sched)
            batch: List[Example] = []
            max_len = 0
            for eg in buf:
                n = len(eg)
                new_max = max(max_len, n)
                if batch and new_max * (len(batch) + 1) > target:
                    yield batch
                    target = next(sched)
                    batch, max_len = [], 0
                    new_max = n
                if n > target:
                    if not discard_oversize:
                        yield [eg]
                        target = next(sched)
                    continue
                batch.append(eg)
                max_len = new_max
            if batch:
                yield batch

    return _Batcher(fn)


# ----------------------------------------------------------------------
# Shape bucketing (TPU-specific, applied after the config batcher)
# ----------------------------------------------------------------------

DEFAULT_LENGTH_BUCKETS = (16, 32, 64, 128, 256, 512)


def bucket_length(n: int, buckets: Sequence[int] = DEFAULT_LENGTH_BUCKETS) -> int:
    """Round a sequence length up to a bucket. Lengths beyond the largest
    bucket round up to the next multiple of it (never truncate — silently
    dropping tokens would corrupt losses and scores)."""
    for b in buckets:
        if n <= b:
            return b
    top = buckets[-1]
    return ((n + top - 1) // top) * top


def bucket_batch_size(n: int) -> int:
    """Round batch size up to a small set of sizes to bound recompiles."""
    for b in (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024):
        if n <= b:
            return b
    return ((n + 255) // 256) * 256


def shard_stream(examples: Iterable[Example], rank: int, world: int) -> Iterator[Example]:
    """Deterministic round-robin shard of the example stream by rank —
    the per-host data sharding the reference lacks (SURVEY.md §2.4
    "No data sharding by rank")."""
    for i, eg in enumerate(examples):
        if i % world == rank:
            yield eg
