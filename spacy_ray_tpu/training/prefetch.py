"""Background batch prefetch: overlap host collation + host->device transfer
with the running device step.

The compiled step dispatches asynchronously, but ``device_put`` of the next
batch only starts once the host loop reaches it — on a remote-attached
device (or any setup where transfer latency rivals step time) the device
idles between steps. ``prefetch_iter`` runs the producer (collate +
place_batch) on a daemon thread with a small bounded queue so batch N+1 is
already on device when step N retires.

Single-process only: the producer performs no collectives. Multi-host
training keeps the inline path — its per-group allgathers (shape/termination
sync, training/loop.py) must stay ordered with the update collectives on one
thread per process, or two hosts can interleave collective launches
differently and deadlock.

The same one-thread rule governs the collation worker pool layered under
this (training/collate_pool.py): pool workers do pure host collation only;
``device_put`` and every collective run on the single thread that consumes
the pool — which under prefetch is THIS producer thread.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator, TypeVar

T = TypeVar("T")

_DONE = object()


class _Raised:
    def __init__(self, err: BaseException):
        self.err = err


def prefetch_iter(it: Iterator[T], size: int = 2) -> Iterator[T]:
    """Drain ``it`` on a background thread, at most ``size`` items ahead.

    Exceptions in the producer re-raise at the consumer's next pull; the
    thread is a daemon so an abandoned iterator cannot hang interpreter
    exit. ``size < 2`` returns ``it`` unchanged (nothing to overlap).

    Closing the returned generator (or dropping it — early stop, exceptions)
    stops the producer: each ``put`` polls a stop event, so the thread exits
    and the buffered items (which may pin device memory) are dropped rather
    than sitting in a blocked ``q.put`` for the process lifetime.
    """
    if size < 2:
        return it
    return _Prefetcher(it, size)


class _Prefetcher:
    """Iterator wrapper around the producer thread. A class (not a consumer
    generator) so ``close()`` releases the producer even when the iterator
    was never advanced — a generator's ``finally`` only runs once its body
    has started."""

    def __init__(self, it: Iterator, size: int):
        self._q: "queue.Queue" = queue.Queue(maxsize=size)
        self._stopped = threading.Event()
        self._it = it
        self._thread = threading.Thread(
            target=self._produce, daemon=True, name="batch-prefetch"
        )
        self._thread.start()

    def _put(self, item) -> bool:
        while not self._stopped.is_set():
            try:
                self._q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def _produce(self) -> None:
        try:
            for item in self._it:
                if not self._put(item):
                    return
        except BaseException as e:  # noqa: BLE001 — re-raised on the consumer
            self._put(_Raised(e))
            return
        self._put(_DONE)

    def __iter__(self) -> "_Prefetcher":
        return self

    def __next__(self):
        if self._stopped.is_set():
            raise StopIteration
        item = self._q.get()
        if item is _DONE:
            self.close()
            raise StopIteration
        if isinstance(item, _Raised):
            self.close()
            raise item.err
        return item

    def close(self) -> None:
        """Stop the producer and drop any buffered (possibly on-device)
        batches. Join BEFORE draining — a producer mid-put could otherwise
        slip one item into the just-drained queue and keep it referenced
        after close. Once the producer thread is confirmed dead, close the
        underlying iterator too: a generator source may hold resources in
        its ``finally`` (e.g. the collation worker pool — see
        training/collate_pool.py) that must not wait for GC. Idempotent."""
        self._stopped.set()
        self._thread.join(timeout=5.0)
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        if not self._thread.is_alive():
            close = getattr(self._it, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:
                    pass  # releasing resources is best-effort on teardown

    def __del__(self):  # abandoned without close(): still release the thread
        self.close()
