"""Training loggers: the pluggable ``[training.logger]`` registry slot.

Capability parity with the reference's console logger plugin (reference
loggers.py:8-66, registered ``spacy-ray.ConsoleLogger.v1`` via
setup.cfg:40-41; SURVEY.md §5.5). Same protocol: the factory returns a
setup function taking the pipeline and returning ``(log_step, finalize)``;
``log_step(info_or_None)`` is called every step (None = no new row).

TPU additions (SURVEY.md §5.5 "add words/sec/chip and step-time metrics as
first-class"): WPS and WPS/chip columns computed from the loop's counters.
"""

from __future__ import annotations

import sys
from typing import Any, Callable, Dict, IO, List, Optional, Tuple

from ..registry import registry


def _fmt(value: float, width: int = 8, places: int = 2) -> str:
    return f"{value:{width}.{places}f}"


@registry.loggers("spacy-ray.ConsoleLogger.v1")
@registry.loggers("spacy_ray_tpu.ConsoleLogger.v1")
def console_logger(progress_bar: bool = False):
    def setup(nlp, stdout: IO = sys.stdout, stderr: IO = sys.stderr):
        pipe_names = [
            n for n in nlp.head_names() if nlp.components[n].trainable
        ]
        score_keys = list(nlp.config.get("training", {}).get("score_weights", {}) or {})
        loss_cols = [f"Loss {n}" for n in pipe_names]
        score_cols = score_keys
        header = ["E", "#", "W"] + loss_cols + score_cols + ["WPS", "EvalS", "Score"]
        widths = [max(len(h), 8) for h in header]
        stdout.write(" ".join(h.rjust(w) for h, w in zip(header, widths)) + "\n")
        stdout.write(" ".join("-" * w for w in widths) + "\n")

        def log_step(info: Optional[Dict[str, Any]]) -> None:
            if info is None:
                return
            row: List[str] = [
                str(info.get("epoch", 0)).rjust(widths[0]),
                str(info.get("step", 0)).rjust(widths[1]),
                str(info.get("words", 0)).rjust(widths[2]),
            ]
            losses = info.get("losses", {})
            for i, name in enumerate(pipe_names):
                row.append(_fmt(float(losses.get(name, 0.0)), widths[3 + i]))
            scores = info.get("other_scores", {})
            for j, key in enumerate(score_keys):
                val = scores.get(key)
                col = widths[3 + len(pipe_names) + j]
                row.append(_fmt(float(val) * 100, col) if val is not None else " " * col)
            row.append(_fmt(float(info.get("wps", 0.0)), widths[-3], 0))
            row.append(_fmt(float(info.get("eval_seconds", 0.0)), widths[-2]))
            score = info.get("score")
            row.append(
                _fmt(float(score) * 100, widths[-1]) if score is not None else " " * widths[-1]
            )
            stdout.write(" ".join(row) + "\n")
            stdout.flush()

        def finalize() -> None:
            pass

        return log_step, finalize

    return setup


@registry.loggers("spacy_ray_tpu.JsonlLogger.v1")
def jsonl_logger(path: Optional[str] = None):
    """Machine-readable per-step log (jsonl) for dashboards/benchmarks."""
    import json

    def setup(nlp, stdout: IO = sys.stdout, stderr: IO = sys.stderr):
        handle = open(path, "a", encoding="utf8") if path else None

        def log_step(info: Optional[Dict[str, Any]]) -> None:
            if info is None:
                return
            rec = {
                k: info.get(k)
                for k in (
                    "epoch", "step", "words", "wps", "eval_seconds",
                    "score", "losses", "other_scores",
                )
            }
            line = json.dumps(rec, default=float)
            if handle:
                handle.write(line + "\n")
                handle.flush()
            else:
                stdout.write(line + "\n")

        def finalize() -> None:
            if handle:
                handle.close()

        return log_step, finalize

    return setup
