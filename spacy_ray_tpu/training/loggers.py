"""Training loggers: the pluggable ``[training.logger]`` registry slot.

Capability parity with the reference's console logger plugin (reference
loggers.py:8-66, registered ``spacy-ray.ConsoleLogger.v1`` via
setup.cfg:40-41; SURVEY.md §5.5). Same protocol: the factory returns a
setup function taking the pipeline and returning ``(log_step, finalize)``;
``log_step(info_or_None)`` is called every step (None = no new row).

TPU additions (SURVEY.md §5.5 "add words/sec/chip and step-time metrics as
first-class"): WPS and WPS/chip columns computed from the loop's counters.
"""

from __future__ import annotations

import sys
from typing import Any, Callable, Dict, IO, List, Optional, Tuple

from ..registry import registry


def _fmt(value: float, width: int = 8, places: int = 2) -> str:
    return f"{value:{width}.{places}f}"


def _elapsed(seconds: float) -> str:
    """H:MM:SS wall-clock elapsed — the reference's first column
    (reference loggers.py:52)."""
    s = int(seconds)
    return f"{s // 3600}:{(s % 3600) // 60:02d}:{s % 60:02d}"


@registry.loggers("spacy-ray.ConsoleLogger.v1")
@registry.loggers("spacy_ray_tpu.ConsoleLogger.v1")
def console_logger(progress_bar: bool = False):
    def setup(nlp, stdout: IO = sys.stdout, stderr: IO = sys.stderr):
        import time

        pipe_names = [
            n for n in nlp.head_names() if nlp.components[n].trainable
        ]
        score_keys = list(nlp.config.get("training", {}).get("score_weights", {}) or {})
        if not score_keys:
            # same fallback as the loop's final score: the components'
            # declared default weights (positive-weight keys only)
            from .loop import default_pipeline_score_weights

            score_keys = [
                k for k, v in default_pipeline_score_weights(nlp).items() if v > 0
            ]
        loss_cols = [f"Loss {n}" for n in pipe_names]
        score_cols = score_keys
        # Stp50/Stp95: rolling step-time percentiles in ms, populated when
        # [training] metrics_dir enables telemetry (blank otherwise) —
        # SURVEY §5.5's step-time-as-first-class-metric column
        header = (
            ["T", "E", "#", "W"]
            + loss_cols
            + score_cols
            + ["Stp50", "Stp95", "WPS", "EvalS", "Score"]
        )
        widths = [max(len(h), 8) for h in header]
        stdout.write(" ".join(h.rjust(w) for h, w in zip(header, widths)) + "\n")
        stdout.write(" ".join("-" * w for w in widths) + "\n")
        t0 = time.perf_counter()
        eval_freq = int(nlp.config.get("training", {}).get("eval_frequency", 0) or 0)
        pending = 0  # steps since the last printed row (progress bar)

        def log_step(info: Optional[Dict[str, Any]]) -> None:
            nonlocal pending
            if info is None:
                if progress_bar and stderr is not None:
                    pending += 1
                    if eval_freq:
                        done = int(20 * pending / eval_freq)
                        bar = "#" * done + "-" * (20 - done)
                        stderr.write(f"\r[{bar}] {pending}/{eval_freq}")
                    else:
                        stderr.write(f"\rstep +{pending}")
                    stderr.flush()
                return
            if progress_bar and stderr is not None and pending:
                stderr.write("\r" + " " * 40 + "\r")
                stderr.flush()
            pending = 0
            row: List[str] = [
                _elapsed(time.perf_counter() - t0).rjust(widths[0]),
                str(info.get("epoch", 0)).rjust(widths[1]),
                str(info.get("step", 0)).rjust(widths[2]),
                str(info.get("words", 0)).rjust(widths[3]),
            ]
            losses = info.get("losses", {})
            for i, name in enumerate(pipe_names):
                row.append(_fmt(float(losses.get(name, 0.0)), widths[4 + i]))
            scores = info.get("other_scores", {})
            for j, key in enumerate(score_keys):
                val = scores.get(key)
                col = widths[4 + len(pipe_names) + j]
                row.append(_fmt(float(val) * 100, col) if val is not None else " " * col)
            for j, key in enumerate(("step_ms_p50", "step_ms_p95")):
                val = info.get(key)
                col = widths[-5 + j]
                row.append(
                    _fmt(float(val), col, 1) if val is not None else " " * col
                )
            row.append(_fmt(float(info.get("wps", 0.0)), widths[-3], 0))
            row.append(_fmt(float(info.get("eval_seconds", 0.0)), widths[-2]))
            score = info.get("score")
            row.append(
                _fmt(float(score) * 100, widths[-1]) if score is not None else " " * widths[-1]
            )
            stdout.write(" ".join(row) + "\n")
            stdout.flush()

        def finalize() -> None:
            if progress_bar and stderr is not None and pending:
                stderr.write("\r" + " " * 40 + "\r")
                stderr.flush()

        return log_step, finalize

    return setup


@registry.loggers("spacy_ray_tpu.JsonlLogger.v1")
def jsonl_logger(path: Optional[str] = None):
    """Machine-readable per-step log (jsonl) for dashboards/benchmarks."""
    import json

    def setup(nlp, stdout: IO = sys.stdout, stderr: IO = sys.stderr):
        from .resilience import drain_events
        from .telemetry import sanitize_json

        handle = open(path, "a", encoding="utf8") if path else None

        def log_step(info: Optional[Dict[str, Any]]) -> None:
            if info is None:
                return
            rec = {
                k: info.get(k)
                for k in (
                    "epoch", "step", "words", "wps", "eval_seconds",
                    "score", "losses", "other_scores", "input_pipeline",
                    # telemetry gauge snapshot (step-time p50/p95, HBM,
                    # compile count, MFU) when [training] metrics_dir is on
                    "telemetry",
                )
            }
            if rec.get("telemetry") is None:
                rec.pop("telemetry", None)
            # resilience events since the last row (resume anomalies,
            # retries, checkpoint fallbacks, preemption) — jsonl is the
            # machine-readable record, so anomalies must land here too
            events = drain_events()
            if events:
                rec["events"] = events
            # sanitize: a NaN loss/score must not emit a bare `NaN` token
            # (invalid JSON) in the machine-readable log
            line = json.dumps(sanitize_json(rec), default=float)
            if handle:
                handle.write(line + "\n")
                handle.flush()
            else:
                stdout.write(line + "\n")

        def finalize() -> None:
            # events queued AFTER the last row (the `preempted` record and
            # any final-checkpoint retries live exactly there) still land
            # in the jsonl file as a trailing events-only record
            events = drain_events()
            if events:
                line = json.dumps({"events": events}, default=float)
                if handle:
                    handle.write(line + "\n")
                else:
                    stdout.write(line + "\n")
            if handle:
                handle.close()

        return log_step, finalize

    return setup
