"""Host-side parameter-ownership layout for the trainer fleet.

The shard rule is deliberately THE SAME as the in-mesh owner-shard spec
(:func:`~...parallel.mesh.zero1_spec`: shard the first axis divisible by
the worker count, replicate otherwise) and the same as the v2 checkpoint
writer's ``_shard_plan`` derives from those shardings — so a fleet of N
processes owns exactly the slices an N-replica mesh checkpoints as
``opt_state-{stamp}.partKofN.pkl`` part files. That identity is what
makes elastic cross-process resume free: parts written by N separate
fleet processes reassemble through the UNCHANGED
``checkpoint._assemble_opt_parts`` into the canonical unsharded layout
any mesh shape (or a single-process synchronous run) resumes from.

Leaves no axis can shard (scalars, small biases) are owned WHOLE by
worker 0 — mirroring the v2 format, where replicated leaves are written
once into part 0 with ``index=None``.

Everything here is numpy-on-host; jax appears only for pytree walking
(``tree_flatten_with_path``) when mapping a worker's LOCAL optimizer
state (built by ``tx.init`` over its owned slice tree) onto the
CANONICAL full-state leaf ordinals. The mapping leans on one structural
fact: the owned slice tree is the param tree restricted to owned paths,
so every local optimizer leaf's key path is literally a key path of the
full state — matching is exact string equality, no heuristics.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

PathT = Tuple[str, ...]
IndexT = Tuple[Tuple[int, int], ...]


def shard_axis(shape: Sequence[int], n_workers: int) -> Optional[int]:
    """First axis divisible by (and at least) ``n_workers`` — the
    :func:`~...parallel.mesh.zero1_spec` rule verbatim; None means the
    leaf cannot shard (owned whole by worker 0)."""
    if n_workers <= 1:
        return None
    for axis, dim in enumerate(shape):
        if dim % n_workers == 0 and dim >= n_workers:
            return axis
    return None


def path_key(path: PathT) -> str:
    return "/".join(path)


def iter_leaves(tree: Any, prefix: PathT = ()) -> Iterator[Tuple[PathT, Any]]:
    """Depth-first (sorted-key — jax's dict order) walk of a nested-dict
    tree, yielding (path, leaf). The SAME path scheme as
    ``checkpoint._flatten``'s '/'-joined keys (test-pinned: fleet part
    files and params-npz must round-trip through checkpoint.py), minus
    that helper's host materialization — slicing and merging need the
    raw leaves, not copies."""
    if isinstance(tree, dict):
        for k in sorted(tree):
            yield from iter_leaves(tree[k], prefix + (str(k),))
    else:
        yield prefix, tree


def tree_from_flat(flat: Dict[str, Any]) -> Dict[str, Any]:
    """'/'-joined path keys back into a nested dict — checkpoint.py's
    ``_unflatten``, re-exported as the fleet's one unflatten."""
    from ..checkpoint import _unflatten

    return _unflatten(flat)


class OwnershipLayout:
    """Which worker owns which slice of every param-shaped leaf.

    Built once from the (host) parameter template; the same layout
    slices gradients (same tree shape as params) and answers the
    checkpoint writer's ``((start, stop), ...)`` index questions.
    """

    def __init__(self, template: Any, n_workers: int) -> None:
        self.n_workers = max(int(n_workers), 1)
        self.paths: List[PathT] = []
        self.shapes: List[Tuple[int, ...]] = []
        self.axes: List[Optional[int]] = []
        self._by_key: Dict[str, int] = {}
        for path, leaf in iter_leaves(template):
            shape = tuple(int(d) for d in np.shape(leaf))
            self.paths.append(path)
            self.shapes.append(shape)
            self.axes.append(shard_axis(shape, self.n_workers))
            self._by_key[path_key(path)] = len(self.paths) - 1

    # -- ownership queries --------------------------------------------
    def owns(self, ordinal: int, worker: int) -> bool:
        """Does ``worker`` own a piece of leaf ``ordinal``? Shardable
        leaves: every worker owns its slice. Unshardable: worker 0 owns
        the whole leaf."""
        if self.axes[ordinal] is None:
            return worker == 0
        return 0 <= worker < self.n_workers

    def index(self, ordinal: int, worker: int) -> Optional[IndexT]:
        """The v2-checkpoint index of ``worker``'s slice of leaf
        ``ordinal`` — ``((start, stop), ...)`` over ALL axes, or None
        for a whole (unshardable) leaf."""
        axis = self.axes[ordinal]
        shape = self.shapes[ordinal]
        if axis is None:
            return None
        span = shape[axis] // self.n_workers
        out = []
        for a, dim in enumerate(shape):
            if a == axis:
                out.append((worker * span, (worker + 1) * span))
            else:
                out.append((0, dim))
        return tuple(out)

    def key_index(self, key: str, worker: int) -> Optional[IndexT]:
        """``index`` addressed by '/'-joined path key instead of
        ordinal — what re-shard geometry comparisons work in, since
        ordinals are only stable within one layout."""
        ordinal = self._by_key.get(key)
        if ordinal is None:
            raise ValueError(f"unknown param leaf {key!r}")
        return self.index(ordinal, worker)

    def index_for_shape(
        self, shape: Sequence[int], worker: int
    ) -> Optional[IndexT]:
        """Index of ``worker``'s slice for an arbitrary leaf shape (the
        optimizer-state leaves, whose own shapes decide their sharding —
        the same by-shape rule ``_shard_plan`` recovers from in-mesh
        shardings)."""
        axis = shard_axis(shape, self.n_workers)
        if axis is None:
            return None
        span = int(shape[axis]) // self.n_workers
        return tuple(
            (worker * span, (worker + 1) * span) if a == axis else (0, int(d))
            for a, d in enumerate(shape)
        )

    @staticmethod
    def slice_with(arr: np.ndarray, index: Optional[IndexT]) -> np.ndarray:
        if index is None:
            return np.asarray(arr)
        return np.asarray(arr)[tuple(slice(a, b) for a, b in index)]

    # -- tree operations ----------------------------------------------
    def owned_keys(self, worker: int) -> List[str]:
        return [
            path_key(self.paths[i])
            for i in range(len(self.paths))
            if self.owns(i, worker)
        ]

    def flat_slices(self, tree: Any, worker: int) -> Dict[str, np.ndarray]:
        """``worker``'s owned slices of a params-shaped tree, as a flat
        '/'-keyed dict of COPIES (safe to mutate / serialize after the
        source tree moves on)."""
        out: Dict[str, np.ndarray] = {}
        for path, leaf in iter_leaves(tree):
            ordinal = self._by_key[path_key(path)]
            if not self.owns(ordinal, worker):
                continue
            out[path_key(path)] = np.array(
                self.slice_with(np.asarray(leaf), self.index(ordinal, worker))
            )
        return out

    def slice_tree(self, tree: Any, worker: int) -> Dict[str, Any]:
        """Owned slices as a NESTED dict restricted to owned paths —
        the tree ``tx.init`` runs on and the jitted shard apply updates."""
        return tree_from_flat(self.flat_slices(tree, worker))

    def merge_flat(
        self,
        full: Any,
        worker: int,
        flat: Dict[str, np.ndarray],
        *,
        add: bool = False,
    ) -> None:
        """Write ``worker``'s slices back into the full host tree IN
        PLACE (the pull path: refresh non-owned shards from their
        owner's bytes). ``add=True`` ACCUMULATES instead of assigning —
        a delta pull ships ``wire_v - wire_known`` and the puller adds
        it onto the slice it already holds. Unknown keys and shape
        mismatches raise — a peer sending a different model is a config
        error, not data."""
        for key, piece in flat.items():
            ordinal = self._by_key.get(key)
            if ordinal is None:
                raise ValueError(f"unknown param leaf {key!r} in merge")
            node = full
            for p in self.paths[ordinal][:-1]:
                node = node[p]
            leaf_key = self.paths[ordinal][-1]
            index = self.index(ordinal, worker)
            arr = np.asarray(node[leaf_key])
            if not isinstance(node[leaf_key], np.ndarray):
                # first merge into a tree that still holds jax arrays:
                # materialize a mutable host copy once
                arr = np.array(arr)
                node[leaf_key] = arr
            if index is None:
                if piece.shape != arr.shape:
                    raise ValueError(
                        f"shape mismatch for {key!r}: {piece.shape} vs "
                        f"{arr.shape}"
                    )
                if add:
                    arr[...] += piece
                else:
                    arr[...] = piece
            else:
                where = tuple(slice(a, b) for a, b in index)
                if add:
                    arr[where] += piece
                else:
                    arr[where] = piece

    def signature(self) -> str:
        """Cheap structural digest (paths + shapes + worker count) every
        peer must agree on — pushed slices are meaningless across
        differing layouts, so /healthz carries this and startup verifies
        it."""
        import hashlib

        text = f"n={self.n_workers}|" + "|".join(
            f"{path_key(p)}:{'x'.join(map(str, s))}"
            for p, s in zip(self.paths, self.shapes)
        )
        return hashlib.sha256(text.encode("utf8")).hexdigest()[:16]


# ----------------------------------------------------------------------
# Optimizer-state local <-> canonical mapping
# ----------------------------------------------------------------------


def _flatten_with_keystr(tree: Any) -> List[Tuple[str, Any]]:
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [(jax.tree_util.keystr(path), leaf) for path, leaf in flat]


def opt_part_records(
    tx: Any,
    param_template: Any,
    layout: OwnershipLayout,
    local_opt_state: Any,
    worker: int,
) -> Tuple[int, Any, List[Tuple[int, Optional[IndexT], Tuple[int, ...], str, np.ndarray]]]:
    """Map one worker's LOCAL optimizer state onto the canonical full
    state's leaf ordinals, producing the v2 part-file records
    ``(ordinal, index, global_shape, dtype, piece)``.

    Returns ``(n_leaves, skeleton, records)`` — ``skeleton`` is the
    structure-only (all-zeros) canonical state worker 0's part-0 header
    carries, exactly like the in-mesh writer's.

    Chain scalars (Adam/schedule counts) exist in EVERY worker's local
    state but are emitted by the rank-0 owner only, with ``index=None``
    — the same placement the in-mesh v2 writer gives replicated leaves.
    (With a plain :class:`OwnershipLayout` rank == worker id; an elastic
    :class:`~.membership.RankedLayout` maps surviving ids to dense
    ranks, so after a failover the new lowest-id survivor writes them.)
    """
    import jax

    rank = worker
    rank_of = getattr(layout, "rank_of", None)
    if rank_of is not None:
        rank = rank_of(worker)
        if rank is None:
            raise ValueError(
                f"worker {worker} is not in the layout's active set"
            )
    template_struct = jax.eval_shape(tx.init, param_template)
    global_leaves = _flatten_with_keystr(template_struct)
    global_by_key = {
        key: (ordinal, tuple(int(d) for d in leaf.shape), str(leaf.dtype))
        for ordinal, (key, leaf) in enumerate(global_leaves)
    }
    skeleton = jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(template_struct),
        [0] * len(global_leaves),
    )
    records: List[
        Tuple[int, Optional[IndexT], Tuple[int, ...], str, np.ndarray]
    ] = []
    for key, leaf in _flatten_with_keystr(local_opt_state):
        if key not in global_by_key:
            raise ValueError(
                f"local optimizer leaf {key!r} has no canonical "
                "counterpart — owned slice tree diverged from the param "
                "template"
            )
        ordinal, gshape, _dtype = global_by_key[key]
        index = layout.index_for_shape(gshape, worker)
        piece = np.asarray(jax.device_get(leaf))
        if index is None:
            if rank != 0:
                continue  # the rank-0 owner writes the whole-leaf copies
            if piece.shape != gshape:
                raise ValueError(
                    f"unshardable optimizer leaf {key!r} has local shape "
                    f"{piece.shape}, canonical {gshape}"
                )
        else:
            want = tuple(b - a for a, b in index)
            if piece.shape != want:
                raise ValueError(
                    f"optimizer leaf {key!r}: local slice shape "
                    f"{piece.shape} != owner-shard shape {want}"
                )
        records.append((ordinal, index, gshape, str(piece.dtype), piece))
    return len(global_leaves), skeleton, records


def local_opt_from_canonical(
    tx: Any,
    layout: OwnershipLayout,
    canonical_opt: Any,
    worker: int,
    slice_params: Any,
) -> Any:
    """The resume direction: carve one worker's LOCAL optimizer state out
    of a loaded canonical (unsharded) state. The local structure comes
    from ``tx.init`` over the owned slice tree; every local leaf's value
    is the matching slice of the canonical leaf — bit-identical round
    trip with :func:`opt_part_records`."""
    import jax
    import jax.numpy as jnp

    canonical_by_key = {
        key: leaf for key, leaf in _flatten_with_keystr(canonical_opt)
    }
    local_template = jax.eval_shape(tx.init, slice_params)
    flat, treedef = jax.tree_util.tree_flatten_with_path(local_template)
    leaves = []
    for path, struct in flat:
        key = jax.tree_util.keystr(path)
        if key not in canonical_by_key:
            raise ValueError(
                f"checkpointed optimizer state has no leaf {key!r} — "
                "optimizer config changed since the checkpoint was written?"
            )
        full = np.asarray(jax.device_get(canonical_by_key[key]))
        index = layout.index_for_shape(full.shape, worker)
        piece = OwnershipLayout.slice_with(full, index)
        if tuple(piece.shape) != tuple(struct.shape):
            raise ValueError(
                f"optimizer leaf {key!r}: checkpoint slice shape "
                f"{piece.shape} != local shape {tuple(struct.shape)}"
            )
        leaves.append(jnp.asarray(piece))
    return jax.tree_util.tree_unflatten(treedef, leaves)
