"""One trainer-fleet process: the asynchronous pull → grad → push →
apply-wait loop (PAPER.md §L3/L4, reference worker.py:117-155).

Each of the N processes:

* computes gradients on ITS OWN corpus shard
  (:func:`~..batcher.shard_stream` by worker id — the per-rank data
  sharding the reference lacked);
* pushes the non-owned shard gradients to their owners, fire-and-forget
  with a bounded :class:`~..resilience.RetryPolicy` (a dead peer costs a
  counted drop, never a stall);
* feeds its OWN shard's gradients to its local :class:`~.peer.OwnerState`,
  which applies the optimizer at quorum and bumps the shard version;
* blocks (apply-wait) until its own shard's version passes the stamp it
  pushed against — bounded by ``quorum_wait_s`` so a lost quorum degrades
  to a counted timeout, not a wedge;
* pulls newer shard bytes from the other owners at the top of the next
  step.

Gradient-clip semantics: with a fusable optimizer (Adam.v1 / RAdam.v1)
the global-norm clip runs WORKER-SIDE over the full gradient tree
(exact global norm of that worker's gradient) and the owner applies a
clip-free fused chain on its slice — the one optimizer stage that needs
the whole tree moves to where the whole tree lives. The owner's state
STRUCTURE still delegates to the reference chain, so fleet part files
reassemble into exactly the canonical state a synchronous run resumes
from (the clip element's state is empty). Non-fusable optimizers run
their full chain per-shard (per-shard clip — documented caveat,
TUNING.md §19).

Per-phase wall time (data / pull / grad / push / apply_wait) is
accounted every step and lands on the bench record and the per-worker
result file ``fleet-worker-{k}.json`` (which doubles as the CI failure
artifact's discard-counter ledger).
"""

from __future__ import annotations

import http.client
import json
import logging
import socket
import threading
import time
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Tuple
from urllib.parse import urlparse

import numpy as np

from ...registry import registry
from ..batcher import bucket_batch_size, bucket_length, shard_stream
from ..checkpoint import (
    CheckpointCorrupt,
    TrainCheckpoint,
    commit_fleet_generation,
    write_fleet_opt_part,
)
from .. import resilience
from ..resilience import (
    RetryPolicy,
    ShutdownCoordinator,
    Watchdog,
    log_event,
    maybe_fail,
    retry_io,
)
from .membership import (
    LeaseTracker,
    Membership,
    MembershipLedger,
    PeerBackoff,
)
from .ownership import (
    local_opt_from_canonical,
    opt_part_records,
)
from .peer import FleetCounters, OwnerState, PeerServer
from .wire import (
    GradCompressor,
    WireError,
    decode_arrays,
    decode_delta_frame,
    encode_arrays,
    negotiate_push_codec,
    resolve_grad_compression,
)

logger = logging.getLogger("spacy_ray_tpu.training")

DEFAULT_FLEET_BASE_PORT = 47200
PHASES = ("data", "pull", "grad", "push", "apply_wait")

__all__ = [
    "DEFAULT_FLEET_BASE_PORT",
    "PHASES",
    "resolve_quorum",
    "train_fleet_worker",
]


def resolve_quorum(quorum: Optional[int], n_workers: int) -> int:
    """0/None = auto: all-but-one (min 1) — the fleet keeps stepping
    through a single crashed peer (the supervisor restarts it) while
    still averaging nearly every worker's gradient."""
    if not quorum:
        return max(1, int(n_workers) - 1)
    return int(quorum)


class _PeerClient:
    """Minimal persistent HTTP client for one peer (keep-alive, one
    reconnect on a dead socket, every failure surfaced as OSError so
    ``retry_io`` treats the whole family as transient)."""

    def __init__(self, url: str, timeout: float = 10.0) -> None:
        parsed = urlparse(url)
        if parsed.scheme != "http":
            raise ValueError(f"fleet peers speak plain http, got {url!r}")
        self.host = parsed.hostname or "127.0.0.1"
        self.port = int(parsed.port or 80)
        self.timeout = float(timeout)
        self._conn: Optional[http.client.HTTPConnection] = None

    def _connection(self) -> http.client.HTTPConnection:
        if self._conn is None:
            self._conn = http.client.HTTPConnection(
                self.host, self.port, timeout=self.timeout
            )
        return self._conn

    def close(self) -> None:
        if self._conn is not None:
            try:
                self._conn.close()
            except OSError:
                pass
            self._conn = None

    def request(
        self,
        method: str,
        path: str,
        body: Optional[bytes] = None,
        content_type: str = "application/octet-stream",
        headers: Optional[Dict[str, str]] = None,
    ) -> Tuple[int, Dict[str, str], bytes]:
        last: Optional[Exception] = None
        for attempt in (0, 1):  # one transparent reconnect on a dead socket
            conn = self._connection()
            try:
                hdrs = {"Content-Type": content_type} if body else {}
                if headers:
                    hdrs.update(headers)
                conn.request(method, path, body=body, headers=hdrs)
                resp = conn.getresponse()
                payload = resp.read()
                return resp.status, dict(resp.getheaders()), payload
            except (http.client.HTTPException, OSError, socket.timeout) as e:
                last = e
                self.close()
        raise OSError(f"peer {self.host}:{self.port} unreachable: {last}")


def _np_tree(tree: Any) -> Any:
    """Deep host copy: every leaf a fresh mutable np.ndarray."""
    if isinstance(tree, dict):
        return {k: _np_tree(v) for k, v in tree.items()}
    return np.array(np.asarray(tree))


def train_fleet_worker(
    config: Any,
    output_path: Optional[Path] = None,
    *,
    worker_id: int,
    n_workers: int,
    quorum: int = 0,
    max_staleness: int = 1,
    base_port: int = DEFAULT_FLEET_BASE_PORT,
    port: Optional[int] = None,
    peer_urls: Optional[List[str]] = None,
    bind_host: str = "127.0.0.1",
    resume: bool = False,
    stdout_log: bool = True,
    metrics_dir: Optional[Path] = None,
    metrics_port: Optional[int] = None,
    max_steps_override: Optional[int] = None,
    install_signal_handlers: bool = True,
    quorum_wait_s: float = 30.0,
    push_retries: int = 1,
    peer_wait_s: float = 120.0,
    finalize_wait_s: float = 600.0,
    checkpoint_timeout_s: float = 600.0,
    peer_lease_s: float = 60.0,
    lease_miss_threshold: int = 3,
    lease_poll_s: float = 2.0,
    peer_timeout_s: Optional[float] = None,
    probe_timeout_s: Optional[float] = None,
    watch_interval_s: float = 5.0,
    alert_interval_s: float = 5.0,
    grad_compression: str = "auto",
    param_delta_window: int = 4,
    grad_error_feedback: bool = True,
) -> Tuple[Any, Any]:
    """Run ONE fleet worker process; returns ``(nlp, TrainResult)`` like
    :func:`~..loop.train` (whose ``fleet=`` mode delegates here).

    ``metrics_port`` is unused (the peer server IS the telemetry
    endpoint — one port per worker, ``base_port + worker_id``); accepted
    so the CLI plumbing stays uniform.

    ``grad_compression`` picks the push codec (``auto`` resolves per
    backend, TUNING.md §20); ``param_delta_window`` is the owner-side K
    for version-delta pulls (0 = PR 14 full pulls). Both degrade to f32
    against peers that don't advertise the codec.
    ``grad_error_feedback=False`` is the ablation control the
    convergence suite uses — never turn it off for real runs (sub-step
    gradient signal then quantizes to zero forever).

    ``peer_lease_s`` arms elastic membership (RESILIENCE.md "Ownership
    failover"): every worker leases its peers off ``/healthz``; the
    acting lead (lowest live active id) evicts a peer whose lease
    expired AND that missed ``lease_miss_threshold`` consecutive
    probes, bumps the fleet-wide membership epoch, and survivors
    re-shard ownership over the remaining ids at their next step
    boundary. Set ``peer_lease_s=0`` to disable eviction entirely
    (PR 14 frozen-membership behavior). ``peer_timeout_s`` /
    ``probe_timeout_s`` override the ``[training]``
    ``fleet_peer_timeout_s`` / ``fleet_probe_timeout_s`` knobs for
    step-traffic and liveness-probe connections respectively.
    """
    import jax
    import jax.numpy as jnp

    from ...parallel import context as pctx
    from ...parallel.mesh import build_mesh
    from ...parallel.step import make_shard_apply
    from ...pipeline.language import Pipeline
    from .. import optimizers as _optimizers
    from ..loop import (
        TrainResult,
        default_pipeline_score_weights,
        resolve_dot_name,
        resolve_training,
        weighted_score,
    )

    if jax.process_count() > 1:
        raise ValueError(
            "the trainer fleet IS the multi-process mode — run it on "
            "single-process jax (one fleet worker per process), not under "
            "jax.distributed"
        )
    worker_id = int(worker_id)
    n_workers = int(n_workers)
    if not (0 <= worker_id < n_workers):
        raise ValueError(
            f"fleet worker id {worker_id} outside [0, {n_workers})"
        )
    quorum_requested = int(quorum or 0)
    quorum = resolve_quorum(quorum, n_workers)
    if not (1 <= quorum <= n_workers):
        raise ValueError(f"quorum {quorum} outside [1, {n_workers}]")

    def _quorum_for(n_active: int) -> int:
        """The effective quorum after a membership change: auto re-auto-
        resolves over the survivor count; an explicit quorum is clamped
        so a shrunken fleet can still reach it."""
        if quorum_requested <= 0:
            return resolve_quorum(0, n_active)
        return max(1, min(quorum_requested, n_active))

    max_staleness = int(max_staleness)
    if max_staleness < 0:
        raise ValueError(f"max_staleness must be >= 0, got {max_staleness}")

    config = config.interpolate()
    T = resolve_training(config)
    if int(T.get("accumulate_gradient") or 1) != 1:
        raise ValueError(
            "fleet mode: accumulate_gradient > 1 is not supported — the "
            "quorum IS the accumulation (the reference folds them "
            "together too; SURVEY.md §2.4)"
        )
    if T.get("annotating_components"):
        raise ValueError(
            "fleet mode does not support annotating_components yet"
        )
    if T.get("frozen_components"):
        raise ValueError(
            "fleet mode does not support frozen_components yet (the "
            "optax.masked mask is built over the full tree and cannot "
            "follow owner-shard slices)"
        )

    seed = int(T.get("seed") or 0)
    import random as _random

    _random.seed(seed)
    np.random.seed(seed)

    resilience.activate_env_fault_plan()
    resilience.drain_events()
    resilience.set_default_retry_policy(
        RetryPolicy(
            max_retries=int(T.get("io_retries", 3) or 0),
            base_delay=float(T.get("io_retry_base_s", 0.5) or 0.5),
        )
    )
    push_policy = RetryPolicy(
        max_retries=max(int(push_retries), 0), base_delay=0.05, max_delay=1.0
    )
    shutdown = ShutdownCoordinator()
    # per-peer connection deadlines: explicit kwargs win, then the
    # [training] knobs, then the historical constants (10s step traffic,
    # 5s liveness probes — same precedence as checkpoint_timeout_s)
    peer_timeout = float(
        peer_timeout_s if peer_timeout_s is not None
        else T.get("fleet_peer_timeout_s") or 10.0
    )
    probe_timeout = float(
        probe_timeout_s if probe_timeout_s is not None
        else T.get("fleet_probe_timeout_s") or 5.0
    )
    if peer_timeout <= 0 or probe_timeout <= 0:
        raise ValueError(
            "fleet_peer_timeout_s / fleet_probe_timeout_s must be > 0"
        )
    peer_lease_s = float(peer_lease_s)
    lease_miss_threshold = max(1, int(lease_miss_threshold))
    lease_poll_s = max(0.2, float(lease_poll_s))

    # ---- telemetry (per-worker sub-directory; the peer server serves it)
    tel = None
    tel_dir = str(metrics_dir) if metrics_dir is not None else str(
        T.get("metrics_dir") or ""
    )
    if tel_dir:
        from ...alerting import default_training_rules
        from ..telemetry import Telemetry

        trace_steps = T.get("trace_steps") or [0, 50]
        tel = Telemetry(
            Path(tel_dir) / f"fleet-worker-{worker_id}",
            trace_steps=(int(trace_steps[0]), int(trace_steps[1])),
            anomaly_detection=bool(T.get("anomaly_detection", True)),
            process_index=worker_id,
            alerting=bool(T.get("alerting", True)),
            alert_rules=default_training_rules(fleet=True),
            alert_interval_s=float(alert_interval_s),
            incident_dir=(
                Path(str(T.get("incident_dir")))
                if T.get("incident_dir") else None
            ),
            process_name=f"fleet-worker-{worker_id}",
        )
        tel.registry.gauge("fleet_worker").set(worker_id)

    # ---- corpora / pipeline -----------------------------------------
    corpora_cfg = config.get("corpora", {})
    resolved_corpora = {
        name: registry.resolve(block) for name, block in corpora_cfg.items()
    }
    train_corpus = resolve_dot_name(config, resolved_corpora, T["train_corpus"])
    dev_corpus = resolve_dot_name(config, resolved_corpora, T["dev_corpus"])
    nlp = Pipeline.from_config(config)
    nlp.initialize(train_corpus, seed=seed)

    mesh = build_mesh(n_data=1)
    tx = registry.resolve(T.get("optimizer") or {"@optimizers": "Adam.v1"})
    use_averages = bool(getattr(tx, "use_averages", False))
    if use_averages:
        raise ValueError(
            "fleet mode does not support use_averages (the running mean "
            "needs every post-apply param tree on one host)"
        )
    meta = getattr(tx, "fusable", None)
    if meta:
        # worker-side exact global-norm clip; owner applies the clip-free
        # fused chain on its slice (state structure delegates to the
        # reference chain, so checkpoints stay canonical)
        worker_clip = float(meta.get("grad_clip") or 0.0)
        from ...ops.fused_update import make_fused_transformation

        fused = make_fused_transformation(
            reference_tx=tx.tx, **{**meta, "grad_clip": 0.0}
        )
        owner_tx = _optimizers.OptimizerWrapper(fused)
        owner_tx.applies_updates = True
    else:
        worker_clip = 0.0
        owner_tx = tx
        log_event(
            "fleet-per-shard-optimizer",
            "optimizer is not fusable: the full chain (including any "
            "global-norm clip) runs PER OWNER SHARD — clip norms are "
            "shard-local, not global (TUNING.md §19)",
        )

    batcher = registry.resolve(
        T.get("batcher")
        or {"@batchers": "spacy.batch_by_words.v1", "size": 1000,
            "tolerance": 0.2}
    )
    dropout = float(T["dropout"])
    loss_fn = nlp.make_loss_fn(dropout=dropout)

    params_host = _np_tree(nlp.params)
    membership = Membership(range(n_workers))
    layout = membership.layout(params_host)

    # ---- state (fresh or resumed) -----------------------------------
    step = 0
    epoch = 0
    best_score = -1.0
    best_step = -1
    version = 0
    rng = jax.random.fold_in(jax.random.PRNGKey(seed), worker_id)
    resumed_from: Optional[int] = None
    ckpt = None
    if resume and output_path is not None:
        try:
            ckpt = TrainCheckpoint.load(Path(output_path) / "last-model")
        except CheckpointCorrupt as e:
            log_event(
                "resume-failed",
                f"--resume found no intact checkpoint generation ({e}); "
                "starting from scratch",
            )
    if ckpt is not None:
        params_host = _np_tree(ckpt["params"])
        step = int(ckpt["step"])
        epoch = int(ckpt["epoch"])
        best_score = float(ckpt["best_score"])
        best_step = int(ckpt["best_step"])
        resumed_from = step
        fleet_extra = (ckpt.get("extra") or {}).get("fleet") or {}
        ck_active = fleet_extra.get("active")
        if ck_active:
            # the checkpoint carries the membership it was committed
            # under — resume into THAT fleet, not the config's nominal
            # one (a pre-elastic checkpoint has no such field: epoch 0,
            # everyone active)
            try:
                membership = Membership(
                    [int(a) for a in ck_active],
                    int(fleet_extra.get("epoch") or 0),
                )
                layout = membership.layout(params_host)
            except (TypeError, ValueError) as e:
                log_event(
                    "fleet-resume-membership-invalid",
                    f"checkpoint extra.fleet.active is malformed ({e}); "
                    "assuming the full nominal fleet at epoch 0",
                    worker=worker_id,
                )
        versions = fleet_extra.get("versions") or []
        if worker_id < len(versions) and versions[worker_id] is not None:
            version = int(versions[worker_id])
        rngs = fleet_extra.get("rngs") or []
        if worker_id < len(rngs) and rngs[worker_id] is not None:
            rng = jnp.asarray(np.array(rngs[worker_id], dtype=np.uint32))
        else:
            rng = jax.random.fold_in(
                jnp.asarray(
                    np.array(
                        np.asarray(jax.device_get(ckpt["rng"])),
                        dtype=np.uint32,
                    )
                ),
                worker_id,
            )
        log_event(
            "fleet-resume",
            f"worker {worker_id} resumed from checkpoint step {step} "
            f"(shard version {version})",
            worker=worker_id, step=step, version=version,
        )

    quorum = _quorum_for(len(membership.active))
    slice_np = layout.slice_tree(params_host, worker_id)
    slice_params = jax.tree_util.tree_map(jnp.asarray, slice_np)
    if ckpt is not None and worker_id in membership:
        opt_local = local_opt_from_canonical(
            owner_tx, layout, ckpt["opt_state"], worker_id, slice_np
        )
    else:
        opt_local = owner_tx.init(slice_params)
    ckpt = None  # drop the loaded canonical trees

    owns_any = bool(layout.owned_keys(worker_id))
    if worker_id not in membership:
        # resumed from a checkpoint committed AFTER our eviction: we are
        # a returning member, not a config error — the join flow below
        # asks the acting lead to admit us at the next epoch boundary;
        # until the admit broadcast lands, every push is epoch-fenced
        # (counted) at the owners
        log_event(
            "fleet-resume-evicted",
            f"worker {worker_id} resumed into membership epoch "
            f"{membership.epoch} which no longer names it (active "
            f"{list(membership.active)}) — requesting rejoin",
            worker=worker_id, epoch=membership.epoch,
            active=list(membership.active),
        )
    elif not owns_any:
        # legal but degenerate (no leaf axis divisible by n_workers
        # beyond worker 0's whole-leaf ownership): this worker
        # contributes gradients to the owners but its own shard is empty
        # — its version never moves, so it must not quorum-wait on it
        log_event(
            "fleet-worker-owns-nothing",
            f"worker {worker_id} owns no parameter slices at "
            f"n_workers={n_workers} (no axis divisible); it will push "
            "gradients but apply nothing — consider fewer workers",
            worker=worker_id, n_workers=n_workers,
        )
    # ---- wire compression (ROADMAP item 3: the bandwidth plane) ------
    # one resolved codec per process; the ACTUAL codec per peer is
    # negotiated at push time against what its /healthz advertises, so
    # a mixed fleet (an old f32-only worker among compressed ones)
    # interoperates — it just gets f32 frames
    wire_codec, wire_reason = resolve_grad_compression(
        grad_compression, jax.default_backend()
    )
    param_delta_window = max(0, int(param_delta_window))
    compressor = GradCompressor(
        wire_codec, error_feedback=bool(grad_error_feedback)
    )
    peer_codecs: Dict[int, Any] = {}
    log_event(
        "fleet-wire-codec",
        f"worker {worker_id}: grad compression {grad_compression} -> "
        f"{wire_codec} ({wire_reason}); param delta window "
        f"{param_delta_window}",
        worker=worker_id, codec=wire_codec, delta_window=param_delta_window,
    )
    counters = FleetCounters(
        registry=tel.registry if tel is not None else None
    )
    version_gauge = (
        tel.registry.gauge("param_version") if tel is not None else None
    )
    epoch_gauge = (
        tel.registry.gauge("membership_epoch") if tel is not None else None
    )
    if epoch_gauge is not None:
        epoch_gauge.set(membership.epoch)
    member_ledger = MembershipLedger(
        Path(output_path) / "fleet-membership.jsonl"
        if output_path is not None else None
    )
    backoff = PeerBackoff(
        base_s=1.0, cap_s=max(1.0, min(30.0, float(quorum_wait_s)))
    )
    # worker-side per-phase dynamics histograms (shared bucket tables —
    # docs/OBSERVABILITY.md "Training fleet"); telemetry off constructs
    # none of them (the zero-calls contract)
    phase_hists: Optional[Dict[str, Any]] = None
    if tel is not None:
        from ..telemetry import FLEET_DYNAMICS_HISTOGRAMS

        phase_hists = {
            p: tel.registry.histogram(
                f"phase_{p}_seconds",
                buckets=FLEET_DYNAMICS_HISTOGRAMS[f"phase_{p}_seconds"],
            )
            for p in PHASES
        }
    owner = OwnerState(
        worker_id=worker_id,
        n_workers=n_workers,
        quorum=quorum,
        max_staleness=max_staleness,
        apply_fn=make_shard_apply(owner_tx),
        slice_params=slice_params,
        opt_state=opt_local,
        counters=counters,
        version=version,
        on_version=(version_gauge.set if version_gauge is not None else None),
        registry=tel.registry if tel is not None else None,
        trace=tel.trace if tel is not None else None,
        delta_window=param_delta_window,
        delta_codec=wire_codec,
    )

    # mutable holders the checkpoint callback (handler thread) reads
    state_holder: Dict[str, Any] = {"step": step, "rng": rng}

    def checkpoint_cb(ckpt_dir: str, stamp: int) -> Dict[str, Any]:
        # snapshot the membership-dependent pieces once: the step loop
        # may swap layout/membership at its next boundary while this
        # handler-thread call is in flight
        lay, member = layout, membership
        rank = lay.rank_of(worker_id)
        if rank is None:
            raise ValueError(
                f"worker {worker_id} is not in membership epoch "
                f"{member.epoch} — cannot contribute a checkpoint part"
            )

        def writer(cur_version, opt_state, host_flat):
            n_leaves, skeleton, records = opt_part_records(
                owner_tx, params_host, lay, opt_state, worker_id
            )
            digest = write_fleet_opt_part(
                ckpt_dir,
                stamp=stamp,
                part=rank,
                parts=len(member.active),
                n_leaves=n_leaves,
                records=records,
                skeleton=skeleton if rank == 0 else None,
            )
            return cur_version, digest, host_flat

        cur_version, digest, host_flat = owner.checkpoint_parts(writer)
        return {
            "meta": {
                "digest": digest,
                "version": cur_version,
                "part": rank,
                "step": int(state_holder["step"]),
                "rng": np.asarray(
                    jax.device_get(state_holder["rng"])
                ).tolist(),
            },
            "params": host_flat,
        }

    server = PeerServer(
        owner,
        worker_id=worker_id,
        layout_signature=layout.signature(),
        counters=counters,
        tel=tel,
        host=bind_host,
        port=int(port) if port is not None else int(base_port) + worker_id,
        checkpoint_cb=checkpoint_cb,
    )
    server.set_membership(membership, layout.signature())
    server.start()
    urls = list(peer_urls) if peer_urls is not None else [
        f"http://127.0.0.1:{int(base_port) + i}" for i in range(n_workers)
    ]
    if len(urls) != n_workers:
        raise ValueError(
            f"peer_urls names {len(urls)} workers, fleet has {n_workers}"
        )
    clients: Dict[int, _PeerClient] = {
        w: _PeerClient(urls[w], timeout=peer_timeout)
        for w in membership.active if w != worker_id
    }
    ckpt_clients: Dict[int, _PeerClient] = {}  # long-deadline, lazy

    # what each peer exchange WOULD cost as a PR 14 f32 frame — the
    # _uncompressed twin counters' source (slice shapes are static, so
    # one encode of the template per peer at startup is exact)
    wire_full_bytes: Dict[int, int] = {}
    for w in clients:
        flat_w = layout.flat_slices(params_host, w)
        if flat_w:
            wire_full_bytes[w] = len(encode_arrays(
                {"worker": worker_id, "stamp": 0},
                {k: np.asarray(v, np.float32) for k, v in flat_w.items()},
            ))

    drifted: set = set()  # peers seen at a different membership epoch

    def wait_for_peers() -> None:
        """Block until every peer answers /healthz with a matching
        layout signature. A COLD start that never sees its peers is a
        misconfiguration (wrong ports/config) and raises loudly; a
        REJOINING worker (supervisor restart with --resume) proceeds
        after a short wait instead — its peers may legitimately have
        finished and exited while it was down (their final state is in
        the checkpoint it just resumed), and every unreachable-peer
        push/pull from here on is a counted drop, not a crash."""
        rejoining = resumed_from is not None
        wait_s = min(float(peer_wait_s), 15.0) if rejoining else float(
            peer_wait_s
        )
        deadline = time.monotonic() + wait_s
        pending = set(clients)
        while pending:
            for w in sorted(pending):
                try:
                    status, _, body = clients[w].request("GET", "/healthz")
                except OSError:
                    continue
                if status != 200:
                    continue
                payload = json.loads(body.decode("utf8"))
                sig = payload.get("layout")
                if sig != layout.signature():
                    peer_epoch = payload.get("epoch")
                    if (
                        isinstance(peer_epoch, int)
                        and not isinstance(peer_epoch, bool)
                        and peer_epoch != membership.epoch
                    ):
                        # not a config error — the peer is at a different
                        # MEMBERSHIP epoch (the fleet re-sharded while we
                        # were down); the join/refresh flow reconciles
                        log_event(
                            "fleet-membership-drift",
                            f"worker {w} is at membership epoch "
                            f"{peer_epoch}, we are at {membership.epoch} "
                            "— syncing membership instead of failing "
                            "the layout check",
                            worker=worker_id, peer=w,
                            peer_epoch=peer_epoch,
                            epoch=membership.epoch,
                        )
                        drifted.add(w)
                        pending.discard(w)
                        continue
                    raise RuntimeError(
                        f"fleet worker {w} runs a different parameter "
                        f"layout ({sig} vs {layout.signature()}) — all "
                        "workers must resolve the same config"
                    )
                # what this peer can DECODE (absent on pre-compression
                # peers: they get f32 pushes)
                peer_codecs[w] = payload.get("codecs")
                pending.discard(w)
            if pending:
                if time.monotonic() > deadline:
                    if rejoining:
                        log_event(
                            "fleet-peers-unreachable",
                            f"rejoined worker {worker_id}: peers "
                            f"{sorted(pending)} unreachable after "
                            f"{wait_s:.0f}s — proceeding (they may have "
                            "finished; lost RPCs are counted)",
                            worker=worker_id, peers=sorted(pending),
                        )
                        return
                    raise RuntimeError(
                        f"fleet peers never became reachable: "
                        f"{sorted(pending)} (waited {wait_s:.0f}s)"
                    )
                time.sleep(0.1)

    # ---- elastic membership: refresh / join / epoch-fenced re-shard --
    _join_throttle = {"t": -(10.0 ** 9)}

    def request_join(m: Membership) -> None:
        """First-class rejoin: ask ``m``'s lead to admit us at the next
        epoch boundary. We keep training meanwhile — our pushes stay
        epoch-fenced (counted) at the owners until the admit broadcast
        lands. Throttled: the pull loop hits a fence every step while
        we are out, and one join request per few seconds is plenty."""
        now = time.monotonic()
        if now - _join_throttle["t"] < 5.0:
            return
        _join_throttle["t"] = now
        lead = m.lead
        if lead == worker_id:
            return
        client = clients.get(lead)
        if client is None:
            client = clients[lead] = _PeerClient(
                urls[lead], timeout=peer_timeout
            )
        try:
            client.request(
                "POST", "/membership/join",
                body=json.dumps({"worker": worker_id}).encode("utf8"),
                content_type="application/json",
            )
        except OSError:
            return
        member_ledger.append(
            "join-requested", worker=worker_id, epoch=m.epoch
        )
        log_event(
            "fleet-join-requested",
            f"worker {worker_id} asked lead {lead} to rejoin the fleet "
            f"(their membership epoch {m.epoch})",
            worker=worker_id, lead=lead, epoch=m.epoch,
        )

    def refresh_membership(w: int) -> None:
        """Sync membership off peer ``w`` after a fence/drift signal:
        adopt its view when newer (queued — the step boundary applies
        it), or request a join when it no longer names us. Step-loop
        thread only (it shares the keep-alive clients)."""
        client = clients.get(w)
        if client is None:
            return
        try:
            status, _, body = client.request("GET", "/membership")
            if status != 200:
                return
            m = Membership.from_wire(json.loads(body.decode("utf8")))
        except (OSError, ValueError, KeyError, UnicodeDecodeError):
            return
        if m.epoch <= membership.epoch:
            return
        if worker_id in m:
            server.queue_membership(m)
        else:
            request_join(m)

    def apply_membership(new_m: Membership) -> None:
        """The epoch-fenced re-shard, at a step boundary only: recompute
        ownership over the new active set (same first-divisible-axis
        rule, survivor-rank addressed), adopt re-owned slices (params
        from this worker's ``params_host`` — the owners' last broadcast
        versions — and optimizer state carved from the last intact fleet
        checkpoint, fresh-init fallback), swap the OwnerState, and stamp
        the new epoch on everything downstream. Handler threads only
        QUEUE memberships; this runs exclusively on the step loop."""
        nonlocal membership, layout, owner, owns_any, quorum
        old_m, old_layout = membership, layout
        was_active = worker_id in old_m
        version_base = owner.version
        if was_active:
            # fold the live owner shard into params_host first: its
            # quorum applies since the last pull must survive the swap
            _, self_flat = owner.current_flat()
            old_layout.merge_flat(params_host, worker_id, self_flat)
        old_index = {
            k: old_layout.key_index(k, worker_id)
            for k in (old_layout.owned_keys(worker_id) if was_active else ())
        }
        membership = new_m
        layout = membership.layout(params_host)
        quorum = _quorum_for(len(membership.active))
        now_active = worker_id in membership
        changed = [
            k for k in layout.owned_keys(worker_id)
            if k not in old_index
            or old_index[k] != layout.key_index(k, worker_id)
        ] if now_active else []
        slice_np = layout.slice_tree(params_host, worker_id)
        new_slice = jax.tree_util.tree_map(jnp.asarray, slice_np)
        new_opt = None
        opt_src = "fresh-init"
        if now_active and not changed:
            # geometry unchanged (pure join/evict of a worker we took
            # nothing from): keep the live optimizer moments
            def _grab(cur_version, opt_state, host_flat):
                return cur_version, opt_state, host_flat

            _, new_opt, _ = owner.checkpoint_parts(_grab)
            opt_src = "live"
        elif now_active and output_path is not None:
            try:
                ck2 = TrainCheckpoint.load(Path(output_path) / "last-model")
                new_opt = local_opt_from_canonical(
                    owner_tx, layout, ck2["opt_state"], worker_id, slice_np
                )
                opt_src = f"checkpoint@{int(ck2['step'])}"
            except (CheckpointCorrupt, OSError, KeyError, ValueError,
                    TypeError):
                new_opt = None
        if new_opt is None:
            new_opt = owner_tx.init(new_slice)
            if changed:
                log_event(
                    "fleet-opt-reinit",
                    f"worker {worker_id}: no intact fleet checkpoint to "
                    f"carve adopted optimizer state from — fresh moments "
                    f"for {len(changed)} re-sharded slices",
                    worker=worker_id, epoch=membership.epoch,
                    resharded=len(changed),
                )
        new_owner = OwnerState(
            worker_id=worker_id,
            n_workers=n_workers,
            quorum=quorum,
            max_staleness=max_staleness,
            apply_fn=make_shard_apply(owner_tx),
            slice_params=new_slice,
            opt_state=new_opt,
            counters=counters,
            version=version_base,
            on_version=(
                version_gauge.set if version_gauge is not None else None
            ),
            registry=tel.registry if tel is not None else None,
            trace=tel.trace if tel is not None else None,
            delta_window=param_delta_window,
            delta_codec=wire_codec,
        )
        owner = new_owner
        server.set_owner(new_owner)
        server.set_membership(membership, layout.signature())
        owns_any = bool(layout.owned_keys(worker_id))
        # clients follow the active set
        for w in [w for w in list(clients) if w not in membership]:
            clients.pop(w).close()
            gone = ckpt_clients.pop(w, None)
            if gone is not None:
                gone.close()
            known.pop(w, None)
            last_stamp.pop(w, None)
            wire_full_bytes.pop(w, None)
            peer_codecs.pop(w, None)
        for w in membership.active:
            if w == worker_id or w in clients:
                continue
            clients[w] = _PeerClient(urls[w], timeout=peer_timeout)
            try:
                status, _, body = clients[w].request("GET", "/healthz")
                if status == 200:
                    peer_codecs[w] = json.loads(
                        body.decode("utf8")
                    ).get("codecs")
            except (OSError, ValueError):
                pass
        # the old epoch's version bookkeeping and delta chains are void
        # under the new slice geometry: force full re-pulls
        for w in clients:
            known[w] = -1
            last_stamp[w] = -(10 ** 9)
            flat_w = layout.flat_slices(params_host, w)
            if flat_w:
                wire_full_bytes[w] = len(encode_arrays(
                    {"worker": worker_id, "stamp": 0},
                    {k: np.asarray(v, np.float32)
                     for k, v in flat_w.items()},
                ))
            else:
                wire_full_bytes.pop(w, None)
        # grad-push error-feedback residuals telescope against slices
        # of the dead geometry — carrying them would corrupt
        compressor.reset()
        if changed:
            counters.inc("shards_adopted", len(changed))
        if epoch_gauge is not None:
            epoch_gauge.set(membership.epoch)
        member_ledger.append(
            "apply", worker=worker_id, epoch=membership.epoch,
            active=list(membership.active), resharded=len(changed),
            opt_source=opt_src,
        )
        log_event(
            "fleet-membership-applied",
            f"worker {worker_id}: membership epoch {membership.epoch} "
            f"applied (active {list(membership.active)}, "
            f"{len(changed)} slices re-sharded, optimizer {opt_src})",
            worker=worker_id, epoch=membership.epoch,
            active=list(membership.active), resharded=len(changed),
        )
        if was_active and not now_active:
            # the fleet moved on without us (a heal after a partition,
            # say): request readmission — our pushes are fenced until it
            log_event(
                "fleet-self-evicted",
                f"worker {worker_id}: membership epoch "
                f"{membership.epoch} no longer names this worker — "
                "requesting rejoin",
                worker=worker_id, epoch=membership.epoch,
            )
            request_join(membership)

    # ---- jitted gradient step ---------------------------------------
    def gstep(params, tokens, targets, rng_key):
        import optax

        (loss, metrics), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(params, tokens, targets, rng_key)
        gnorm = optax.global_norm(grads)
        if worker_clip > 0:
            scale = jnp.minimum(
                1.0, worker_clip / jnp.maximum(gnorm, 1e-16)
            )
            grads = jax.tree_util.tree_map(lambda g: g * scale, grads)
        return loss, metrics, grads, gnorm

    gstep_jit = jax.jit(gstep)

    def run_gstep(*args):
        with pctx.use_mesh(mesh):
            return gstep_jit(*args)

    # ---- logger / eval scaffolding (worker 0 reports) ----------------
    log_step: Callable[[Optional[Dict[str, Any]]], None]
    log_finalize: Callable[[], None]
    if worker_id == 0:
        import io as _io
        import sys as _sys

        logger_cfg = T.get("logger") or {
            "@loggers": "spacy_ray_tpu.ConsoleLogger.v1"
        }
        logger_setup = registry.resolve(logger_cfg)
        out_stream = _sys.stdout if stdout_log else _io.StringIO()
        log_step, log_finalize = logger_setup(nlp, out_stream, _sys.stderr)
        dev_examples = list(dev_corpus())
        score_weights = dict(T.get("score_weights") or {})
        if not score_weights:
            score_weights = default_pipeline_score_weights(nlp)
    else:
        log_step, log_finalize = (lambda info: None), (lambda: None)
        dev_examples = []
        score_weights = {}

    max_steps = int(max_steps_override or T["max_steps"] or 0)
    max_epochs = int(T["max_epochs"] or 0)
    eval_frequency = int(T["eval_frequency"] or 200)
    patience = int(T["patience"] or 0)
    keep_checkpoints = int(T.get("keep_checkpoints", 2) or 1)
    n_data = 1

    result = TrainResult()
    phases: Dict[str, float] = {p: 0.0 for p in PHASES}
    loss_accum: Dict[str, float] = {}
    known: Dict[int, int] = {w: -1 for w in clients}
    last_saved_step = -1 if resumed_from is None else resumed_from
    stop = False
    clean_exit = False  # set at normal loop exit; a crash leaves it False
    steps_run = 0
    words_since_log = 0
    start_time = time.perf_counter()
    last_log_time = start_time

    # ---- data stream (this worker's corpus shard) --------------------
    def batches():
        nonlocal epoch
        while True:
            stream = train_corpus()
            if n_workers > 1:
                stream = shard_stream(stream, worker_id, n_workers)
            got_any = False
            for b in batcher(stream):
                got_any = True
                yield b
            if not got_any:
                raise ValueError(
                    f"Training corpus is empty on worker {worker_id}'s "
                    "shard"
                )
            epoch += 1
            if max_epochs and epoch >= max_epochs:
                return

    last_stamp: Dict[int, int] = {w: -(10 ** 9) for w in clients}

    def pull_peers() -> Dict[int, int]:
        """Refresh non-owned shards; returns the version stamps the next
        push will carry (per owner).

        The staleness gate: a worker may run at most ``max_staleness``
        rounds ahead of any owner — it blocks (bounded by
        ``quorum_wait_s``) until owner ``w``'s version has passed
        ``last_stamp[w] - S``, i.e. until the round it last contributed
        to has closed, S rounds of slack allowed. At S=0 this is what
        makes quorum=N synchronous-equivalent: without it a fast worker
        re-pulls an owner mid-round, stamps the OLD version, and its
        push is discarded — wedging the round it was needed for."""
        stamps: Dict[int, int] = {}
        self_version, self_flat = owner.current_flat()
        if worker_id in membership:
            layout.merge_flat(params_host, worker_id, self_flat)
        stamps[worker_id] = self_version
        deadline = time.monotonic() + float(quorum_wait_s)
        # ask for delta frames only when we track a window ourselves; an
        # owner that can't serve one (old peer ignores the header, new
        # peer outside the window) replies with a full frame — degrade,
        # never stall (RESILIENCE.md). Every pull carries our membership
        # epoch: a re-sharded owner 409s a stale one (the fence), which
        # is our cue to sync membership instead of merging wrong-geometry
        # bytes.
        accept_hdrs: Dict[str, str] = {
            "X-SRT-Epoch": str(membership.epoch)
        }
        if param_delta_window > 0:
            accept_hdrs["X-SRT-Accept"] = "delta"
        fenced_by: Optional[int] = None
        for w, client in list(clients.items()):
            if backoff.skip(w):
                # mid-outage: zero wait spent on this owner (the
                # dead-owner pull-spin fix) — push against what we know
                stamps[w] = known.get(w, -1)
                continue
            timed_out = False
            unreachable = False
            while True:
                try:
                    if resilience.partitioned(w):
                        raise OSError(f"peer {w} partitioned (fault plan)")
                    maybe_fail("param-pull")
                    act = resilience.consume_wire_fault("param-pull")
                    if act is not None and act[0] == "delay":
                        time.sleep(float(act[1] or 1.0))
                    status, headers, body = client.request(
                        "GET", f"/params?known={known[w]}",
                        headers=accept_hdrs,
                    )
                    if act is not None and act[0] == "dup":
                        # duplicated request: idempotent GET, the second
                        # reply wins — proves re-reads are harmless
                        status, headers, body = client.request(
                            "GET", f"/params?known={known[w]}",
                            headers=accept_hdrs,
                        )
                    if act is not None and act[0] == "corrupt":
                        body = resilience.corrupt_bytes(body)
                except (OSError, resilience.FaultInjected):
                    counters.inc("pull_failed")
                    unreachable = True
                    break
                if status == 204:
                    v = int(headers.get("X-SRT-Version", known[w]))
                elif status == 409:
                    # epoch fence: the fleet re-sharded past us
                    fenced_by = w
                    break
                elif status == 200:
                    try:
                        meta_w, arrays = decode_arrays(body)
                        v = int(meta_w["version"])
                        is_delta = str(meta_w.get("codec") or "") == "delta"
                        deltas = None
                        if is_delta:
                            base = int(meta_w.get("base", -1))
                            if base != known[w]:
                                raise WireError(
                                    f"delta frame base {base} does not "
                                    f"match known version {known[w]}"
                                )
                            deltas = decode_delta_frame(meta_w, arrays)
                    except Exception:
                        counters.inc("pull_failed")
                        break
                    if is_delta:
                        layout.merge_flat(
                            params_host, w, deltas, add=True
                        )
                    else:
                        layout.merge_flat(params_host, w, arrays)
                    counters.inc("wire_pull_bytes", len(body))
                    counters.inc(
                        "wire_pull_bytes_uncompressed",
                        wire_full_bytes.get(w, len(body))
                        if is_delta else len(body),
                    )
                    if v < known[w]:
                        # a restarted owner legitimately REGRESSES to its
                        # checkpointed version: our round bookkeeping
                        # against the pre-crash lineage is void — reset it
                        # or the staleness gate below would block a full
                        # timeout every step waiting for versions that no
                        # longer exist
                        last_stamp[w] = -(10 ** 9)
                        log_event(
                            "fleet-owner-regressed",
                            f"owner {w} regressed to version {v} (knew "
                            f"{known[w]}) — it restarted from its "
                            "checkpoint; resyncing",
                            owner=w, version=v, known=known[w],
                        )
                    known[w] = v
                else:
                    counters.inc("pull_failed")
                    break
                if v > last_stamp[w] - max_staleness or timed_out:
                    stamps[w] = v
                    break
                if time.monotonic() > deadline:
                    timed_out = True  # one final fetch, then proceed
                    counters.inc("pull_wait_timeouts")
                    continue
                time.sleep(0.01)
            if unreachable or timed_out:
                # ONE structured event per outage, then capped backoff —
                # not a quorum_wait_s burn plus a counter tick every step
                if backoff.record_failure(w):
                    log_event(
                        "fleet-peer-unreachable",
                        f"worker {worker_id}: owner {w} "
                        f"{'unreachable' if unreachable else 'missing its staleness deadline'}"
                        f" — pulls back off (cap {backoff.cap_s:.0f}s) "
                        "until it answers again",
                        worker=worker_id, owner=w,
                        reason=(
                            "unreachable" if unreachable else "deadline"
                        ),
                    )
            elif fenced_by != w and backoff.record_success(w):
                log_event(
                    "fleet-peer-recovered",
                    f"worker {worker_id}: owner {w} answering again — "
                    "backoff cleared",
                    worker=worker_id, owner=w,
                )
            stamps.setdefault(w, known.get(w, -1))
        if fenced_by is not None:
            refresh_membership(fenced_by)
        return stamps

    def push_grads(grads: Any, stamps: Dict[int, int]) -> None:
        fenced_peer: Dict[str, Optional[int]] = {"w": None}
        for w in list(membership.active):
            flat = layout.flat_slices(grads, w)
            if not flat:
                continue  # nothing shardable lands on this owner
            if w == worker_id:
                # self-delivery is NOT counted as a push: grad_pushed is
                # the fleet-health signal (the push-stalled AbsenceRule
                # watches it), and an always-succeeding local submit
                # would keep it moving exactly when every peer is gone
                owner.submit(worker_id, stamps[worker_id], flat)
                continue
            if w not in clients:
                continue
            # per-peer negotiated codec: the error-feedback residual for
            # peer w absorbs THIS frame's quantization error and rides
            # into the next round's gradient for w (f32 keeps none)
            codec_w = negotiate_push_codec(wire_codec, peer_codecs.get(w))
            body = compressor.encode(
                w,
                {
                    "worker": worker_id,
                    "stamp": int(stamps.get(w, -1)),
                    "epoch": int(membership.epoch),
                },
                flat,
                codec_w,
            )
            # wire chaos (the drill matrix): one queued fault covers one
            # frame — a corrupted body stays corrupted across retries
            # (the owner 400s it every time: a counted, typed discard)
            act = resilience.consume_wire_fault("grad-push")
            dup = False
            if act is not None:
                if act[0] == "corrupt":
                    body = resilience.corrupt_bytes(body)
                elif act[0] == "delay":
                    time.sleep(float(act[1] or 1.0))
                elif act[0] == "dup":
                    dup = True

            def send(w=w, body=body, dup=dup):
                maybe_fail("grad-push")
                if resilience.partitioned(w):
                    raise OSError(f"peer {w} partitioned (fault plan)")
                status, _, reply = clients[w].request(
                    "POST", "/grad", body=body
                )
                if status != 200:
                    raise OSError(
                        f"peer {w} rejected grad push: HTTP {status}"
                    )
                if dup:
                    # duplicated frame: the owner's round bookkeeping
                    # takes one contribution per (worker, stamp) — the
                    # twin is a counted discard, never a double-apply
                    clients[w].request("POST", "/grad", body=body)
                try:
                    if json.loads(reply.decode("utf8")).get("fenced"):
                        fenced_peer["w"] = w
                except (ValueError, UnicodeDecodeError, AttributeError):
                    pass

            t_send = time.perf_counter()
            delivered = False
            try:
                retry_io("grad-push", send, policy=push_policy)
                counters.inc("grad_pushed")
                counters.inc("wire_push_bytes", len(body))
                counters.inc(
                    "wire_push_bytes_uncompressed",
                    wire_full_bytes.get(w, len(body)),
                )
                delivered = True
            except (OSError, resilience.FaultInjected):
                # fire-and-forget: a dead/unreachable owner costs a
                # counted drop, never a stalled fleet
                counters.inc("push_failed")
            if tel is not None:
                # the sender-side half of the cross-worker hop the merged
                # fleet timeline shows (owner-side twin: grad_apply)
                tel.trace.add_span(
                    "grad_push",
                    t_send,
                    time.perf_counter() - t_send,
                    cat="fleet",
                    args={
                        "to": w,
                        "stamp": int(stamps.get(w, -1)),
                        "delivered": delivered,
                        "codec": codec_w,
                        "bytes": len(body),
                    },
                )
            last_stamp[w] = int(stamps.get(w, -1))
        if fenced_peer["w"] is not None:
            # an owner fenced our frame: we are at a stale epoch — sync
            refresh_membership(fenced_peer["w"])

    def fleet_checkpoint() -> None:
        """Worker 0 coordinates one generation: every owner writes its
        own part (this process directly, peers via POST /checkpoint,
        which also returns an atomically-consistent copy of their param
        slices), then worker 0 assembles params and commits meta. Any
        unreachable peer aborts the generation (a committed meta naming
        a missing part would poison load()'s fallback walk) — the
        previous generation stays current."""
        nonlocal last_saved_step
        if output_path is None or step == last_saved_step:
            return
        if worker_id not in membership:
            return  # a fenced-out worker must not commit generations
        stamp = int(step)
        ckpt_dir = Path(output_path) / "last-model"
        my = checkpoint_cb(str(ckpt_dir), stamp)
        # part digests are keyed by survivor RANK: a post-failover
        # generation is a normal len(active)-shard v2 generation
        digests: Dict[int, str] = {
            int(my["meta"]["part"]): my["meta"]["digest"]
        }
        versions: List[Optional[int]] = [None] * n_workers
        rngs: List[Optional[List[int]]] = [None] * n_workers
        versions[worker_id] = int(my["meta"]["version"])
        rngs[worker_id] = list(my["meta"]["rng"])
        assembled = _np_tree(params_host)
        layout.merge_flat(assembled, worker_id, my["params"])
        req = json.dumps({
            "dir": str(ckpt_dir), "stamp": stamp,
            "epoch": int(membership.epoch),
        }).encode("utf8")
        for w in sorted(clients):
            try:
                maybe_fail("checkpoint-wire")
                if resilience.partitioned(w):
                    raise OSError(f"peer {w} partitioned (fault plan)")
                act = resilience.consume_wire_fault("checkpoint-wire")
                if act is not None and act[0] == "delay":
                    time.sleep(float(act[1] or 1.0))
                # a /checkpoint reply arrives only after the peer's whole
                # owner-shard part file is hashed and written — the 10s
                # step-traffic timeout would abort every generation on a
                # big model, so checkpoint coordination gets its own
                # long-deadline connections
                client = ckpt_clients.get(w)
                if client is None:
                    client = ckpt_clients[w] = _PeerClient(
                        urls[w], timeout=float(checkpoint_timeout_s)
                    )
                status, _, body = client.request(
                    "POST", "/checkpoint", body=req,
                    content_type="application/json",
                )
                if status != 200:
                    raise OSError(f"peer {w} checkpoint: HTTP {status}")
                if act is not None and act[0] == "dup":
                    # re-sent coordination request: same stamp, same
                    # part file — idempotent by construction
                    status, _, body = client.request(
                        "POST", "/checkpoint", body=req,
                        content_type="application/json",
                    )
                    if status != 200:
                        raise OSError(
                            f"peer {w} checkpoint: HTTP {status}"
                        )
                if act is not None and act[0] == "corrupt":
                    body = resilience.corrupt_bytes(body)
                meta_w, arrays = decode_arrays(body)
                digests[int(meta_w["part"])] = str(meta_w["digest"])
                versions[w] = int(meta_w["version"])
                rngs[w] = list(meta_w["rng"])
                layout.merge_flat(assembled, w, arrays)
            except (OSError, WireError, KeyError, ValueError, TypeError,
                    resilience.FaultInjected) as e:
                # unreachable, wire-malformed, meta-incomplete, or
                # structurally mismatched reply — ALL of them abort the
                # generation (the docstring's promise); a partial commit
                # naming a bad part would poison load()'s fallback walk,
                # and an exception here must not crash the lead's loop
                log_event(
                    "fleet-checkpoint-aborted",
                    f"worker {w} failed the checkpoint exchange at step "
                    f"{stamp} ({type(e).__name__}: {e}); keeping the "
                    "previous generation",
                    worker=w, step=stamp,
                )
                return
        commit_fleet_generation(
            ckpt_dir,
            params=assembled,
            step=stamp,
            epoch=epoch,
            rng=np.asarray(jax.device_get(rng)),
            best_score=best_score,
            best_step=best_step,
            opt_shards=len(membership.active),
            opt_digests=digests,
            extra={
                "fleet": {
                    "n_workers": n_workers,
                    "quorum": quorum,
                    "max_staleness": max_staleness,
                    "epoch": int(membership.epoch),
                    "active": list(membership.active),
                    "versions": versions,
                    "rngs": rngs,
                },
                "mesh": {"n_data": n_data, "update_sharding": "fleet"},
            },
            keep=keep_checkpoints,
        )
        last_saved_step = stamp

    # ---- convergence watch (lead-side, docs/OBSERVABILITY.md) --------
    # worker 0 polls every peer's /metrics on a slow daemon thread and
    # feeds the cross-worker divergence detector: a worker whose recent
    # loss median is an outlier vs its PEERS (or that is training on
    # NaNs, or whose arriving gradients keep being discarded) emits
    # through the anomaly chain — metrics row + trace instant + flight-
    # recorder bundle naming the worker — and bumps divergence_flags,
    # which the fleet-worker-diverging alert rule pages on. Telemetry
    # off constructs neither the detector nor the thread.
    watch_stop = threading.Event()
    watch_thread: Optional[threading.Thread] = None
    if tel is not None and worker_id == 0 and n_workers > 1:
        from ..telemetry import FleetDivergenceDetector

        div_counter = tel.registry.counter("divergence_flags")

        def _emit_divergence(event: str, message: str, **fields: Any) -> None:
            div_counter.inc()
            tel._emit_anomaly(event, message, **fields)

        divergence = FleetDivergenceDetector(_emit_divergence)

        def _watch_stats(payload: Dict[str, Any]) -> Dict[str, Any]:
            counters_p = payload.get("counters") or {}
            loss_h = (payload.get("histograms") or {}).get("loss") or {}
            return {
                "loss": loss_h.get("p50"),
                "steps": counters_p.get("steps"),
                "received": counters_p.get("grad_received"),
                "discarded": counters_p.get("grad_discarded"),
                "loss_nonfinite": counters_p.get("loss_nonfinite"),
            }

        def _watch_loop() -> None:
            # the step loop's keep-alive peer connections are NOT
            # thread-safe; the watch owns its own clients
            watch_clients = {
                w: _PeerClient(urls[w], timeout=probe_timeout)
                for w in clients
            }
            try:
                while not watch_stop.wait(float(watch_interval_s)):
                    stats = {
                        worker_id: _watch_stats(tel.registry.snapshot())
                    }
                    for w, client in watch_clients.items():
                        try:
                            status, _, body = client.request(
                                "GET", "/metrics"
                            )
                            if status != 200:
                                continue
                            stats[w] = _watch_stats(
                                json.loads(body.decode("utf8"))
                            )
                        except (OSError, ValueError):
                            continue  # an exiting peer: no-signal, no crash
                    try:
                        divergence.observe(stats)
                    except Exception:
                        logger.exception("fleet divergence watch failed")
            finally:
                for client in watch_clients.values():
                    client.close()

        watch_thread = threading.Thread(
            target=_watch_loop, name="fleet-watch", daemon=True
        )

    # ---- lease-based liveness + the eviction verdict -----------------
    # EVERY worker runs the tracker; only the ACTING LEAD — the lowest
    # active id it still believes live — issues verdicts. Lead death
    # therefore falls through to the next survivor deterministically,
    # no election. Verdicts and admits are queued/broadcast here but
    # APPLIED only at step boundaries (apply_membership), so handler
    # threads and this thread never touch the layout.
    member_stop = threading.Event()
    member_thread: Optional[threading.Thread] = None
    if n_workers > 1 and peer_lease_s > 0:
        def _membership_loop() -> None:
            # own clients: the step loop's keep-alive connections are
            # not thread-safe (same rule as the watch loop)
            probes = {
                w: _PeerClient(urls[w], timeout=probe_timeout)
                for w in range(n_workers) if w != worker_id
            }
            tracker = LeaseTracker(
                [w for w in membership.active if w != worker_id],
                lease_s=peer_lease_s,
                miss_threshold=lease_miss_threshold,
            )
            # epoch of our own last QUEUED verdict: a verdict applies
            # only at the step loop's next boundary, so without this the
            # lead would re-evict (and re-count, and re-log) the same
            # peer every poll round until the apply lands
            verdict_epoch = 0
            try:
                while not member_stop.wait(lease_poll_s):
                    m = membership  # one snapshot per round
                    if worker_id not in m:
                        continue  # fenced-out: no verdicts while stale
                    if m.epoch < verdict_epoch:
                        continue  # our verdict is still pending apply
                    for w in list(tracker.peers()):
                        if w not in m:
                            tracker.remove(w)
                    for w in m.active:
                        if w != worker_id:
                            tracker.add(w)
                    drift_from: Optional[int] = None
                    for w in m.active:
                        if w == worker_id:
                            continue
                        ok = False
                        try:
                            status, _, body = probes[w].request(
                                "GET", "/healthz"
                            )
                            if status == 200:
                                ok = True
                                pe = json.loads(
                                    body.decode("utf8")
                                ).get("epoch")
                                if (
                                    isinstance(pe, int)
                                    and not isinstance(pe, bool)
                                    and pe > m.epoch
                                ):
                                    drift_from = w
                        except (OSError, ValueError):
                            ok = False
                        tracker.observe(w, ok)
                    if drift_from is not None:
                        # a peer is ahead of us — we missed a broadcast;
                        # pull its membership and queue it
                        try:
                            status, _, body = probes[drift_from].request(
                                "GET", "/membership"
                            )
                            if status == 200:
                                mm = Membership.from_wire(
                                    json.loads(body.decode("utf8"))
                                )
                                if mm.epoch > m.epoch and worker_id in mm:
                                    server.queue_membership(mm)
                        except (OSError, ValueError, KeyError,
                                UnicodeDecodeError):
                            pass
                        continue  # re-probe under the new membership
                    live = [
                        w for w in m.active
                        if w == worker_id or not tracker.dead(w)
                    ]
                    if not live or min(live) != worker_id:
                        continue  # not the acting lead this round
                    new_m = m
                    dead = [w for w in m.active if w not in live]
                    for w in dead:
                        new_m = new_m.evict(w)
                    joiners = sorted(
                        int(j) for j in server.drain_join_requests()
                        if isinstance(j, int)
                        and 0 <= int(j) < n_workers
                        and int(j) not in new_m
                    )
                    for j in joiners:
                        new_m = new_m.admit(j)
                    if new_m.epoch == m.epoch:
                        continue
                    if dead:
                        counters.inc("evictions", len(dead))
                        member_ledger.append(
                            "evict", lead=worker_id, evicted=dead,
                            epoch=new_m.epoch,
                            active=list(new_m.active),
                        )
                        log_event(
                            "fleet-owner-evicted",
                            f"acting lead {worker_id}: evicting {dead} "
                            f"(lease {peer_lease_s:.0f}s and "
                            f"{lease_miss_threshold} consecutive misses "
                            f"both expired) — membership epoch "
                            f"{new_m.epoch}, survivors "
                            f"{list(new_m.active)}",
                            lead=worker_id, evicted=dead,
                            epoch=new_m.epoch,
                            active=list(new_m.active),
                        )
                    if joiners:
                        member_ledger.append(
                            "admit", lead=worker_id, admitted=joiners,
                            epoch=new_m.epoch,
                            active=list(new_m.active),
                        )
                        log_event(
                            "fleet-worker-admitted",
                            f"acting lead {worker_id}: admitting "
                            f"{joiners} at membership epoch "
                            f"{new_m.epoch}",
                            lead=worker_id, admitted=joiners,
                            epoch=new_m.epoch,
                        )
                    verdict_epoch = new_m.epoch
                    wire_m = json.dumps(new_m.to_wire()).encode("utf8")
                    for w in new_m.active:
                        if w == worker_id:
                            continue
                        try:
                            probes[w].request(
                                "POST", "/membership", body=wire_m,
                                content_type="application/json",
                            )
                        except OSError:
                            pass  # it will drift-sync off /healthz
                    server.queue_membership(new_m)
            finally:
                for c in probes.values():
                    c.close()

        member_thread = threading.Thread(
            target=_membership_loop, name="fleet-membership", daemon=True
        )

    # ---- resilience arming ------------------------------------------
    watchdog: Optional[Watchdog] = None
    watchdog_timeout = float(T.get("watchdog_timeout_s", 0) or 0)
    if watchdog_timeout > 0:
        def watchdog_stats():
            if tel is not None:
                tel.emergency_flush()
            return {
                "fleet_worker": worker_id,
                "version": owner.version,
                **counters.snapshot(),
            }

        watchdog = Watchdog(watchdog_timeout, stats_fn=watchdog_stats)
    if install_signal_handlers:
        shutdown.install()
    if watchdog is not None:
        watchdog.start()
    wait_for_peers()
    for w in sorted(drifted):
        refresh_membership(w)
    if n_workers > 1 and worker_id not in membership:
        request_join(membership)
    if tel is not None:
        tel.loop_start()
    if watch_thread is not None:
        watch_thread.start()
    if member_thread is not None:
        member_thread.start()

    def note_phase(name: str, t0: float, t1: float) -> None:
        """One phase's wall time: the ledger accumulator, the shared-
        bucket histogram, and (inside the trace window) a span on this
        worker's track — one stamp pair feeds all three surfaces."""
        d = t1 - t0
        phases[name] += d
        if phase_hists is not None:
            phase_hists[name].observe(d)
            tel.trace.add_span(
                f"phase_{name}", t0, d, cat="fleet",
                args={"step": step + 1},
            )

    try:
        batch_iter = batches()
        while not stop:
            # step boundary: adopt any queued membership (a lead
            # broadcast, our own verdict, or a drift-sync) before any
            # frame of this step is stamped
            pending_m = server.take_pending_membership()
            if pending_m is not None and pending_m.epoch > membership.epoch:
                apply_membership(pending_m)
            t_data = time.perf_counter()
            try:
                b = next(batch_iter)
            except StopIteration:
                break
            max_len = max(len(eg) for eg in b)
            T_pad = bucket_length(max_len, nlp.length_buckets)
            B_pad = bucket_batch_size(len(b))
            collated = nlp.collate(
                b, pad_batch_to=B_pad, pad_len_to=T_pad, host=True
            )
            tokens, targets = collated["tokens"], collated["targets"]
            n_words = int(collated["n_words"])
            now = time.perf_counter()
            note_phase("data", t_data, now)

            t_pull = now
            stamps = pull_peers()
            now = time.perf_counter()
            note_phase("pull", t_pull, now)

            maybe_fail("step")
            poisoned = resilience.consume_poison("step")
            t_grad = now
            rng, sub = jax.random.split(rng)
            state_holder["rng"] = rng
            loss, metrics, grads, gnorm = run_gstep(
                params_host, tokens, targets, sub
            )
            grads = jax.tree_util.tree_map(
                lambda g: np.asarray(jax.device_get(g)), grads
            )
            now = time.perf_counter()
            note_phase("grad", t_grad, now)

            t_push = now
            push_grads(grads, stamps)
            now = time.perf_counter()
            note_phase("push", t_push, now)

            t_wait = now
            if owns_any:
                wait_deadline = time.monotonic() + float(quorum_wait_s)
                reached = False
                wait_fenced = False
                while True:
                    remaining = wait_deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    if owner.wait_version_above(
                        stamps[worker_id], min(0.25, remaining)
                    ):
                        reached = True
                        break
                    pending_epoch = server.pending_membership_epoch()
                    if (
                        pending_epoch is not None
                        and pending_epoch > membership.epoch
                    ):
                        # an eviction verdict is queued: survivors
                        # already stamp the NEW epoch, so this epoch's
                        # quorum can never complete — yield to the
                        # apply at the top of the next iteration
                        wait_fenced = True
                        break
                if not reached and not wait_fenced:
                    counters.inc("apply_wait_timeouts")
                    log_event(
                        "fleet-quorum-timeout",
                        f"worker {worker_id}: own shard stuck at version "
                        f"{owner.version} for {quorum_wait_s:.0f}s (quorum "
                        f"{quorum} not reached) — proceeding",
                        worker=worker_id, version=owner.version,
                    )
            note_phase("apply_wait", t_wait, time.perf_counter())

            step += 1
            steps_run += 1
            state_holder["step"] = step
            result.words_seen += n_words
            words_since_log += n_words
            loss_val = float("nan") if poisoned else float(loss)
            for key, value in jax.device_get(metrics).items():
                if key.startswith("loss_"):
                    v = float("nan") if poisoned else float(value)
                    loss_accum[key[5:]] = loss_accum.get(key[5:], 0.0) + v
            if tel is not None:
                # per-step loss streaming: the row lands in metrics.jsonl
                # (the run report's loss trajectories) and the recent-
                # median ring is what the lead's convergence watch polls
                tel.step_boundary(
                    step=step, epoch=epoch, n_words=n_words,
                    steps_run=steps_run, loss=loss_val,
                )

            info: Optional[Dict[str, Any]] = None
            if worker_id == 0 and step % eval_frequency == 0:
                eval_t0 = time.perf_counter()
                scores = nlp.evaluate(dev_examples, params_host, mesh=mesh)
                eval_seconds = time.perf_counter() - eval_t0
                score = weighted_score(scores, score_weights)
                now2 = time.perf_counter()
                wps = words_since_log / max(now2 - last_log_time, 1e-9)
                last_log_time = now2
                words_since_log = 0
                info = {
                    "epoch": epoch,
                    "step": step,
                    "words": result.words_seen,
                    "losses": dict(loss_accum),
                    "other_scores": scores,
                    "score": score,
                    "wps": wps,
                    "eval_seconds": eval_seconds,
                    "fleet": {
                        "worker": worker_id,
                        "version": owner.version,
                        **counters.snapshot(),
                    },
                }
                result.history.append(info)
                loss_accum = {}
                if score > best_score:
                    best_score = score
                    best_step = step
                    if output_path is not None:
                        nlp.params = params_host
                        nlp.to_disk(Path(output_path) / "best-model")
                fleet_checkpoint()
                if tel is not None:
                    tel.rearm_step_clock()
            elif (
                worker_id != 0
                and worker_id == membership.lead
                and step % eval_frequency == 0
            ):
                # lead failover: the acting lead inherits CHECKPOINT
                # duty (scores pause — the dev corpus and logger live on
                # worker 0 — but the lineage keeps committing;
                # RESILIENCE.md "Ownership failover")
                fleet_checkpoint()
                if tel is not None:
                    tel.rearm_step_clock()
            log_step(info)
            if watchdog is not None:
                watchdog.beat()

            if max_steps and step >= max_steps:
                stop = True
            if (
                worker_id == 0
                and patience
                and best_step >= 0
                and (step - best_step) >= patience
            ):
                stop = True
            if (
                not stop
                and worker_id != membership.lead
                and server.finalize_event.is_set()
            ):
                # the lead finished (patience, max_steps, preemption) and
                # committed its final generation: follow it instead of
                # training headless to our own max_steps — progress past
                # this point could never be checkpointed (worker 0 owns
                # the commit) and every push to it would be a dead letter
                log_event(
                    "fleet-finalized",
                    f"worker {worker_id}: lead worker finalized the "
                    f"fleet at our step {step} — stopping",
                    worker=worker_id, step=step,
                )
                stop = True
            if not stop and shutdown.coordinated_stop(1):
                if worker_id == membership.lead:
                    fleet_checkpoint()
                result.interrupted = True
                log_event(
                    "preempted",
                    f"fleet worker {worker_id}: shutdown signal at step "
                    f"{step}; resume with --resume",
                    step=step, worker=worker_id,
                )
                stop = True
        clean_exit = True
    finally:
        if watchdog is not None:
            watchdog.stop()
        watch_stop.set()
        member_stop.set()
        if watch_thread is not None:
            watch_thread.join(timeout=5.0)
        if member_thread is not None and member_thread.is_alive():
            member_thread.join(timeout=5.0)
        if install_signal_handlers:
            shutdown.restore()
        try:
            if worker_id == membership.lead:
                # finalize ONLY on a clean exit (max_steps / patience /
                # preemption): a CRASHED lead is about to be relaunched
                # with --resume by its supervisor, and broadcasting
                # /finalize here would shut down the very peers it needs
                # to rejoin — the survivors-keep-stepping contract.
                # membership.lead, not literal 0: after a lead failover
                # the acting lead owns the final commit and broadcast
                if clean_exit:
                    if not result.interrupted:
                        fleet_checkpoint()
                    for w, client in clients.items():
                        try:
                            client.request(
                                "POST", "/finalize", body=b"{}",
                                content_type="application/json",
                            )
                        except OSError:
                            pass
            elif clean_exit:
                # keep serving /grad, /params and /checkpoint until the
                # lead finishes its final generation: with quorum < N a
                # non-evaluating peer finishes max_steps well BEFORE the
                # lead (eval/checkpoint overhead is lead-only), and
                # shutting this server early would abort the lead's
                # final commit. Patience is bounded two ways: the long
                # finalize_wait_s deadline, and a lead-liveness probe —
                # a DEAD lead (past its restart cap) will never post
                # /finalize, and waiting the full deadline for it would
                # just delay this worker's own ledger
                lead = clients.get(membership.lead)
                deadline = time.monotonic() + float(finalize_wait_s)
                lead_misses = 0
                while not server.finalize_event.wait(timeout=5.0):
                    if time.monotonic() > deadline:
                        break
                    if lead is None:
                        continue
                    try:
                        lead.request("GET", "/healthz")
                        lead_misses = 0
                    except OSError:
                        lead_misses += 1
                        if lead_misses >= 2:
                            log_event(
                                "fleet-lead-gone",
                                f"worker {worker_id}: lead unreachable "
                                "while awaiting finalize — exiting",
                                worker=worker_id,
                            )
                            break
        finally:
            result.seconds = time.perf_counter() - start_time
            result.best_score = best_score
            result.best_step = best_step
            result.final_step = step
            result.epoch = epoch
            result.fleet = {
                "worker": worker_id,
                "n_workers": n_workers,
                "quorum": quorum,
                "max_staleness": max_staleness,
                "version": owner.version,
                "membership_epoch": int(membership.epoch),
                "active": list(membership.active),
                "grad_compression": wire_codec,
                "param_delta_window": param_delta_window,
                "counters": counters.snapshot(),
                "phases": {p: round(v, 6) for p, v in phases.items()},
                "owner_apply_seconds": round(owner.apply_seconds, 6),
            }
            if output_path is not None:
                out = Path(output_path)
                out.mkdir(parents=True, exist_ok=True)
                ledger = {
                    "worker": worker_id,
                    "steps": step,
                    "words_seen": result.words_seen,
                    "seconds": round(result.seconds, 6),
                    "interrupted": result.interrupted,
                    "resumed_from": resumed_from,
                    **result.fleet,
                }
                (out / f"fleet-worker-{worker_id}.json").write_text(
                    json.dumps(ledger, indent=2), encoding="utf8"
                )
            if tel is not None:
                # the kind:"fleet" exit row: the dynamics histograms'
                # final snapshots ride into metrics.jsonl so the run
                # report and `telemetry summarize` can digest them
                # offline (the in-memory registry dies with the process)
                snap_h = tel.registry.snapshot().get("histograms") or {}
                tel.append_row({
                    "kind": "fleet",
                    "worker": worker_id,
                    "n_workers": n_workers,
                    "quorum": quorum,
                    "max_staleness": max_staleness,
                    "version": owner.version,
                    "membership_epoch": int(membership.epoch),
                    "active": list(membership.active),
                    "grad_compression": wire_codec,
                    "param_delta_window": param_delta_window,
                    "counters": counters.snapshot(),
                    "phases": {p: round(v, 6) for p, v in phases.items()},
                    "histograms": {
                        k: v for k, v in snap_h.items()
                        if k in ("staleness", "quorum_wait_seconds",
                                 "apply_seconds", "loss")
                        or k.startswith("phase_")
                    },
                })
            for client in clients.values():
                client.close()
            for client in ckpt_clients.values():
                client.close()
            server.stop()
            if tel is not None:
                tel.finalize()
    nlp.params = params_host
    if worker_id == membership.lead and output_path is not None:
        nlp.to_disk(Path(output_path) / "last-model")
    log_finalize()
    return nlp, result
