"""Cross-process asynchronous trainer fleet (PAPER.md §L3 ``RayPeerProxy``).

The paper's actual training core — every parameter owned by exactly one
worker, fire-and-forget gradient push to the owner, optimizer applied at
quorum, version-check discard of stale gradients — reproduced ACROSS
processes over the same stdlib-HTTP idiom the serving fleet proved out
(serving/fleet/), sidestepping this container's missing multi-process CPU
collectives (``test_multihost`` stays capability-skipped).

Modules:

* :mod:`.ownership` — the host-side owner-shard layout (the same
  first-divisible-axis rule as :func:`~...parallel.mesh.zero1_spec`, so
  fleet workers own exactly the shards the v2 checkpoint format writes
  as per-owner part files) and the local↔canonical optimizer-state
  mapping elastic cross-process resume stands on;
* :mod:`.wire` — the pickle-free array codec gradients and parameters
  ride over HTTP in (json header + raw little-endian bytes — an open
  port must never ``pickle.load`` client bytes, the PR 8 rule);
* :mod:`.peer` — :class:`~.peer.OwnerState` (quorum buffer, staleness
  discard, versioned apply via the single-shard jitted update) and the
  per-worker HTTP peer server (``/grad``, ``/params``, ``/checkpoint``,
  plus the standard trainer telemetry surface ``/metrics``/``/healthz``/
  ``/trace`` that ``telemetry top`` and Prometheus already scrape);
* :mod:`.worker` — the per-process async training loop (pull → grad →
  push → apply-wait, with per-phase timing);
* :mod:`.coordinator` — spawns and supervises the N worker processes
  (1-core pinning, crash restarts with ``--resume``, SIGTERM drain).
"""

from .ownership import OwnershipLayout, shard_axis  # noqa: F401
