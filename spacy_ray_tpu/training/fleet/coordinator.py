"""Trainer-fleet coordinator: spawn and supervise the N worker processes.

The jax-free parent (the ``train --fleet-workers N`` entry): one
:class:`~..resilience.Supervisor` per worker on its own thread, so a
crashed worker is relaunched WITH ``--resume`` (it reloads the last
committed fleet generation, rejoins the peer plane, and its first
stale-stamped push is discarded and counted — the SIGKILL drill's
recovery path) while the survivors keep stepping at quorum. Signals to
the coordinator fan out to every supervisor
(:meth:`~..resilience.Supervisor.request_shutdown` — SIGTERM → SIGKILL
escalation per child), and a relayed shutdown is a clean preemption
(``RC_PREEMPTED``), not a restart.

CPU pinning follows the serving fleet's idiom (PR 6): on a CPU device
each worker gets a ``taskset -c`` core mask cycled from ``cpu_cores``
(or this process's affinity set with ``"auto"``) — unmasked co-scheduled
jax processes thrash each other's XLA thread pools into negative
scaling.
"""

from __future__ import annotations

import shutil
import signal
import sys
import threading
from typing import Any, Dict, List, Optional

from ..resilience import RC_PREEMPTED, Supervisor, log_event

__all__ = ["FLEET_SHUTDOWN_GRACE_S", "run_fleet"]

# SIGTERM → SIGKILL escalation window for fleet workers. Deliberately
# much longer than the serving fleet's 10s: worker 0's preemption path
# finishes the in-flight step and then commits a DISTRIBUTED generation
# (N-1 HTTP /checkpoint round trips shipping full param slices), and the
# peers must stay alive to serve those writes — a 10s grace would SIGKILL
# the commit mid-flight on any non-toy model. terminate_with_grace only
# waits this long for a child that ignores SIGTERM; a clean preemption
# exits the moment its checkpoint lands.
FLEET_SHUTDOWN_GRACE_S = 120.0


def _worker_cmd(
    child_argv: List[str],
    worker_id: int,
    attempt: int,
    taskset_prefix: Optional[List[str]],
) -> List[str]:
    cmd = list(taskset_prefix or []) + [
        sys.executable, "-m", "spacy_ray_tpu", "train",
    ] + list(child_argv) + ["--fleet-worker-id", str(worker_id)]
    if attempt > 0 and "--resume" not in cmd:
        cmd.append("--resume")  # rejoin from the last committed generation
    return cmd


def run_fleet(
    child_argv: List[str],
    *,
    n_workers: int,
    max_restarts: int = 0,
    cpu_cores: Optional[List[str]] = None,
    pin_cores: bool = True,
    grace_s: float = FLEET_SHUTDOWN_GRACE_S,
) -> int:
    """Run the fleet to completion; returns the tree's exit code.

    ``child_argv`` is the worker-side ``train`` argv (config path, fleet
    knobs, output, …) WITHOUT ``--fleet-worker-id`` — each worker gets
    its own id appended. Exit code: 0 when every worker exits 0;
    ``RC_PREEMPTED`` for a relayed shutdown; otherwise the first
    non-zero worker code (a worker that kept dying past
    ``max_restarts``).
    """
    n_workers = int(n_workers)
    taskset = shutil.which("taskset") if pin_cores else None
    if pin_cores and cpu_cores and taskset is None:
        log_event(
            "fleet-pinning-unavailable",
            "cpu_cores set but taskset is unavailable; fleet workers run "
            "unpinned (expect thrash between co-scheduled XLA pools)",
        )
    supervisors: List[Supervisor] = []
    for w in range(n_workers):
        prefix: Optional[List[str]] = None
        if taskset is not None and cpu_cores:
            prefix = [taskset, "-c", cpu_cores[w % len(cpu_cores)]]

        def build_cmd(attempt: int, w=w, prefix=prefix) -> List[str]:
            return _worker_cmd(child_argv, w, attempt, prefix)

        supervisors.append(
            Supervisor(build_cmd, max_restarts, grace_s=grace_s)
        )

    rcs: Dict[int, int] = {}
    threads: List[threading.Thread] = []
    for w, sup in enumerate(supervisors):
        t = threading.Thread(
            target=lambda w=w, sup=sup: rcs.__setitem__(w, sup.run()),
            name=f"fleet-supervisor-{w}",
            daemon=True,
        )
        threads.append(t)

    relayed = threading.Event()

    def _relay(signum: int, frame: Any) -> None:
        relayed.set()
        for sup in supervisors:
            sup.request_shutdown()

    prev_handlers: Dict[int, Any] = {}
    in_main = threading.current_thread() is threading.main_thread()
    if in_main:
        for signum in (signal.SIGTERM, signal.SIGINT):
            try:
                prev_handlers[signum] = signal.signal(signum, _relay)
            except (ValueError, OSError):  # pragma: no cover
                pass
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        if in_main:
            for signum, prev in prev_handlers.items():
                try:
                    signal.signal(signum, prev)
                except (ValueError, OSError):  # pragma: no cover
                    pass
    if relayed.is_set():
        return RC_PREEMPTED
    codes = [rcs.get(w, 1) for w in range(n_workers)]
    if all(rc == 0 for rc in codes):
        return 0
    if any(rc == RC_PREEMPTED for rc in codes):
        return RC_PREEMPTED
    if any(rc == 0 for rc in codes):
        # elastic membership (RESILIENCE.md "Ownership failover"): a
        # worker that died past its restart budget was lease-evicted and
        # its shards re-owned; the survivors finishing CLEANLY means the
        # lineage committed to convergence without it. That is the
        # designed degraded outcome, not a fleet failure — report
        # success, loudly.
        lost = [w for w, rc in enumerate(codes) if rc != 0]
        log_event(
            "fleet-degraded-success",
            f"workers {lost} exhausted their restart budget (exit codes "
            f"{codes}) and were evicted; the survivors finished cleanly "
            "— reporting rc=0",
            codes=codes, lost=lost,
        )
        return 0
    first_bad = next(rc for rc in codes if rc != 0)
    log_event(
        "fleet-failed",
        f"fleet worker exit codes {codes}; reporting rc={first_bad}",
        codes=codes,
    )
    return first_bad
