"""The fleet worker's peer plane: owner state + HTTP server.

:class:`OwnerState` is the paper's ``RayPeerProxy`` owner side
(PAPER.md §L3; reference proxies.py:111-133): it holds the one
authoritative copy of this worker's owned parameter slices and their
optimizer state, buffers incoming gradients keyed by sender, DISCARDS
(and counts) gradients whose version stamp is more than ``max_staleness``
behind the current shard version, and applies the optimizer — the jitted
single-shard update from :func:`~...parallel.step.make_shard_apply` —
the moment ``quorum`` distinct workers' gradients are buffered, bumping
the shard version.

:class:`PeerServer` is the stdlib-HTTP shell around it (the serving
fleet's proven idiom): ``POST /grad`` (wire.py payloads — never pickle),
``GET /params`` (version-gated slice pull; 204 = already current),
``POST /checkpoint`` (write my owner-shard v2 part file, reply digest +
an atomic same-version copy of my param slices), ``POST /finalize``, and
the standard trainer telemetry surface — ``GET /metrics`` (JSON or
Prometheus with a ``worker`` label on every family), ``/healthz`` (clock
anchor + layout signature), ``/trace``, ``/admin/alerts`` — so
``telemetry top``, Prometheus scrapers, and ``telemetry collect-trace``
see each fleet worker exactly as they see a plain trainer.
"""

from __future__ import annotations

import json
import logging
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse

import numpy as np

from .membership import Membership
from .ownership import iter_leaves, path_key, tree_from_flat
from .wire import (
    WIRE_CODECS,
    WireError,
    _compress_leaf,
    decode_grads,
    encode_arrays,
    encode_delta_frame,
    frame_epoch,
)

#: hard request-body ceiling (bytes) — a frame bigger than any sane
#: gradient/checkpoint payload for this repo's models is hostile or
#: corrupt input, and reading it into memory before discovering that is
#: the damage. 413 + counted discard, never an allocation stampede.
MAX_BODY_BYTES = 1 << 30

logger = logging.getLogger("spacy_ray_tpu.training")

__all__ = ["FleetCounters", "OwnerState", "PeerServer"]

# counter names are chosen so the Prometheus rendering (prefix
# srt_training, counters get _total) yields the observability plane's
# documented series: srt_training_grad_{pushed,applied,discarded}_total
COUNTER_NAMES = (
    "grad_pushed",      # worker-side: payloads delivered to PEER owners
                        # (self-delivery excluded: this is the alert
                        # plane's "is this worker talking to its fleet"
                        # signal, which a local submit must not feed)
    "grad_received",    # owner-side: payloads that arrived at this owner
    "grad_applied",     # owner-side: buffered contributions folded into applies
    "grad_discarded",   # owner-side: stale-version payloads dropped
    "push_failed",      # worker-side: pushes that exhausted their retries
    "pull_failed",      # worker-side: parameter pulls that failed
    "apply_wait_timeouts",  # worker-side: quorum waits that timed out
    "pull_wait_timeouts",   # worker-side: staleness-gate waits that timed out
    "applies",          # owner-side: optimizer applies (version bumps)
    # wire-byte accounting (the compression ledger): bytes actually on
    # the wire vs what the same payloads would have cost as PR 14 f32
    # frames — the _uncompressed twins make the ratio computable from
    # any scrape. Counted on the SENDING/REQUESTING worker: pushes when
    # delivered, pulls on a 200 body.
    "wire_push_bytes",
    "wire_push_bytes_uncompressed",
    "wire_pull_bytes",
    "wire_pull_bytes_uncompressed",
    # elastic-membership ledger (PR 17): frames carrying a stale/foreign
    # membership epoch that were counted-discarded at the fence, peers
    # this worker (as acting lead) declared dead, and orphaned param
    # leaves this worker adopted at a re-shard. Prometheus names:
    # srt_training_{epoch_fenced,evictions,shards_adopted}_total.
    "epoch_fenced",
    "evictions",
    "shards_adopted",
)


class FleetCounters:
    """The fleet ledger: plain thread-safe ints that exist with or
    without telemetry (the result file / CI discard ledger reads them),
    optionally mirrored into a ``MetricsRegistry``'s counters so the
    /metrics surfaces and alert rules see the same numbers."""

    def __init__(self, registry: Any = None) -> None:
        self._v: Dict[str, int] = {n: 0 for n in COUNTER_NAMES}
        self._lock = threading.Lock()
        self._mirror = (
            {n: registry.counter(n) for n in COUNTER_NAMES}
            if registry is not None
            else None
        )

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._v[name] += int(n)
        if self._mirror is not None:
            self._mirror[name].inc(n)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._v)


class OwnerState:
    """Authoritative owner of this worker's parameter slices.

    Apply policy (the knob-controlled async core): an arriving gradient
    stamped ``s`` against current version ``v`` is

    * buffered when ``s == v`` (current round);
    * buffered when ``0 < v - s <= max_staleness`` (bounded staleness —
      a late gradient still contributes to the CURRENT state, classic
      async-SGD semantics);
    * discarded and counted otherwise — too stale, or stamped with a
      FUTURE version (a peer pushing against a pre-crash cache after
      this owner restarted and rolled back to its checkpoint).

    The buffer is keyed by sender (a worker re-pushing before an apply
    overwrites its previous contribution); once ``quorum`` distinct
    senders are buffered the mean gradient goes through the jitted
    shard apply, the version bumps, and waiters are notified.
    """

    def __init__(
        self,
        *,
        worker_id: int,
        n_workers: int,
        quorum: int,
        max_staleness: int,
        apply_fn: Callable,
        slice_params: Any,
        opt_state: Any,
        counters: FleetCounters,
        version: int = 0,
        on_version: Optional[Callable[[int], None]] = None,
        clock: Callable[[], float] = time.monotonic,
        registry: Any = None,
        trace: Any = None,
        delta_window: int = 0,
        delta_codec: str = "int8",
        delta_budget_bytes: int = 8 << 20,
    ) -> None:
        if not (1 <= quorum <= n_workers):
            raise ValueError(
                f"quorum must be in [1, {n_workers}], got {quorum}"
            )
        if max_staleness < 0:
            raise ValueError(f"max_staleness must be >= 0, got {max_staleness}")
        self.worker_id = int(worker_id)
        self.n_workers = int(n_workers)
        self.quorum = int(quorum)
        self.max_staleness = int(max_staleness)
        self.apply_fn = apply_fn
        self.params = slice_params  # device tree (nested dict)
        self.opt_state = opt_state  # device tree
        self.counters = counters
        self.version = int(version)
        self.on_version = on_version
        self.clock = clock
        self.lock = threading.Lock()
        self._cond = threading.Condition(self.lock)
        self._buffer: Dict[int, Dict[str, np.ndarray]] = {}
        self._host_flat: Dict[str, np.ndarray] = {
            path_key(p): np.array(np.asarray(leaf))
            for p, leaf in iter_leaves(slice_params)
        }
        self._encoded: Optional[bytes] = None
        # version-delta pull state (delta_window=0 disables — the PR 14
        # full-pull wire). The owner maintains a DETERMINISTIC f32 "wire
        # chain": wire_v = wire_{v-1} + deq(Q(p_v - wire_{v-1})) — error
        # feedback on the chain itself, so |wire_v - p_v| stays bounded
        # by one quantization step and never accumulates. Each apply
        # stores that version's COMPRESSED piece; a pull from known=k
        # within the window ships the stacked pieces k+1..v, and every
        # delta-following puller lands exactly on wire_v regardless of
        # how many pulls it skipped. Window misses and budget evictions
        # fall back to a full pull — degrade, never stall.
        self.delta_window = max(0, int(delta_window))
        self.delta_codec = str(delta_codec)
        self.delta_budget_bytes = int(delta_budget_bytes)
        self._wire_flat: Optional[Dict[str, np.ndarray]] = (
            {
                k: np.asarray(v, dtype=np.float32).copy()
                for k, v in self._host_flat.items()
            }
            if self.delta_window > 0
            else None
        )
        # version -> (piece codec, compressed piece arrays, data bytes)
        self._delta_pieces: Dict[int, Tuple[str, Dict[str, np.ndarray], int]] = {}
        self._delta_bytes = 0
        self._delta_cache: Dict[int, bytes] = {}  # known -> assembled frame
        self.apply_seconds = 0.0
        # owner-side dynamics instrumentation (docs/OBSERVABILITY.md
        # "Training fleet"): the staleness of each ACCEPTED push, the
        # wall time a round spends waiting for quorum, and the apply
        # itself — shared bucket tables so cross-worker _bucket series
        # sum exactly. registry=None (telemetry off) constructs nothing.
        self.trace = trace
        self._staleness_hist = self._quorum_wait_hist = None
        self._apply_hist = None
        if registry is not None:
            from ..telemetry import FLEET_DYNAMICS_HISTOGRAMS

            self._staleness_hist = registry.histogram(
                "staleness",
                buckets=FLEET_DYNAMICS_HISTOGRAMS["staleness"],
            )
            self._quorum_wait_hist = registry.histogram(
                "quorum_wait_seconds",
                buckets=FLEET_DYNAMICS_HISTOGRAMS["quorum_wait_seconds"],
            )
            self._apply_hist = registry.histogram(
                "apply_seconds",
                buckets=FLEET_DYNAMICS_HISTOGRAMS["apply_seconds"],
            )
        self._round_start: Optional[float] = None
        if self.on_version is not None:
            self.on_version(self.version)

    # -- owner side ----------------------------------------------------
    def submit(
        self, worker: int, stamp: int, grads: Dict[str, np.ndarray]
    ) -> Tuple[bool, int]:
        """One gradient payload from ``worker`` stamped against shard
        version ``stamp``. Returns (accepted, current version).

        Structural validation happens HERE, before anything enters the
        quorum buffer: a wire-valid payload whose keys/shapes don't
        match the owned slices (a peer resolving a different config — a
        rejoining worker can get past the tolerant rejoin path without
        the healthz signature check) must be a counted discard, never a
        buffered entry that makes the NEXT apply raise mid-quorum and
        wedge the shard forever."""
        with self._cond:
            self.counters.inc("grad_received")
            if not (0 <= int(worker) < self.n_workers):
                # a bogus sender id must not count toward quorum
                self.counters.inc("grad_discarded")
                return False, self.version
            lag = self.version - int(stamp)
            if lag < 0 or lag > self.max_staleness:
                self.counters.inc("grad_discarded")
                return False, self.version
            if set(grads) != set(self._host_flat) or any(
                grads[k].shape != self._host_flat[k].shape for k in grads
            ):
                self.counters.inc("grad_discarded")
                logger.warning(
                    "fleet owner %d: structurally mismatched gradient "
                    "payload from worker %s discarded (peer running a "
                    "different parameter layout?)",
                    self.worker_id, worker,
                )
                return False, self.version
            if self._staleness_hist is not None:
                self._staleness_hist.observe(float(lag))
            if not self._buffer:
                # quorum-wait clock starts when a round OPENS (first
                # buffered contribution) and stops at the apply
                self._round_start = self.clock()
            self._buffer[int(worker)] = grads
            if len(self._buffer) >= self.quorum:
                try:
                    self._apply_locked()
                except Exception:
                    # belt over the validation above: an apply that still
                    # raises must not leave a poisoned buffer that
                    # re-raises at every future quorum — drop the round
                    # (counted) and keep the shard serving
                    # accounting caveat: the dropped round's pushes were
                    # already observed into the staleness histogram at
                    # their accept gate (observations can't be undone),
                    # so after this once-ever path the histogram's count
                    # exceeds applied+still-buffered by the dropped
                    # round's size — the loud exception below is the
                    # marker an operator reconciling the two would need
                    self.counters.inc(
                        "grad_discarded", len(self._buffer)
                    )
                    self._buffer.clear()
                    self._round_start = None
                    logger.exception(
                        "fleet owner %d: quorum apply failed; round "
                        "dropped", self.worker_id,
                    )
            return True, self.version

    def _apply_locked(self) -> None:
        t0 = self.clock()
        trace_t0 = self.trace.now() if self.trace is not None else None
        n = len(self._buffer)
        mean_flat: Dict[str, np.ndarray] = {}
        for flat in self._buffer.values():
            for key, arr in flat.items():
                acc = mean_flat.get(key)
                mean_flat[key] = arr.astype(np.float32) if acc is None else acc + arr
        for key in mean_flat:
            mean_flat[key] = mean_flat[key] / np.float32(n)
        grads_tree = tree_from_flat(mean_flat)
        self.params, self.opt_state = self.apply_fn(
            self.params, self.opt_state, grads_tree
        )
        self._host_flat = {
            path_key(p): np.array(np.asarray(leaf))
            for p, leaf in iter_leaves(self.params)
        }
        self._encoded = None
        self.version += 1
        if self._wire_flat is not None:
            self._record_delta_locked()
        self.counters.inc("grad_applied", n)
        self.counters.inc("applies")
        self._buffer.clear()
        dur = self.clock() - t0
        self.apply_seconds += dur
        if self._apply_hist is not None:
            self._apply_hist.observe(dur)
        if self._quorum_wait_hist is not None and self._round_start is not None:
            self._quorum_wait_hist.observe(t0 - self._round_start)
        self._round_start = None
        if self.trace is not None:
            # the owner-side half of the cross-worker hop the merged
            # fleet timeline shows: a grad_push span on the sender's
            # track, this grad_apply span on the owner's. Forced — an
            # apply is the async plane's heartbeat and must outlive the
            # per-step trace window.
            self.trace.add_span(
                "grad_apply",
                trace_t0,
                self.trace.now() - trace_t0,
                cat="fleet",
                force=True,
                args={"version": self.version, "contributors": n},
            )
        if self.on_version is not None:
            self.on_version(self.version)
        self._cond.notify_all()

    def _record_delta_locked(self) -> None:
        """Advance the wire chain past the apply that just bumped
        ``self.version`` and store that version's compressed piece
        (changed leaves only — a leaf the apply didn't move costs zero
        wire bytes; decode treats a missing key as a zero delta)."""
        assert self._wire_flat is not None
        piece: Dict[str, np.ndarray] = {}
        nbytes = 0
        for key, new in self._host_flat.items():
            delta = np.asarray(new, dtype=np.float32) - self._wire_flat[key]
            if not np.any(delta):
                continue
            entries, deq = _compress_leaf(self.delta_codec, key, delta)
            piece.update(entries)
            self._wire_flat[key] = self._wire_flat[key] + deq
            nbytes += sum(int(a.nbytes) for a in entries.values())
        self._delta_pieces[self.version] = (self.delta_codec, piece, nbytes)
        self._delta_bytes += nbytes
        self._delta_cache.clear()
        floor = self.version - self.delta_window
        for v in sorted(self._delta_pieces):
            over_budget = self._delta_bytes > self.delta_budget_bytes
            if v > floor and not (over_budget and v < self.version):
                break
            self._delta_bytes -= self._delta_pieces.pop(v)[2]

    # -- reader side ---------------------------------------------------
    def current_flat(self) -> Tuple[int, Dict[str, np.ndarray]]:
        """(version, owned slices) — the arrays are the post-apply host
        copies (replaced wholesale on each apply, never mutated), so the
        returned dict is safe to merge without holding the lock."""
        with self.lock:
            return self.version, dict(self._host_flat)

    def encoded(self, known: Optional[int]) -> Tuple[int, Optional[bytes]]:
        """Wire payload of the current slices, or ``(version, None)``
        when the caller's ``known`` version is already current. The
        encoding is cached per version (one encode, many pulls)."""
        version, body, _ = self.encoded_for(known, accept_delta=False)
        return version, body

    def _full_encoded_locked(self) -> bytes:
        if self._encoded is None:
            self._encoded = encode_arrays(
                {"version": self.version, "worker": self.worker_id},
                self._host_flat,
            )
        return self._encoded

    def encoded_for(
        self, known: Optional[int], accept_delta: bool = False
    ) -> Tuple[int, Optional[bytes], str]:
        """``(version, body, codec)`` for one pull. ``body is None`` =
        caller is current (204). A delta frame is served only when the
        caller asked for one (``X-SRT-Accept: delta``), every piece
        ``known+1..version`` is still retained (window + byte budget),
        AND the delta is actually smaller than the cached full frame —
        otherwise the full f32 frame, so a window miss degrades, never
        stalls. Assembled frames are cached per ``known`` (cleared on
        every apply; at most ``delta_window`` entries)."""
        with self.lock:
            if known is not None and int(known) == self.version:
                return self.version, None, "current"
            if (
                accept_delta
                and self._wire_flat is not None
                and known is not None
                and 0 <= self.version - int(known) <= self.delta_window
            ):
                k = int(known)
                needed = range(k + 1, self.version + 1)
                if all(v in self._delta_pieces for v in needed):
                    body = self._delta_cache.get(k)
                    if body is None:
                        body = encode_delta_frame(
                            {
                                "version": self.version,
                                "worker": self.worker_id,
                                "base": k,
                            },
                            [
                                (v,) + self._delta_pieces[v][:2]
                                for v in needed
                            ],
                        )
                        self._delta_cache[k] = body
                    full = self._full_encoded_locked()
                    if len(body) < len(full):
                        return self.version, body, "delta"
            return self.version, self._full_encoded_locked(), "f32"

    def checkpoint_parts(self, writer: Callable[[int, Any, Dict[str, np.ndarray]], Any]) -> Any:
        """Run ``writer(version, opt_state, host_flat)`` under the owner
        lock: no apply can bump the version — or DONATE the optimizer
        state's device buffers out from under the writer's device_get —
        while the part file is being written, so the part and the param
        slices it ships with are one consistent (version-stamped) cut."""
        with self.lock:
            return writer(self.version, self.opt_state, dict(self._host_flat))

    def wait_version_above(self, stamp: int, timeout: float) -> bool:
        """Block until the shard version exceeds ``stamp`` (my round was
        folded in, or a later one superseded it) — the worker loop's
        apply-wait phase. False on timeout."""
        deadline = self.clock() + float(timeout)
        with self._cond:
            while self.version <= int(stamp):
                remaining = deadline - self.clock()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)
            return True


class _PeerHTTPD(ThreadingHTTPServer):
    daemon_threads = True

    # ``server_close`` only closes the LISTENING socket; keep-alive
    # connections stay serviced by their daemon handler threads, so a
    # "stopped" server would keep answering /healthz probes over
    # established connections forever — a thread-fleet worker could
    # never be declared dead by the lease tracker. Track every accepted
    # connection so stop() can sever them the way a killed PROCESS
    # would.
    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__(*args, **kwargs)
        self._conns: set = set()
        self._conns_lock = threading.Lock()

    def process_request(self, request: Any, client_address: Any) -> None:
        with self._conns_lock:
            self._conns.add(request)
        super().process_request(request, client_address)

    def shutdown_request(self, request: Any) -> None:
        with self._conns_lock:
            self._conns.discard(request)
        super().shutdown_request(request)

    def close_all_connections(self) -> None:
        with self._conns_lock:
            conns = list(self._conns)
            self._conns.clear()
        for sock in conns:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass

    owner: OwnerState
    worker_id: int
    layout_signature: str
    tel: Any
    checkpoint_cb: Optional[Callable[[str, int], Dict[str, Any]]]
    finalize_event: threading.Event
    counters: FleetCounters
    # elastic membership (PR 17): the epoch every frame is fenced
    # against, the advertised membership, broadcast adoptions pending
    # the worker loop's next step boundary, and queued join requests
    # (drained by the acting lead's membership thread)
    epoch: int
    membership: Optional[Dict[str, Any]]
    membership_lock: threading.Lock
    pending_membership: Optional[Membership]
    join_requests: list
    max_body_bytes: int


class _PeerHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server: _PeerHTTPD

    def log_message(self, fmt: str, *args: Any) -> None:
        logger.debug("%s " + fmt, self.address_string(), *args)

    # -- reply helpers -------------------------------------------------
    def _reply_json(self, status: int, payload: Dict[str, Any]) -> None:
        from ..telemetry import sanitize_json

        body = json.dumps(sanitize_json(payload)).encode("utf8")
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _reply_bytes(self, status: int, body: bytes, content_type: str) -> None:
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _read_body(self) -> bytes:
        length = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(length) if length > 0 else b""

    # -- GET -----------------------------------------------------------
    def do_GET(self) -> None:  # noqa: N802 (stdlib handler naming)
        parsed = urlparse(self.path)
        srv = self.server
        if parsed.path == "/healthz":
            payload: Dict[str, Any] = {
                "status": "ok",
                "role": "fleet-worker",
                "worker": srv.worker_id,
                "version": srv.owner.version,
                "layout": srv.layout_signature,
                # wire codecs this build DECODES — pushers negotiate
                # against this (absent on old peers -> they get f32)
                "codecs": list(WIRE_CODECS),
                "delta_window": srv.owner.delta_window,
                "epoch": srv.epoch,
            }
            if srv.tel is not None:
                payload["anchor"] = srv.tel.trace.anchor()
            self._reply_json(200, payload)
        elif parsed.path == "/membership":
            with srv.membership_lock:
                payload = dict(srv.membership or {})
            payload.setdefault("epoch", srv.epoch)
            self._reply_json(200, payload)
        elif parsed.path == "/params":
            q = parse_qs(parsed.query)
            known_s = (q.get("known") or [None])[0]
            try:
                known = int(known_s) if known_s is not None else None
            except ValueError:
                # same discipline as every other input on this port:
                # malformed client bytes are a clean 400, never a
                # handler-thread traceback
                self._reply_json(
                    400, {"error": "bad_request",
                          "message": f"known={known_s!r} is not an int"}
                )
                return
            # epoch fence on the pull side: a zombie owner (or a peer
            # still on a pre-eviction membership) must not receive the
            # NEW layout's slices — its merge offsets would be wrong.
            # Absent header = epoch 0 (pre-elastic puller); garbage is a
            # 400 like every other malformed input on this port.
            epoch_s = self.headers.get("X-SRT-Epoch")
            if epoch_s is not None:
                try:
                    req_epoch = int(epoch_s)
                except ValueError:
                    self._reply_json(
                        400, {"error": "bad_request",
                              "message": f"X-SRT-Epoch {epoch_s!r} is not an int"}
                    )
                    return
            else:
                req_epoch = 0
            if req_epoch != srv.epoch:
                srv.counters.inc("epoch_fenced")
                self.send_response(409)
                self.send_header("X-SRT-Epoch", str(srv.epoch))
                body = json.dumps(
                    {"error": "epoch_fenced", "epoch": srv.epoch}
                ).encode("utf8")
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            # delta negotiation rides a REQUEST header (an old worker
            # sends no header and gets the PR 14 full frame); the reply
            # names what was actually served so the puller never has to
            # sniff the frame
            accept = str(self.headers.get("X-SRT-Accept") or "")
            version, body, codec = srv.owner.encoded_for(
                known, accept_delta="delta" in accept
            )
            if body is None:
                self.send_response(204)
                self.send_header("X-SRT-Version", str(version))
                self.send_header("Content-Length", "0")
                self.end_headers()
            else:
                self.send_response(200)
                self.send_header("X-SRT-Version", str(version))
                self.send_header("X-SRT-Codec", codec)
                self.send_header("Content-Type", "application/octet-stream")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
        elif parsed.path == "/metrics":
            self._metrics(parsed)
        elif parsed.path == "/admin/alerts":
            if srv.tel is None:
                self._reply_json(200, {"alerts": "disabled"})
            else:
                from ..telemetry_http import alerts_reply

                self._reply_json(200, alerts_reply(srv.tel))
        elif parsed.path == "/trace":
            if srv.tel is None:
                self._reply_json(404, {"error": "telemetry_disabled"})
            else:
                from ..telemetry_http import trace_reply

                self._reply_json(200, trace_reply(srv.tel, "fleet-worker"))
        else:
            self._reply_json(404, {"error": "not_found", "message": parsed.path})

    def _metrics(self, parsed: Any) -> None:
        srv = self.server
        fmt = (parse_qs(parsed.query).get("format") or [""])[0]
        if srv.tel is None:
            # telemetry off: the peer plane still serves its own ledger
            # (counters + version) so an operator can see the fleet move —
            # but constructs no registry/trace objects (the zero-calls
            # contract stays with the worker loop)
            snap = {
                "counters": srv.counters.snapshot(),
                "gauges": {
                    "fleet_worker": srv.worker_id,
                    "param_version": srv.owner.version,
                    "membership_epoch": srv.epoch,
                },
            }
            if fmt == "prometheus":
                from ..prometheus import EXPOSITION_CONTENT_TYPE, render_snapshot

                self._reply_bytes(
                    200,
                    render_snapshot(
                        snap,
                        prefix="srt_training",
                        labels={"worker": str(srv.worker_id)},
                    ).encode("utf8"),
                    EXPOSITION_CONTENT_TYPE,
                )
            else:
                self._reply_json(200, snap)
            return
        from ..telemetry_http import metrics_reply

        # one shared reply builder with the trainer listener (the worker
        # label on every family: one Prometheus server scraping N fleet
        # workers gets N distinct series instead of N colliding ones)
        body, content_type = metrics_reply(
            srv.tel,
            fmt,
            labels={"worker": str(srv.worker_id)},
            json_extra={"worker": srv.worker_id},
        )
        self._reply_bytes(200, body, content_type)

    def _body_or_413(self) -> Optional[bytes]:
        """Read the request body, or reply 413 + counted discard and
        return None when the declared length exceeds the cap — an
        oversized frame must cost a typed rejection, not a
        multi-gigabyte allocation inside a handler thread."""
        srv = self.server
        try:
            length = int(self.headers.get("Content-Length") or 0)
        except ValueError:
            self._reply_json(
                400, {"error": "bad_request",
                      "message": "Content-Length is not an int"}
            )
            return None
        if length > srv.max_body_bytes:
            srv.counters.inc("grad_discarded")
            self._reply_json(
                413, {"error": "body_too_large",
                      "message": f"{length} bytes exceeds the "
                      f"{srv.max_body_bytes}-byte frame cap"}
            )
            return None
        return self.rfile.read(length) if length > 0 else b""

    # -- POST ----------------------------------------------------------
    def do_POST(self) -> None:  # noqa: N802
        parsed = urlparse(self.path)
        srv = self.server
        if parsed.path == "/grad":
            body = self._body_or_413()
            if body is None:
                return
            try:
                # decode_grads dequantizes bf16/int8 frames to f32 and
                # passes unknown codecs through untouched — the
                # structural check in OwnerState.submit turns a genuine
                # mismatch into a counted discard, not a 400
                meta, arrays = decode_grads(body)
                epoch = frame_epoch(meta)
                worker = int(meta["worker"])
                stamp = int(meta["stamp"])
            except (WireError, KeyError, TypeError, ValueError) as e:
                self._reply_json(400, {"error": "bad_payload", "message": str(e)})
                return
            if epoch != srv.epoch:
                # the zombie fence: a push stamped with a dead
                # membership's epoch is counted-discarded BEFORE the
                # quorum buffer — its slice offsets describe a layout
                # that no longer exists, and applying them would corrupt
                # the re-sharded state silently
                srv.counters.inc("epoch_fenced")
                self._reply_json(
                    200,
                    {"accepted": False, "fenced": True, "epoch": srv.epoch},
                )
                return
            accepted, version = srv.owner.submit(worker, stamp, arrays)
            self._reply_json(
                200, {"accepted": accepted, "version": version}
            )
        elif parsed.path == "/checkpoint":
            if srv.checkpoint_cb is None:
                self._reply_json(503, {"error": "not_ready"})
                return
            body = self._body_or_413()
            if body is None:
                return
            try:
                req = json.loads(body.decode("utf8") or "{}")
                ckpt_dir = str(req["dir"])
                stamp = int(req["stamp"])
                epoch = frame_epoch(req if isinstance(req, dict) else {})
            except (WireError, ValueError, KeyError, UnicodeDecodeError) as e:
                self._reply_json(400, {"error": "bad_request", "message": str(e)})
                return
            if epoch != srv.epoch:
                # a checkpoint generation must be one membership's
                # consistent cut: parts written under different epochs
                # have different shard geometry and would assemble into
                # garbage — fence the request, keep the old generation
                srv.counters.inc("epoch_fenced")
                self._reply_json(
                    409, {"error": "epoch_fenced", "epoch": srv.epoch}
                )
                return
            try:
                result = srv.checkpoint_cb(ckpt_dir, stamp)
            except Exception as e:  # surfaced to the coordinator, not eaten
                logger.exception("fleet checkpoint part write failed")
                self._reply_json(
                    500, {"error": "checkpoint_failed", "message": str(e)}
                )
                return
            body = encode_arrays(result["meta"], result["params"])
            self._reply_bytes(200, body, "application/octet-stream")
        elif parsed.path == "/membership":
            # lead-broadcast adoption: a NEW membership (strictly higher
            # epoch) is queued for the worker loop's next step boundary
            # — the swap must happen between steps, not mid-push, and
            # not on a handler thread that races the trainer
            body = self._body_or_413()
            if body is None:
                return
            try:
                m = Membership.from_wire(
                    json.loads(body.decode("utf8") or "{}")
                )
            except (ValueError, UnicodeDecodeError) as e:
                self._reply_json(
                    400, {"error": "bad_request", "message": str(e)}
                )
                return
            with srv.membership_lock:
                if m.epoch <= srv.epoch and not (
                    srv.pending_membership is not None
                    and m.epoch > srv.pending_membership.epoch
                ):
                    # a zombie lead re-broadcasting its dead membership
                    # is fenced exactly like its pushes
                    srv.counters.inc("epoch_fenced")
                    self._reply_json(
                        409, {"error": "epoch_fenced", "epoch": srv.epoch}
                    )
                    return
                # racing broadcasts: the HIGHEST epoch wins the pending
                # slot (same rule as PeerServer.queue_membership) — an
                # older-but-unfenced frame must not regress it
                if (
                    srv.pending_membership is None
                    or m.epoch > srv.pending_membership.epoch
                ):
                    srv.pending_membership = m
            self._reply_json(200, {"adopted": True, "epoch": m.epoch})
        elif parsed.path == "/membership/join":
            body = self._body_or_413()
            if body is None:
                return
            try:
                req = json.loads(body.decode("utf8") or "{}")
                joiner = req["worker"]
                if (
                    isinstance(joiner, bool)
                    or not isinstance(joiner, int)
                    or joiner < 0
                ):
                    raise ValueError(f"worker {joiner!r} is not an id")
            except (ValueError, KeyError, UnicodeDecodeError) as e:
                self._reply_json(
                    400, {"error": "bad_request", "message": str(e)}
                )
                return
            with srv.membership_lock:
                if joiner not in srv.join_requests:
                    srv.join_requests.append(joiner)
            self._reply_json(200, {"queued": True, "epoch": srv.epoch})
        elif parsed.path == "/finalize":
            srv.finalize_event.set()
            self._reply_json(200, {"status": "finalizing"})
        else:
            self._reply_json(404, {"error": "not_found", "message": parsed.path})


class PeerServer:
    """Lifecycle wrapper for one worker's peer endpoint (daemon serve
    thread, like the trainer telemetry server)."""

    def __init__(
        self,
        owner: OwnerState,
        *,
        worker_id: int,
        layout_signature: str,
        counters: FleetCounters,
        tel: Any = None,
        host: str = "127.0.0.1",
        port: int = 0,
        checkpoint_cb: Optional[Callable[[str, int], Dict[str, Any]]] = None,
    ) -> None:
        self.httpd = _PeerHTTPD((host, int(port)), _PeerHandler)
        self.httpd.owner = owner
        self.httpd.worker_id = int(worker_id)
        self.httpd.layout_signature = layout_signature
        self.httpd.tel = tel
        self.httpd.counters = counters
        self.httpd.checkpoint_cb = checkpoint_cb
        self.httpd.finalize_event = threading.Event()
        self.httpd.epoch = 0
        self.httpd.membership = None
        self.httpd.membership_lock = threading.Lock()
        self.httpd.pending_membership = None
        self.httpd.join_requests = []
        self.httpd.max_body_bytes = int(MAX_BODY_BYTES)
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> Tuple[str, int]:
        host, port = self.httpd.server_address[:2]
        return str(host), int(port)

    @property
    def finalize_event(self) -> threading.Event:
        return self.httpd.finalize_event

    def set_checkpoint_cb(
        self, cb: Callable[[str, int], Dict[str, Any]]
    ) -> None:
        self.httpd.checkpoint_cb = cb

    # -- elastic membership (PR 17) ------------------------------------
    @property
    def epoch(self) -> int:
        return self.httpd.epoch

    def set_membership(
        self, membership: Membership, layout_signature: str
    ) -> None:
        """Adopt ``membership`` as this server's fencing truth — called
        by the worker loop at the step boundary where it applies the
        re-shard (never from a handler thread)."""
        with self.httpd.membership_lock:
            self.httpd.epoch = int(membership.epoch)
            self.httpd.membership = membership.to_wire()
            self.httpd.layout_signature = str(layout_signature)

    def set_owner(self, owner: OwnerState) -> None:
        """Swap in the re-sharded owner state (same step boundary as
        :meth:`set_membership`). Handler threads read ``srv.owner`` per
        request, so the swap is one attribute assignment."""
        self.httpd.owner = owner

    def queue_membership(self, membership: Membership) -> None:
        """Queue a membership the LOCAL worker decided on (the acting
        lead's own eviction verdict) for its next step boundary — the
        same pending slot a broadcast lands in."""
        with self.httpd.membership_lock:
            pending = self.httpd.pending_membership
            if pending is None or membership.epoch > pending.epoch:
                self.httpd.pending_membership = membership

    def take_pending_membership(self) -> Optional[Membership]:
        with self.httpd.membership_lock:
            m = self.httpd.pending_membership
            self.httpd.pending_membership = None
            return m

    def pending_membership_epoch(self) -> Optional[int]:
        """Non-consuming peek for the step loop's quorum wait: a queued
        epoch newer than the current one means survivors already stamp
        their frames with the NEW epoch, so the old epoch's quorum can
        never complete — the wait should yield to the apply instead of
        burning ``quorum_wait_s``."""
        with self.httpd.membership_lock:
            m = self.httpd.pending_membership
            return None if m is None else m.epoch

    def drain_join_requests(self) -> list:
        with self.httpd.membership_lock:
            reqs = list(self.httpd.join_requests)
            self.httpd.join_requests.clear()
            return reqs

    def start(self) -> Tuple[str, int]:
        self._thread = threading.Thread(
            target=self.httpd.serve_forever,
            kwargs={"poll_interval": 0.2},
            name=f"fleet-peer-{self.httpd.worker_id}",
            daemon=True,
        )
        self._thread.start()
        return self.address

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        # sever established keep-alive connections too — peers' lease
        # probes must see this worker DIE (connection dropped), exactly
        # as they would if the whole process were SIGKILLed
        self.httpd.close_all_connections()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
