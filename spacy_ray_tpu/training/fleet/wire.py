"""Pickle-free wire codec for the trainer fleet's array payloads.

Gradient pushes and parameter pulls move ``{leaf-path: ndarray}`` dicts
between processes. The serving subsystem's rule (PR 8) applies here too:
an open port must never ``pickle.load`` client-supplied bytes. The
format is a json header (lengths, dtypes, shapes — data, not code)
followed by the arrays' raw little-endian bytes:

    b"SRTF1" | u64 header length (big-endian) | header json | raw bytes

Arrays are decoded with ``np.frombuffer`` against the declared dtype —
nothing in the payload is executable. Decode errors raise
:class:`WireError` (one typed error for every malformed-payload shape).
"""

from __future__ import annotations

import json
from typing import Any, Dict, Tuple

import numpy as np

MAGIC = b"SRTF1"

__all__ = ["MAGIC", "WireError", "encode_arrays", "decode_arrays"]


class WireError(ValueError):
    """Malformed fleet wire payload (truncated, wrong magic, bad
    header, byte-count mismatch)."""


def encode_arrays(meta: Dict[str, Any], arrays: Dict[str, np.ndarray]) -> bytes:
    entries = []
    blobs = []
    for key in sorted(arrays):
        arr = np.ascontiguousarray(arrays[key])
        if arr.dtype.byteorder == ">":  # big-endian host array: normalize
            arr = arr.astype(arr.dtype.newbyteorder("<"))
        entries.append([key, arr.dtype.str, list(arr.shape)])
        blobs.append(arr.tobytes())
    header = json.dumps({"meta": meta, "arrays": entries}).encode("utf8")
    return (
        MAGIC
        + len(header).to_bytes(8, "big")
        + header
        + b"".join(blobs)
    )


def decode_arrays(body: bytes) -> Tuple[Dict[str, Any], Dict[str, np.ndarray]]:
    if len(body) < len(MAGIC) + 8 or body[: len(MAGIC)] != MAGIC:
        raise WireError("bad fleet payload: missing magic")
    hlen = int.from_bytes(body[len(MAGIC): len(MAGIC) + 8], "big")
    start = len(MAGIC) + 8
    if len(body) < start + hlen:
        raise WireError("bad fleet payload: truncated header")
    try:
        header = json.loads(body[start: start + hlen].decode("utf8"))
        entries = header["arrays"]
        meta = header.get("meta") or {}
    except (ValueError, KeyError, UnicodeDecodeError) as e:
        raise WireError(f"bad fleet payload header: {e}") from e
    arrays: Dict[str, np.ndarray] = {}
    offset = start + hlen
    for entry in entries:
        try:
            key, dtype_s, shape = entry
            dtype = np.dtype(str(dtype_s))
            shape = tuple(int(d) for d in shape)
        except (ValueError, TypeError) as e:
            raise WireError(f"bad fleet payload entry {entry!r}: {e}") from e
        count = int(np.prod(shape, dtype=np.int64))  # () -> 1, (0, d) -> 0
        nbytes = dtype.itemsize * count
        if len(body) < offset + nbytes:
            raise WireError(f"bad fleet payload: truncated data for {key!r}")
        arrays[str(key)] = np.frombuffer(
            body, dtype=dtype, count=count, offset=offset
        ).reshape(shape).copy()
        offset += nbytes
    if offset != len(body):
        raise WireError(
            f"bad fleet payload: {len(body) - offset} trailing bytes"
        )
    return meta, arrays
