"""Pickle-free wire codec for the trainer fleet's array payloads.

Gradient pushes and parameter pulls move ``{leaf-path: ndarray}`` dicts
between processes. The serving subsystem's rule (PR 8) applies here too:
an open port must never ``pickle.load`` client-supplied bytes. The
format is a json header (lengths, dtypes, shapes — data, not code)
followed by the arrays' raw little-endian bytes:

    b"SRTF1" | u64 header length (big-endian) | header json | raw bytes

Arrays are decoded with ``np.frombuffer`` against the declared dtype —
nothing in the payload is executable. Decode errors raise
:class:`WireError` (one typed error for every malformed-payload shape).

Wire compression (ROADMAP item 3, the PAPERS.md arXiv 2004.13336
communication-first framing) layers ON TOP of this frame without
changing it: a ``codec`` field in the json meta names how the arrays
were shrunk before encoding —

``f32``
    arrays as-is (the PR 14 wire, and the interop fallback).
``bf16``
    f32 leaves carried as their top 16 bits (round-to-nearest-even),
    2x smaller, ~3 decimal digits — the conservative tier.
``int8``
    per-output-channel symmetric int8 (the trusted ``ops/int8_matmul``
    recipe, 4x smaller): each leaf ``k`` becomes an int8 array plus an
    f32 ``k#scale`` companion. Tiny leaves (rank 0, or fewer than 8
    elements) ride through as f32 — the scale would outweigh the
    savings.
``delta``
    a parameter pull as stacked per-version COMPRESSED deltas
    (``v{n}/{key}`` keys) against the puller's known version; summing
    the dequantized pieces reproduces the owner's deterministic wire
    chain exactly (see ``peer.OwnerState``).

Negotiation is the sender's job: :func:`negotiate_push_codec` drops to
``f32`` unless the receiver advertised the codec on ``/healthz``, and a
receiver that sees an UNKNOWN codec passes the arrays through untouched
rather than erroring — a mixed fleet (old worker, new owner or vice
versa) degrades to the PR 14 wire, never to a crash.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterable, List, Optional, Tuple

import numpy as np

from ...ops.int8_matmul import dequantize_int8_np, quantize_int8_np

MAGIC = b"SRTF1"

#: codecs this build can DECODE — what /healthz advertises to pushers.
WIRE_CODECS = ("f32", "bf16", "int8", "delta")

#: companion-key suffix carrying a quantized leaf's per-channel scales.
SCALE_SUFFIX = "#scale"

#: int8 leaves below this many elements ship as f32 — the f32 scale
#: companion would cost more bytes than quantization saves.
INT8_MIN_LEAF = 8

__all__ = [
    "MAGIC",
    "WIRE_CODECS",
    "SCALE_SUFFIX",
    "WireError",
    "frame_epoch",
    "encode_arrays",
    "decode_arrays",
    "compress_arrays",
    "decompress_arrays",
    "encode_grads",
    "decode_grads",
    "encode_delta_frame",
    "decode_delta_frame",
    "GradCompressor",
    "resolve_grad_compression",
    "negotiate_push_codec",
]


class WireError(ValueError):
    """Malformed fleet wire payload (truncated, wrong magic, bad
    header, byte-count mismatch)."""


def frame_epoch(meta: Dict[str, Any]) -> int:
    """The membership epoch stamped on a frame's meta (PR 17 elastic
    membership: every push/pull/checkpoint frame carries the sender's
    epoch, and owners fence mismatches). A frame WITHOUT the field is a
    pre-elastic peer's — epoch 0 by definition, so an unchanged fleet
    interoperates. A garbage stamp raises :class:`WireError` (the
    malformed-payload family, not a handler traceback)."""
    e = meta.get("epoch", 0)
    if isinstance(e, bool) or not isinstance(e, int) or e < 0:
        raise WireError(
            f"bad fleet payload: epoch {e!r} is not an int >= 0"
        )
    return int(e)


def encode_arrays(meta: Dict[str, Any], arrays: Dict[str, np.ndarray]) -> bytes:
    entries = []
    blobs = []
    for key in sorted(arrays):
        arr = np.ascontiguousarray(arrays[key])
        if arr.dtype.byteorder == ">":  # big-endian host array: normalize
            arr = arr.astype(arr.dtype.newbyteorder("<"))
        entries.append([key, arr.dtype.str, list(arr.shape)])
        blobs.append(arr.tobytes())
    header = json.dumps({"meta": meta, "arrays": entries}).encode("utf8")
    return (
        MAGIC
        + len(header).to_bytes(8, "big")
        + header
        + b"".join(blobs)
    )


def decode_arrays(body: bytes) -> Tuple[Dict[str, Any], Dict[str, np.ndarray]]:
    if len(body) < len(MAGIC) + 8 or body[: len(MAGIC)] != MAGIC:
        raise WireError("bad fleet payload: missing magic")
    hlen = int.from_bytes(body[len(MAGIC): len(MAGIC) + 8], "big")
    start = len(MAGIC) + 8
    if len(body) < start + hlen:
        raise WireError("bad fleet payload: truncated header")
    try:
        header = json.loads(body[start: start + hlen].decode("utf8"))
        entries = header["arrays"]
        meta = header.get("meta") or {}
    except (ValueError, KeyError, UnicodeDecodeError) as e:
        raise WireError(f"bad fleet payload header: {e}") from e
    arrays: Dict[str, np.ndarray] = {}
    offset = start + hlen
    for entry in entries:
        try:
            key, dtype_s, shape = entry
            dtype = np.dtype(str(dtype_s))
            shape = tuple(int(d) for d in shape)
        except (ValueError, TypeError) as e:
            raise WireError(f"bad fleet payload entry {entry!r}: {e}") from e
        count = int(np.prod(shape, dtype=np.int64))  # () -> 1, (0, d) -> 0
        nbytes = dtype.itemsize * count
        if len(body) < offset + nbytes:
            raise WireError(f"bad fleet payload: truncated data for {key!r}")
        arrays[str(key)] = np.frombuffer(
            body, dtype=dtype, count=count, offset=offset
        ).reshape(shape).copy()
        offset += nbytes
    if offset != len(body):
        raise WireError(
            f"bad fleet payload: {len(body) - offset} trailing bytes"
        )
    return meta, arrays


# -- leaf codecs -------------------------------------------------------


def _to_bf16_bits(arr: np.ndarray) -> np.ndarray:
    """f32 -> bf16 carried as uint16: keep the top 16 bits with
    round-to-nearest-even (the widening-add trick, in uint64 so the
    carry can't wrap). No ml_dtypes dependency — the wire dtype is
    plain ``<u2`` and only THIS module gives the bits meaning."""
    a = np.ascontiguousarray(np.asarray(arr, dtype=np.float32))
    bits = a.view(np.uint32).astype(np.uint64)
    one = np.uint64(1)
    rounded = (bits + np.uint64(0x7FFF) + ((bits >> np.uint64(16)) & one))
    return (rounded >> np.uint64(16)).astype(np.uint16).reshape(a.shape)


def _from_bf16_bits(bits: np.ndarray) -> np.ndarray:
    b = np.ascontiguousarray(np.asarray(bits, dtype=np.uint16))
    return (b.astype(np.uint32) << np.uint32(16)).view(np.float32)


def _compress_leaf(
    codec: str, key: str, arr: np.ndarray
) -> Tuple[Dict[str, np.ndarray], np.ndarray]:
    """``(wire-entries, dequantized)`` for one leaf. The second return
    is what the RECEIVER will reconstruct — the error-feedback residual
    and the owner's deterministic wire chain are both defined by it."""
    a32 = np.ascontiguousarray(np.asarray(arr, dtype=np.float32))
    if codec == "bf16":
        bits = _to_bf16_bits(a32)
        return {key: bits}, _from_bf16_bits(bits)
    if codec == "int8":
        if a32.ndim == 0 or a32.size < INT8_MIN_LEAF or key.endswith(SCALE_SUFFIX):
            return {key: a32}, a32
        q, scale = quantize_int8_np(a32)
        return {key: q, key + SCALE_SUFFIX: scale}, dequantize_int8_np(q, scale)
    return {key: a32}, a32  # f32 (and the never-error fallback)


def compress_arrays(
    arrays: Dict[str, np.ndarray], codec: str
) -> Dict[str, np.ndarray]:
    """Stateless (no error feedback) compression of a whole dict —
    parameter pieces and plain grad frames. ``f32`` passes through."""
    if codec == "f32":
        return {k: np.ascontiguousarray(np.asarray(v)) for k, v in arrays.items()}
    out: Dict[str, np.ndarray] = {}
    for key in sorted(arrays):
        entries, _ = _compress_leaf(codec, key, arrays[key])
        out.update(entries)
    return out


def decompress_arrays(
    arrays: Dict[str, np.ndarray], codec: str
) -> Dict[str, np.ndarray]:
    """Invert :func:`compress_arrays`. int8 leaves missing their
    ``#scale`` companion raise :class:`WireError`; an UNKNOWN codec
    passes the arrays through as declared (the interop fallback — the
    receiver's structural checks turn a genuine mismatch into a counted
    discard, never a crash)."""
    if codec == "bf16":
        return {
            k: _from_bf16_bits(v) if v.dtype == np.uint16 else v
            for k, v in arrays.items()
        }
    if codec == "int8":
        out: Dict[str, np.ndarray] = {}
        for k, v in arrays.items():
            if k.endswith(SCALE_SUFFIX):
                continue
            sk = k + SCALE_SUFFIX
            if sk in arrays:
                out[k] = dequantize_int8_np(v, arrays[sk])
            elif v.dtype == np.int8:
                raise WireError(
                    f"bad fleet payload: int8 leaf {k!r} missing {sk!r}"
                )
            else:
                out[k] = v  # tiny-leaf f32 passthrough
        return out
    return dict(arrays)  # f32 and unknown codecs


# -- gradient frames ---------------------------------------------------


def encode_grads(
    meta: Dict[str, Any], grads: Dict[str, np.ndarray], codec: str = "f32"
) -> bytes:
    """A gradient push frame: ``meta["codec"]`` names the compression,
    arrays carry the compressed leaves. Stateless — the push path uses
    :class:`GradCompressor` so the quantization error feeds back."""
    m = dict(meta)
    m["codec"] = str(codec)
    return encode_arrays(m, compress_arrays(grads, str(codec)))


def decode_grads(body: bytes) -> Tuple[Dict[str, Any], Dict[str, np.ndarray]]:
    """Decode a gradient push frame to f32 leaves. A frame without a
    ``codec`` field is a PR 14 f32 frame; a frame with an unknown codec
    decodes to its arrays as declared (fallback, never an error)."""
    meta, arrays = decode_arrays(body)
    codec = str(meta.get("codec") or "f32")
    if codec in ("f32", "bf16", "int8"):
        return meta, decompress_arrays(arrays, codec)
    return meta, arrays


# -- delta frames (version-delta param pulls) --------------------------


def encode_delta_frame(
    meta: Dict[str, Any],
    pieces: Iterable[Tuple[int, str, Dict[str, np.ndarray]]],
) -> bytes:
    """A param pull as stacked per-version deltas. ``pieces`` is
    ``(version, piece_codec, compressed-arrays)`` oldest-first; each
    piece's arrays are ALREADY compressed (they're the owner's stored
    wire-chain pieces — re-encoding them would fork the chain). Keys go
    on the wire as ``v{version}/{key}`` and the piece table rides in
    ``meta["pieces"]``."""
    table: List[List[Any]] = []
    arrays: Dict[str, np.ndarray] = {}
    for version, piece_codec, piece in pieces:
        table.append([int(version), str(piece_codec)])
        for key, arr in piece.items():
            arrays[f"v{int(version)}/{key}"] = arr
    m = dict(meta)
    m["codec"] = "delta"
    m["pieces"] = table
    return encode_arrays(m, arrays)


def decode_delta_frame(
    meta: Dict[str, Any], arrays: Dict[str, np.ndarray]
) -> Dict[str, np.ndarray]:
    """Sum a delta frame's dequantized pieces: ``{key: f32 delta}`` to
    ADD onto the puller's known-version params. Malformed piece tables
    raise :class:`WireError` (truncated array data already raised in
    :func:`decode_arrays`)."""
    try:
        table = [(int(v), str(c)) for v, c in meta["pieces"]]
    except (KeyError, TypeError, ValueError) as e:
        raise WireError(f"bad delta frame piece table: {e}") from e
    total: Dict[str, np.ndarray] = {}
    for version, piece_codec in table:
        prefix = f"v{version}/"
        piece = {
            k[len(prefix):]: a for k, a in arrays.items()
            if k.startswith(prefix)
        }
        for key, delta in decompress_arrays(piece, piece_codec).items():
            d32 = np.asarray(delta, dtype=np.float32)
            total[key] = d32 if key not in total else total[key] + d32
    return total


# -- error-feedback push compression -----------------------------------


class GradCompressor:
    """Per-(peer, leaf) error-feedback quantization for gradient pushes.

    Quantization error is ADDED BACK into the next round's gradient for
    the same peer (``g_t' = g_t + r_{t-1}; r_t = g_t' - deq(Q(g_t'))``),
    so over T rounds the dequantized sum telescopes to the raw-grad sum
    minus one bounded final residual — the property that keeps the
    S∈{0,1,2} convergence envelope intact (tests pin it exactly).
    ``error_feedback=False`` is the ablation control: sub-step signal
    then quantizes to zero forever and never reaches the owner.

    Not thread-safe; the worker's round loop is single-threaded.
    """

    def __init__(self, codec: str, *, error_feedback: bool = True) -> None:
        self.codec = str(codec)
        self.error_feedback = bool(error_feedback)
        self._residual: Dict[Tuple[Any, str], np.ndarray] = {}

    def reset(self) -> None:
        """Drop all accumulated residuals. Required at an ownership
        re-shard: residuals are per-(peer, leaf-slice) and the slice
        geometry they telescope against no longer exists."""
        self._residual.clear()

    def compress(
        self,
        peer: Any,
        grads: Dict[str, np.ndarray],
        codec: Optional[str] = None,
    ) -> Tuple[Dict[str, np.ndarray], str]:
        """``(wire-arrays, codec-used)`` for one peer's push. ``codec``
        overrides the default (per-peer negotiation)."""
        c = str(codec) if codec is not None else self.codec
        out: Dict[str, np.ndarray] = {}
        for key in sorted(grads):
            g32 = np.asarray(grads[key], dtype=np.float32)
            rkey = (peer, key)
            if self.error_feedback and c != "f32":
                residual = self._residual.get(rkey)
                if residual is not None and residual.shape != g32.shape:
                    # slice geometry changed under us (ownership
                    # re-shard raced a push): the residual's region no
                    # longer exists, carrying it would corrupt
                    residual = None
                if residual is not None:
                    g32 = g32 + residual
            entries, deq = _compress_leaf(c, key, g32)
            out.update(entries)
            if self.error_feedback and c != "f32":
                self._residual[rkey] = (g32 - deq).astype(np.float32)
        return out, c

    def encode(
        self,
        peer: Any,
        meta: Dict[str, Any],
        grads: Dict[str, np.ndarray],
        codec: Optional[str] = None,
    ) -> bytes:
        """One call for the push path: compress (with error feedback)
        and frame."""
        arrays, used = self.compress(peer, grads, codec)
        m = dict(meta)
        m["codec"] = used
        return encode_arrays(m, arrays)


# -- negotiation -------------------------------------------------------


def resolve_grad_compression(requested: str, backend: str) -> Tuple[str, str]:
    """``(codec, reason)`` for ``--grad-compression``. ``auto`` resolves
    int8 only where the error-feedback convergence suite has run (the
    cpu fixture suite, tests/test_training_fleet.py); the conservative
    bf16 tier elsewhere — the serving overlay's honest-evidence rule."""
    req = str(requested or "auto").lower()
    if req in ("f32", "bf16", "int8"):
        return req, "explicit"
    if req != "auto":
        raise ValueError(
            f"unknown --grad-compression {requested!r} "
            "(choose auto|f32|bf16|int8)"
        )
    if str(backend).lower() == "cpu":
        return "int8", "error-feedback convergence suite committed on cpu"
    return (
        "bf16",
        f"no committed int8+error-feedback convergence record on "
        f"{backend} — conservative tier",
    )


def negotiate_push_codec(resolved: str, peer_codecs: Any) -> str:
    """The codec to PUSH with, given what the peer's ``/healthz``
    advertised. An old peer (no ``codecs`` field) or one that doesn't
    decode ``resolved`` gets plain f32 — degrade, never error."""
    if not peer_codecs:
        return "f32"
    try:
        advertised = {str(c) for c in peer_codecs}
    except TypeError:
        return "f32"
    return str(resolved) if str(resolved) in advertised else "f32"
