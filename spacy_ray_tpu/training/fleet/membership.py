"""Elastic fleet membership: leases, epochs, survivor re-sharding.

PR 14's fleet assumed the worker set was immutable: a worker that died
PAST its restart cap froze its owned shards forever while peers burned
``pull_wait_timeouts`` every step. This module makes membership a
first-class, *fenced* quantity:

* :class:`LeaseTracker` — lease-based liveness with consecutive-miss
  hysteresis. A peer is declared dead only when BOTH its lease expired
  (no successful ``/healthz`` for ``lease_s`` seconds) AND it missed
  ``miss_threshold`` consecutive probes. ``/healthz`` is served by each
  worker's daemon HTTP thread, so a merely-SLOW worker (long step, long
  eval) keeps answering and provably never gets evicted — the same
  fake-clock-tested discipline as the autoscaler/canary guards.

* :class:`Membership` — the fleet-wide truth: a monotonically increasing
  **epoch** plus the sorted tuple of active worker ids. Every eviction
  or join bumps the epoch; every push/pull/checkpoint frame is stamped
  with it, and owners discard (counted, ``epoch_fenced``) any frame
  carrying a different epoch — a zombie owner resurfacing after its
  eviction cannot corrupt the new layout (RESILIENCE.md "Ownership
  failover").

* :class:`RankedLayout` — the re-shard: the SAME first-divisible-axis
  rule as :class:`~.ownership.OwnershipLayout`, computed over the
  **survivor count** and addressed by original worker id (ids are
  mapped to dense survivor ranks internally). Checkpoint part files are
  written per RANK, so a post-failover generation is a normal
  ``len(active)``-shard v2 generation that a synchronous run — or a
  fresh fleet of any size — resumes exactly.

* :class:`PeerBackoff` — the dead-owner pull-spin fix: a pull target
  that keeps missing its deadline costs ONE structured
  ``fleet-peer-unreachable`` event and a capped exponential backoff,
  not a full ``quorum_wait_s`` burn plus a counter tick every step.

* :class:`MembershipLedger` — append-only ``fleet-membership.jsonl``
  event log (evictions, joins, adoptions) in the run directory; the
  run report's membership timeline and CI's failure artifacts read it.
"""

from __future__ import annotations

import json
import threading
import time
from pathlib import Path
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from .ownership import IndexT, OwnershipLayout

__all__ = [
    "LeaseTracker",
    "Membership",
    "MembershipLedger",
    "PeerBackoff",
    "RankedLayout",
]


class LeaseTracker:
    """Lease + consecutive-miss hysteresis per peer.

    The verdict is two-factor by design: ``lease_s`` bounds how long a
    peer may go unheard (wall clock), ``miss_threshold`` demands the
    silence be corroborated by that many consecutive failed probes.
    Either alone is evictable-by-accident — a long GC pause plus one
    unlucky probe, or a fast probe loop burning through misses inside a
    second — together they are not. Thread-safe; ``clock`` is injectable
    for fake-clock tests.
    """

    def __init__(
        self,
        peers: Iterable[int],
        *,
        lease_s: float,
        miss_threshold: int = 3,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if float(lease_s) <= 0:
            raise ValueError(f"lease_s must be > 0, got {lease_s}")
        if int(miss_threshold) < 1:
            raise ValueError(
                f"miss_threshold must be >= 1, got {miss_threshold}"
            )
        self.lease_s = float(lease_s)
        self.miss_threshold = int(miss_threshold)
        self.clock = clock
        self._lock = threading.Lock()
        now = self.clock()
        # a fresh peer starts with a full lease (grace for startup)
        self._last_ok: Dict[int, float] = {int(p): now for p in peers}
        self._misses: Dict[int, int] = {int(p): 0 for p in self._last_ok}

    def peers(self) -> List[int]:
        with self._lock:
            return sorted(self._last_ok)

    def add(self, peer: int) -> None:
        with self._lock:
            if int(peer) not in self._last_ok:
                self._last_ok[int(peer)] = self.clock()
                self._misses[int(peer)] = 0

    def remove(self, peer: int) -> None:
        with self._lock:
            self._last_ok.pop(int(peer), None)
            self._misses.pop(int(peer), None)

    def observe(self, peer: int, ok: bool) -> None:
        """Record one probe result for ``peer``."""
        p = int(peer)
        with self._lock:
            if p not in self._last_ok:
                return
            if ok:
                self._last_ok[p] = self.clock()
                self._misses[p] = 0
            else:
                self._misses[p] += 1

    def dead(self, peer: int) -> bool:
        p = int(peer)
        with self._lock:
            last = self._last_ok.get(p)
            if last is None:
                return False
            return (
                self.clock() - last > self.lease_s
                and self._misses[p] >= self.miss_threshold
            )

    def expired(self) -> List[int]:
        """Every tracked peer currently past BOTH gates."""
        with self._lock:
            now = self.clock()
            return sorted(
                p
                for p, last in self._last_ok.items()
                if now - last > self.lease_s
                and self._misses[p] >= self.miss_threshold
            )


class RankedLayout:
    """An :class:`~.ownership.OwnershipLayout` over the ACTIVE worker
    set, addressed by original worker id.

    The base layout is computed for ``len(active)`` workers (the same
    first-divisible-axis rule, so part files remain v2-canonical); ids
    are translated to dense survivor ranks at every call. An id outside
    the active set owns nothing — its slices were re-owned at the epoch
    bump, which is exactly what the epoch fence enforces on the wire.
    """

    def __init__(self, template: Any, active: Sequence[int]) -> None:
        self.active = tuple(sorted(int(w) for w in set(active)))
        if not self.active:
            raise ValueError("RankedLayout needs at least one active worker")
        self._rank: Dict[int, int] = {
            w: r for r, w in enumerate(self.active)
        }
        self.base = OwnershipLayout(template, len(self.active))
        self.n_workers = self.base.n_workers
        self.paths = self.base.paths
        self.shapes = self.base.shapes
        self.axes = self.base.axes

    def rank_of(self, worker: int) -> Optional[int]:
        return self._rank.get(int(worker))

    # -- id-addressed delegation --------------------------------------
    def owns(self, ordinal: int, worker: int) -> bool:
        r = self.rank_of(worker)
        return False if r is None else self.base.owns(ordinal, r)

    def index(self, ordinal: int, worker: int) -> Optional[IndexT]:
        r = self.rank_of(worker)
        if r is None:
            raise ValueError(f"worker {worker} is not in the active set")
        return self.base.index(ordinal, r)

    def key_index(self, key: str, worker: int) -> Optional[IndexT]:
        r = self.rank_of(worker)
        if r is None:
            raise ValueError(f"worker {worker} is not in the active set")
        return self.base.key_index(key, r)

    def index_for_shape(
        self, shape: Sequence[int], worker: int
    ) -> Optional[IndexT]:
        r = self.rank_of(worker)
        if r is None:
            raise ValueError(f"worker {worker} is not in the active set")
        return self.base.index_for_shape(shape, r)

    slice_with = staticmethod(OwnershipLayout.slice_with)

    def owned_keys(self, worker: int) -> List[str]:
        r = self.rank_of(worker)
        return [] if r is None else self.base.owned_keys(r)

    def flat_slices(self, tree: Any, worker: int) -> Dict[str, np.ndarray]:
        r = self.rank_of(worker)
        return {} if r is None else self.base.flat_slices(tree, r)

    def slice_tree(self, tree: Any, worker: int) -> Dict[str, Any]:
        r = self.rank_of(worker)
        if r is None:
            return {}
        return self.base.slice_tree(tree, r)

    def merge_flat(
        self,
        full: Any,
        worker: int,
        flat: Dict[str, np.ndarray],
        *,
        add: bool = False,
    ) -> None:
        r = self.rank_of(worker)
        if r is None:
            raise ValueError(f"worker {worker} is not in the active set")
        self.base.merge_flat(full, r, flat, add=add)

    def signature(self) -> str:
        """Structural digest peers must agree on. Includes the ACTIVE
        id set: two fleets at different memberships slice differently,
        so their frames must not interoperate silently."""
        import hashlib

        text = (
            "active=" + ",".join(map(str, self.active)) + "|"
            + self.base.signature()
        )
        return hashlib.sha256(text.encode("utf8")).hexdigest()[:16]


class Membership:
    """The fleet-wide membership truth: ``(epoch, active ids)``.

    Immutable; :meth:`evict` / :meth:`admit` return the NEXT membership
    at ``epoch + 1``. The lead is the lowest active id — a deterministic
    survivor-rank fallback, so when the lead itself dies the next-lowest
    survivor's lease thread takes over the verdict with no election.
    """

    def __init__(self, active: Sequence[int], epoch: int = 0) -> None:
        self.active: Tuple[int, ...] = tuple(
            sorted(int(w) for w in set(active))
        )
        if not self.active:
            raise ValueError("membership needs at least one active worker")
        self.epoch = int(epoch)
        if self.epoch < 0:
            raise ValueError(f"membership epoch must be >= 0, got {self.epoch}")

    @property
    def lead(self) -> int:
        return self.active[0]

    def __contains__(self, worker: int) -> bool:
        return int(worker) in self.active

    def __eq__(self, other: Any) -> bool:
        return (
            isinstance(other, Membership)
            and self.epoch == other.epoch
            and self.active == other.active
        )

    def __repr__(self) -> str:
        return f"Membership(epoch={self.epoch}, active={list(self.active)})"

    def evict(self, worker: int) -> "Membership":
        if int(worker) not in self.active:
            raise ValueError(f"worker {worker} is not active")
        survivors = tuple(w for w in self.active if w != int(worker))
        if not survivors:
            raise ValueError("cannot evict the last active worker")
        return Membership(survivors, self.epoch + 1)

    def admit(self, worker: int) -> "Membership":
        if int(worker) in self.active:
            raise ValueError(f"worker {worker} is already active")
        return Membership(self.active + (int(worker),), self.epoch + 1)

    def layout(self, template: Any) -> RankedLayout:
        return RankedLayout(template, self.active)

    # -- wire form ----------------------------------------------------
    def to_wire(self) -> Dict[str, Any]:
        return {
            "epoch": self.epoch,
            "active": list(self.active),
            "lead": self.lead,
        }

    @classmethod
    def from_wire(cls, payload: Any) -> "Membership":
        """Validated parse of a ``/membership`` body — malformed input
        raises ValueError (the server turns it into a 400, never a
        handler traceback)."""
        if not isinstance(payload, dict):
            raise ValueError("membership payload must be a JSON object")
        epoch = payload.get("epoch")
        active = payload.get("active")
        if not isinstance(epoch, int) or isinstance(epoch, bool) or epoch < 0:
            raise ValueError(f"membership epoch must be an int >= 0, got {epoch!r}")
        if (
            not isinstance(active, (list, tuple))
            or not active
            or not all(
                isinstance(w, int) and not isinstance(w, bool) and w >= 0
                for w in active
            )
        ):
            raise ValueError(
                f"membership active set must be a non-empty list of "
                f"worker ids, got {active!r}"
            )
        return cls(active, epoch)


class PeerBackoff:
    """Capped exponential backoff per unreachable peer (the dead-owner
    pull-spin fix). ``record_failure`` returns True exactly once per
    outage — the caller's cue to emit the single structured
    ``fleet-peer-unreachable`` event; while a peer is backing off,
    ``skip`` is True and the pull loop spends ZERO wait time on it."""

    def __init__(
        self,
        *,
        base_s: float = 1.0,
        cap_s: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.base_s = float(base_s)
        self.cap_s = float(cap_s)
        self.clock = clock
        self._delay: Dict[int, float] = {}
        self._until: Dict[int, float] = {}

    def record_failure(self, peer: int) -> bool:
        p = int(peer)
        first = p not in self._delay
        delay = self.base_s if first else min(
            self._delay[p] * 2.0, self.cap_s
        )
        self._delay[p] = delay
        self._until[p] = self.clock() + delay
        return first

    def record_success(self, peer: int) -> bool:
        """Clear ``peer``'s outage; True when one was in progress (the
        caller's cue to log the recovery)."""
        p = int(peer)
        was_down = p in self._delay
        self._delay.pop(p, None)
        self._until.pop(p, None)
        return was_down

    def skip(self, peer: int) -> bool:
        until = self._until.get(int(peer))
        return until is not None and self.clock() < until

    def current_delay(self, peer: int) -> float:
        return self._delay.get(int(peer), 0.0)


class MembershipLedger:
    """Append-only jsonl event log for membership transitions
    (``fleet-membership.jsonl`` in the run dir). Written by whichever
    worker is the ACTING lead at the time — one writer per event, append
    mode, one line per event, so a lead failover keeps extending the
    same file."""

    def __init__(self, path: Optional[Path]) -> None:
        self.path = Path(path) if path is not None else None
        self._lock = threading.Lock()

    def append(self, event: str, **fields: Any) -> None:
        if self.path is None:
            return
        row = {"ts": time.time(), "event": str(event), **fields}
        line = json.dumps(row, sort_keys=True) + "\n"
        try:
            with self._lock:
                self.path.parent.mkdir(parents=True, exist_ok=True)
                with open(self.path, "a", encoding="utf8") as f:
                    f.write(line)
        except OSError:
            pass  # the ledger is evidence, never a crash source


def read_membership_ledger(path: Path) -> List[Dict[str, Any]]:
    """All well-formed rows of a ``fleet-membership.jsonl`` (bad lines
    skipped — the file may be mid-append when read)."""
    out: List[Dict[str, Any]] = []
    try:
        text = Path(path).read_text(encoding="utf8")
    except OSError:
        return out
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            row = json.loads(line)
        except ValueError:
            continue
        if isinstance(row, dict):
            out.append(row)
    return out
