"""Corpus readers: registered ``@readers`` factories resolving to callables
that yield :class:`Example` streams.

Capability parity with the reference's corpus plumbing: dot-name-resolved
train/dev corpora (reference worker.py:94-95 ``resolve_dot_names``), the
``spacy convert``-produced binary corpus (reference bin/get-data.sh:8-12),
and jsonl sources. Formats:

* ``.jsonl``: one doc per line: {"tokens": [...], "tags": [...], "heads":
  [...], "deps": [...], "ents": [[start, end, label], ...], "spans":
  {"group": [[s, e, label], ...]}, "cats": {...}, "text": ...}
* ``.conllu``: Universal Dependencies format (UPOS/XPOS/head/deprel)
* ``.msgdoc``: this framework's binary DocBin equivalent (msgpack-free:
  JSON-lines inside gzip — portable, no native dep)

Rank-sharding lives in the batcher/loop, not here, so every process can
construct the same reader from the same config (per-host sharding fixes the
reference's duplicated-data gotcha, SURVEY.md §2.4 "No data sharding").
"""

from __future__ import annotations

import gzip
import json
import random
from contextlib import contextmanager
from pathlib import Path
from typing import Callable, Iterable, Iterator, List, Optional, Union

from ..registry import registry
from ..pipeline.doc import Doc, Example, Span
from .resilience import maybe_fail, retry_io

CorpusReader = Callable[[], Iterator[Example]]


def _open_corpus_file(opener: Callable, path, *args, **kwargs):
    """Open a corpus/DocBin file through the resilience layer: the
    ``corpus-read`` fault-injection site plus transient-I/O retry with
    backoff + jitter (fleet filesystems flake on open far more often than
    mid-read; a failed open is also the only retry that is trivially
    idempotent for a streaming reader)."""

    def attempt():
        maybe_fail("corpus-read")
        return opener(path, *args, **kwargs)

    return retry_io("corpus-read", attempt)


_raw_text_tokenizer = None


@contextmanager
def use_raw_text_tokenizer(tokenizer) -> Iterator[None]:
    """Enable raw-text ({"text": ...}) corpus lines, tokenized with the
    PIPELINE's tokenizer so pretraining sees the same token stream the
    pipeline produces at train/inference time (spaCy's JsonlCorpus
    tokenizes with nlp.make_doc for the same reason). Scoped: outside this
    context a raw-text line in a supervised corpus stays a LOUD error —
    silently tokenizing annotation-free docs would train on all-masked
    targets. ``pretrain`` wraps its whole run in this."""
    global _raw_text_tokenizer
    prev = _raw_text_tokenizer
    _raw_text_tokenizer = tokenizer
    try:
        yield
    finally:
        _raw_text_tokenizer = prev


def _doc_from_json(obj: dict) -> Doc:
    words = obj.get("tokens") or obj.get("words")
    if words is None:
        text = obj.get("text")
        if text is not None and _raw_text_tokenizer is not None:
            return _raw_text_tokenizer(text)
        if text is not None:
            raise ValueError(
                "Corpus line has raw 'text' but no 'tokens': raw-text lines "
                "are only readable under a pretraining run (use the "
                "`pretrain` command); supervised corpora need tokenized, "
                "annotated lines"
            )
        raise ValueError(f"Corpus line missing 'tokens': keys={list(obj)}")
    doc = Doc(
        words=list(words),
        spaces=obj.get("spaces"),
        tags=obj.get("tags"),
        pos=obj.get("pos"),
        heads=obj.get("heads"),
        deps=obj.get("deps"),
        lemmas=obj.get("lemmas"),
        morphs=obj.get("morphs"),
        sent_starts=obj.get("sent_starts"),
        cats=dict(obj.get("cats") or {}),
    )
    for ent in obj.get("ents") or []:
        s, e, label = ent[0], ent[1], ent[2]
        kb_id = str(ent[3]) if len(ent) > 3 else ""  # optional KB link
        doc.ents.append(Span(int(s), int(e), str(label), kb_id=kb_id))
    for group, spans in (obj.get("spans") or {}).items():
        doc.spans[group] = [Span(int(s), int(e), str(label)) for s, e, label in spans]
    return doc


def _doc_to_json(doc: Doc) -> dict:
    out: dict = {"tokens": doc.words}
    if doc.spaces is not None:
        out["spaces"] = doc.spaces
    for attr in ("tags", "pos", "heads", "deps", "lemmas", "morphs", "sent_starts"):
        val = getattr(doc, attr)
        if val is not None:
            out[attr] = val
    if doc.ents:
        out["ents"] = [
            [s.start, s.end, s.label] + ([s.kb_id] if s.kb_id else [])
            for s in doc.ents
        ]
    if doc.spans:
        out["spans"] = {
            g: [[s.start, s.end, s.label] for s in spans] for g, spans in doc.spans.items()
        }
    if doc.cats:
        out["cats"] = doc.cats
    return out


def read_jsonl_docs(path: Union[str, Path]) -> Iterator[Doc]:
    with _open_corpus_file(open, path, "r", encoding="utf8") as f:
        for line in f:
            line = line.strip()
            if line:
                yield _doc_from_json(json.loads(line))


def read_conllu_docs(path: Union[str, Path]) -> Iterator[Doc]:
    words: List[str] = []
    tags: List[str] = []
    pos: List[str] = []
    heads: List[int] = []
    deps: List[str] = []
    morphs: List[str] = []

    def flush() -> Optional[Doc]:
        nonlocal words, tags, pos, heads, deps, morphs
        if not words:
            return None
        doc = Doc(words=words, tags=tags, pos=pos, heads=heads, deps=deps, morphs=morphs)
        words, tags, pos, heads, deps, morphs = [], [], [], [], [], []
        return doc

    with _open_corpus_file(open, path, "r", encoding="utf8") as f:
        for line in f:
            line = line.rstrip("\n")
            if not line:
                doc = flush()
                if doc:
                    yield doc
                continue
            if line.startswith("#"):
                continue
            cols = line.split("\t")
            if "-" in cols[0] or "." in cols[0]:
                continue  # skip MWT / empty nodes
            idx = int(cols[0]) - 1
            words.append(cols[1])
            pos.append(cols[3])
            tags.append(cols[4] if cols[4] != "_" else cols[3])
            morphs.append(cols[5] if cols[5] != "_" else "")
            head = int(cols[6]) if cols[6] != "_" else 0
            heads.append(head - 1 if head > 0 else idx)  # root points to itself
            deps.append(cols[7] if cols[7] != "_" else "dep")
    doc = flush()
    if doc:
        yield doc


class DocBin:
    """Serializable collection of docs (the .spacy-DocBin equivalent)."""

    def __init__(self, docs: Optional[Iterable[Doc]] = None):
        self.docs: List[Doc] = list(docs) if docs else []

    def add(self, doc: Doc) -> None:
        self.docs.append(doc)

    def to_disk(self, path: Union[str, Path]) -> None:
        with gzip.open(path, "wt", encoding="utf8") as f:
            for doc in self.docs:
                f.write(json.dumps(_doc_to_json(doc)) + "\n")

    @classmethod
    def from_disk(cls, path: Union[str, Path]) -> "DocBin":
        docs = []
        with _open_corpus_file(gzip.open, path, "rt", encoding="utf8") as f:
            for line in f:
                line = line.strip()
                if line:
                    docs.append(_doc_from_json(json.loads(line)))
        return cls(docs)


def _iter_path(path: Path) -> Iterator[Doc]:
    if path.is_dir():
        for sub in sorted(path.iterdir()):
            if sub.suffix in (".jsonl", ".conllu", ".msgdoc", ".spacy"):
                yield from _iter_path(sub)
        return
    suffix = path.suffix
    if suffix == ".jsonl":
        yield from read_jsonl_docs(path)
    elif suffix == ".conllu":
        yield from read_conllu_docs(path)
    elif suffix == ".msgdoc":
        yield from DocBin.from_disk(path).docs
    elif suffix == ".spacy":
        # real spaCy DocBin (zlib-wrapped msgpack); legacy files from this
        # repo's earlier .spacy spelling were gzip text — sniff the magic
        with _open_corpus_file(open, path, "rb") as f:
            magic = f.read(2)
        if magic == b"\x1f\x8b":
            yield from DocBin.from_disk(path).docs
        else:
            from .spacy_docbin import read_docbin

            yield from read_docbin(path)
    else:
        raise ValueError(f"Unsupported corpus format: {path}")


class Corpus:
    """Config-constructed corpus: callable yielding Example iterators.

    max_length splits long docs on sentence boundaries (or hard-truncates)
    — the mechanism by which the reference ecosystem bounds sequence length
    (SURVEY.md §5.7: document segmentation, not attention sharding).

    ``cache`` (DEFAULT TRUE) materializes the whole corpus in host RAM on
    first use and reuses the same Example objects every epoch — this powers
    the parser's per-Example oracle memo and skips re-parsing files each
    epoch. For larger-than-RAM corpora set ``cache = false`` in the reader
    block to stream from disk per epoch.
    """

    def __init__(
        self,
        path: Union[str, Path],
        *,
        max_length: int = 0,
        limit: int = 0,
        shuffle: bool = False,
        seed: int = 0,
        cache: bool = True,
        augmenter: Optional[Callable] = None,
    ):
        self.path = Path(path)
        self.max_length = max_length
        self.limit = limit
        self.shuffle = shuffle
        self.seed = seed
        self.cache = cache  # materialize once; reuse Example objects across
        self.augmenter = augmenter  # Example -> Iterator[Example], per epoch
        self._examples: Optional[List[Example]] = None  # epochs (enables the
        self._epoch = 0  # parser's per-Example oracle memo); cache=false
        # streams from disk every epoch for larger-than-RAM corpora

    @property
    def augmented(self) -> bool:
        """True when epochs yield FRESH Example copies (an augmenter is
        active). The loop's collation cache keys on Example identity, so
        augmented streams can never hit it — the cache auto-bypasses on
        this flag (training/collate_pool.py)."""
        return self.augmenter is not None

    @property
    def stable_identity(self) -> bool:
        """True when steady-state epochs re-yield the SAME Example
        objects in the SAME batches (materialized cache, no augmenter, no
        shuffle — shuffling reshapes batch membership every epoch) — the
        precondition for the identity-keyed collation cache to ever hit.
        The loop disables the cache when this is False."""
        return self.cache and self.augmenter is None and not self.shuffle

    def _split(self, doc: Doc) -> Iterator[Doc]:
        if self.max_length <= 0 or len(doc) <= self.max_length:
            yield doc
            return
        # split on sentence starts when available, else hard chunks
        bounds: List[int] = [0]
        if doc.sent_starts:
            for i, s in enumerate(doc.sent_starts):
                if s == 1 and i > 0:
                    bounds.append(i)
        else:
            bounds.extend(range(self.max_length, len(doc), self.max_length))
        bounds.append(len(doc))
        for a, b in zip(bounds, bounds[1:]):
            if b <= a:
                continue
            # slice every token-aligned list attribute (heads re-based)
            piece = Doc(
                words=doc.words[a:b],
                spaces=doc.spaces[a:b] if doc.spaces else None,
                tags=doc.tags[a:b] if doc.tags else None,
                pos=doc.pos[a:b] if doc.pos else None,
                # a head outside the slice becomes a root (head == self) —
                # clamping it to the slice edge would fabricate an arc to an
                # unrelated token and corrupt the gold tree
                heads=[
                    h - a if a <= h < b else i
                    for i, h in enumerate(doc.heads[a:b])
                ]
                if doc.heads
                else None,
                deps=doc.deps[a:b] if doc.deps else None,
                lemmas=doc.lemmas[a:b] if doc.lemmas else None,
                morphs=doc.morphs[a:b] if doc.morphs else None,
                sent_starts=doc.sent_starts[a:b] if doc.sent_starts else None,
                cats=dict(doc.cats),
            )
            for span in doc.ents:
                if span.start >= a and span.end <= b:
                    piece.ents.append(Span(span.start - a, span.end - a, span.label))
            for g, spans in doc.spans.items():
                kept = [
                    Span(s.start - a, s.end - a, s.label)
                    for s in spans
                    if s.start >= a and s.end <= b
                ]
                if kept:
                    piece.spans[g] = kept
            yield piece

    def _read_examples(self) -> Iterator[Example]:
        for doc in _iter_path(self.path):
            for piece in self._split(doc):
                if len(piece) == 0:
                    continue
                yield Example.from_gold(piece)

    def __call__(self) -> Iterator[Example]:
        # limit applies AFTER shuffling: with shuffle=True each epoch yields
        # a fresh random subset, not a fixed file-order prefix
        if not self.cache and not self.shuffle:
            # pure streaming path (larger-than-RAM corpora)
            n = 0
            for eg in self._read_examples():
                yield from self._augment(eg)
                n += 1
                if self.limit and n >= self.limit:
                    return
            return
        if self.cache:
            if self._examples is None:
                self._examples = list(self._read_examples())
            examples: List[Example] = self._examples
        else:  # shuffle without cache: must materialize this epoch anyway
            examples = list(self._read_examples())
        if self.shuffle:
            order = list(range(len(examples)))
            random.Random(self.seed + self._epoch).shuffle(order)
            self._epoch += 1
            examples = [examples[i] for i in order]
        if self.limit:
            examples = examples[: self.limit]
        for eg in examples:
            yield from self._augment(eg)

    def _augment(self, eg: Example) -> Iterator[Example]:
        # applied per epoch, AFTER caching: augmented copies are fresh
        # Example objects, the cached originals stay pristine (the parser's
        # oracle memo keys on gold content, so no staleness either way)
        if self.augmenter is None:
            yield eg
        else:
            yield from self.augmenter(eg)


@registry.readers("spacy.Corpus.v1")
def create_corpus(
    path: Optional[str] = None,
    max_length: int = 0,
    gold_preproc: bool = False,
    limit: int = 0,
    augmenter: Optional[Callable] = None,
    shuffle: bool = False,
    seed: int = 0,
    cache: bool = True,
) -> Corpus:
    if path is None:
        raise ValueError("Corpus path is required (set [paths.train]/[paths.dev])")
    return Corpus(
        path, max_length=max_length, limit=limit, shuffle=shuffle, seed=seed,
        cache=cache, augmenter=augmenter,
    )


@registry.readers("spacy.JsonlCorpus.v1")
def create_jsonl_corpus(
    path: Optional[str] = None,
    min_length: int = 0,
    max_length: int = 0,
    limit: int = 0,
    shuffle: bool = False,
    seed: int = 0,
    cache: bool = True,
) -> Corpus:
    if path is None:
        raise ValueError("JsonlCorpus path is required")
    return Corpus(
        path, max_length=max_length, limit=limit, shuffle=shuffle, seed=seed, cache=cache
    )
