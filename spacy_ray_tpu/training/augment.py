"""Data augmenters for the [corpora.train.augmenter] config slot.

Capability parity with spaCy's training augmenters (spacy/training/augment.py
— part of the training stack the reference drives, SURVEY.md §1 E2). An
augmenter is ``Example -> Iterator[Example]``, applied to the training
stream every epoch (training/corpus.py ``Corpus._augment``); yielding the
original plus variants oversamples, yielding only a variant rewrites.

Registered (same names AND semantics as spaCy so configs port unchanged —
the variant REPLACES the original with probability ``level``; the epoch
size does not change):

* ``spacy.lower_case.v1(level)`` — with probability ``level``, yield a
  fully lower-cased copy instead of the original.
* ``spacy.orth_variants.v1(level, lower, orth_variants)`` — with
  probability ``level``, yield a copy where tokens are swapped for
  spelling variants: ``orth_variants = {"single": [{"tags": [...],
  "variants": [...]}, ...], "paired": [{"tags": [...], "variants":
  [["``", "''"], ['"', '"']]}, ...]}``. "single" groups replace any member
  token with another member; "paired" groups (quote pairs) pick one target
  pair per doc and map each matched token to the same position in it.
  Tag restrictions apply when given; with probability ``lower`` the copy
  is additionally lower-cased.

Augmented copies keep all gold annotation (tags/heads/deps/ents/spans) —
only surface forms change, which is the point: the model must be robust to
casing/spelling variation the gold structure is invariant to.
"""

from __future__ import annotations

import random
from typing import Any, Callable, Dict, Iterator, List, Optional

from ..pipeline.doc import Doc, Example
from ..registry import registry


def _copy_with_words(doc: Doc, words: List[str]) -> Doc:
    import copy

    new = copy.deepcopy(doc)
    new.words = list(words)
    return new


def _lowered(doc: Doc) -> Doc:
    return _copy_with_words(doc, [w.lower() for w in doc.words])


@registry.augmenters("spacy.lower_case.v1")
def create_lower_casing_augmenter(level: float = 0.3, seed: int = 0) -> Callable:
    rng = random.Random(seed)

    def augment(eg: Example) -> Iterator[Example]:
        if rng.random() < level:
            yield Example.from_gold(_lowered(eg.reference))
        else:
            yield eg

    return augment


@registry.augmenters("spacy.orth_variants.v1")
def create_orth_variants_augmenter(
    level: float = 0.3,
    lower: float = 0.0,
    orth_variants: Optional[Dict[str, Any]] = None,
    seed: int = 0,
) -> Callable:
    singles = (orth_variants or {}).get("single", [])
    paired = (orth_variants or {}).get("paired", [])
    # word -> (variant group, tag restriction) for O(1) lookup
    table: Dict[str, Any] = {}
    for entry in singles:
        variants = entry.get("variants", [])
        tags = set(entry.get("tags", []))
        for v in variants:
            table[v] = (variants, tags)
    # word -> (positions it can occupy in a pair, all pair groups, tags);
    # a form like the straight quote occupies BOTH positions of its pair —
    # such forms alternate open/close by occurrence order in the doc
    pair_table: Dict[str, Any] = {}
    for entry in paired:
        groups = entry.get("variants", [])
        tags = set(entry.get("tags", []))
        for group in groups:
            for pos, form in enumerate(group):
                if form in pair_table:
                    pair_table[form][0].add(pos)
                else:
                    pair_table[form] = ({pos}, groups, tags)
    rng = random.Random(seed)

    def augment(eg: Example) -> Iterator[Example]:
        if rng.random() >= level:
            yield eg
            return
        ref = eg.reference
        new_words = list(ref.words)
        changed = False
        chosen_pairs: Dict[int, List[str]] = {}  # id(groups) -> target pair
        seen_count: Dict[str, int] = {}  # ambiguous-form occurrence parity
        for i, w in enumerate(new_words):
            hit = table.get(w)
            if hit is not None:
                variants, tags = hit
                if not tags or (ref.tags and ref.tags[i] in tags):
                    alt = [v for v in variants if v != w]
                    if alt:
                        new_words[i] = rng.choice(alt)
                        changed = True
                    continue
            phit = pair_table.get(w)
            if phit is not None:
                positions, groups, tags = phit
                if tags and (not ref.tags or ref.tags[i] not in tags):
                    continue
                if len(positions) == 1:
                    pos = next(iter(positions))
                else:
                    # e.g. the straight quote is both opener and closer:
                    # alternate by occurrence (1st=open, 2nd=close, ...)
                    n_seen = seen_count.get(w, 0)
                    seen_count[w] = n_seen + 1
                    pos = n_seen % 2
                # one consistent target pair per doc per group set, so an
                # opening quote and its closer swap together
                target = chosen_pairs.setdefault(id(groups), rng.choice(groups))
                if pos < len(target) and target[pos] != w:
                    new_words[i] = target[pos]
                    changed = True
        do_lower = rng.random() < lower
        if not changed and not do_lower:
            yield eg
            return
        doc = _copy_with_words(ref, new_words)
        if do_lower:
            doc.words = [w.lower() for w in doc.words]
        yield Example.from_gold(doc)

    return augment
