"""Checkpoint / resume: params + optimizer state + loop position.

The reference defines a save path but never wires it (reference
worker.py:219-222 ``save_checkpoint``; ``--output`` dropped with a TODO at
train_cli.py:41 — SURVEY.md §2.4 "Checkpointing unreachable"), and has no
resume at all (SURVEY.md §5.4). Here both are first-class:

* ``save_params`` / ``load_params``: portable .npz of the flattened params
  pytree ('/'-joined stable path keys) — the exported-model format.
* ``TrainCheckpoint``: full training state (params, optax opt_state, step,
  epoch, rng, best score/step, data position) for exact resume.

Arrays are gathered to host before writing; restore re-shards by whatever
shardings the caller puts them under.

Integrity + history (the resilience subsystem's torn-checkpoint story):
every generation's files are SHA-256-stamped in its meta, the last
``keep`` generations are retained, and ``load()`` verifies digests and
falls back generation-by-generation to the newest INTACT one — a torn,
truncated, or missing file is a warning and an older generation, never a
crash and never a silently-wrong resume (with ZeRO-1-sharded opt state a
desynced params/opt_state pair is undetectable downstream, cf.
arXiv:2004.13336). All corrupt/partial paths raise one typed
:class:`CheckpointCorrupt`, which the fallback logic catches.
"""

from __future__ import annotations

import hashlib
import json
import pickle
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from .resilience import log_event, maybe_fail, retry_io


class CheckpointCorrupt(RuntimeError):
    """A checkpoint generation is torn, truncated, or missing pieces.

    The ONE error type every corrupt/partial-checkpoint path raises —
    including pre-stamping layouts whose ``opt_state.pkl`` vanished (which
    used to surface as an opaque KeyError/pickle error) — so fallback and
    resume logic can catch exactly "this generation is bad" and nothing
    else."""


def _sha256_file(path: Path) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


class _HashingWriter:
    """File tee that hashes bytes as they are written — valid ONLY for
    sequential writers (pickle). Zip-based writers (np.savez) seek back to
    patch entry headers, which would desync digest from file bytes; the
    .npz digest therefore comes from a read-back of the written file."""

    __slots__ = ("_f", "_h")

    def __init__(self, f, h):
        self._f = f
        self._h = h

    def write(self, b):
        self._h.update(b)
        return self._f.write(b)


def gather_to_host(tree: Any) -> Any:
    """Fetch a (possibly cross-host-sharded) pytree to host numpy.

    ZeRO-1 opt state is sharded over the data axis; on multi-host meshes its
    shards span non-addressable devices, where a bare device_get raises —
    gather via multihost_utils first.
    """
    def fetch(x):
        if hasattr(x, "is_fully_addressable") and not x.is_fully_addressable:
            from jax.experimental import multihost_utils

            return np.asarray(multihost_utils.process_allgather(x, tiled=True))
        return np.asarray(jax.device_get(x))

    return jax.tree_util.tree_map(fetch, tree)


def _flatten(tree: Any, prefix: str = "") -> Dict[str, np.ndarray]:
    out: Dict[str, np.ndarray] = {}
    if isinstance(tree, dict):
        for k in sorted(tree):
            sub = f"{prefix}/{k}" if prefix else str(k)
            out.update(_flatten(tree[k], sub))
    else:
        out[prefix] = np.asarray(jax.device_get(tree))
    return out


def _unflatten(flat: Dict[str, np.ndarray]) -> Dict[str, Any]:
    root: Dict[str, Any] = {}
    for path, arr in flat.items():
        parts = path.split("/")
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr
    return root


def save_params(path, params: Any) -> None:
    flat = _flatten(params)
    np.savez(str(path), **flat)


def load_params(path) -> Dict[str, Any]:
    with np.load(str(path)) as data:
        flat = {k: data[k] for k in data.files}
    import jax.numpy as jnp

    return jax.tree_util.tree_map(jnp.asarray, _unflatten(flat))


def _gen_stamp(meta_path: Path) -> Optional[int]:
    """Stamp encoded in a per-generation meta filename, or None."""
    name = meta_path.name
    if not (name.startswith("train_meta-") and name.endswith(".json")):
        return None
    try:
        return int(name[len("train_meta-"):-len(".json")])
    except ValueError:
        return None


class TrainCheckpoint:
    """Full training-state checkpoint directory with generation history.

    Layout per generation ``stamp`` (= the step it was written at):
    ``params-{stamp}.npz``, ``opt_state-{stamp}.pkl`` (optax states are
    nested namedtuples whose structure the restore side reconstructs, so
    pickle of host numpy), and ``train_meta-{stamp}.json`` carrying the
    loop state plus SHA-256 digests of the two array files. The un-stamped
    ``train_meta.json`` — written LAST via atomic os.replace — is the
    pointer to the newest generation; the last ``keep`` generations are
    retained so a corrupt newest generation falls back, not crashes.
    """

    @staticmethod
    def save(
        path,
        *,
        params: Any,
        opt_state: Any,
        step: int,
        epoch: int,
        rng: Any,
        best_score: float,
        best_step: int,
        extra: Optional[Dict[str, Any]] = None,
        keep: int = 2,
    ) -> None:
        """Crash-safe write: array files are generation-stamped by step and
        the pointer meta — written LAST via atomic os.replace — names the
        generation it points at. A crash at ANY point leaves the previous
        complete generations loadable (a torn write of un-stamped files
        could pair an old meta with new params: silently wrong resume).

        Gathers/serialization happen once; only the file writes sit inside
        the transient-I/O retry (tmp + os.replace makes them idempotent).
        """
        import os

        path = Path(path)
        keep = max(int(keep), 1)
        stamp = int(step)
        host_opt = gather_to_host(opt_state)
        meta = {
            "step": int(step),
            "epoch": int(epoch),
            "rng": np.asarray(jax.device_get(rng)).tolist(),
            "best_score": float(best_score),
            "best_step": int(best_step),
            "extra": extra or {},
            "stamp": stamp,
        }

        def write_files() -> None:
            maybe_fail("checkpoint-write")
            path.mkdir(parents=True, exist_ok=True)
            # tmp + os.replace even for the stamped files: a restart WITHOUT
            # --resume can checkpoint at the same step the live meta already
            # points at, and an in-place rewrite of that file would reopen
            # the torn-write hole for exactly that generation
            # np.savez ALWAYS appends .npz to a non-.npz name, so the written
            # file is deterministically params-{stamp}.npz.tmp.npz — never
            # branch on exists(): a stale literal .tmp left by other tooling
            # would be promoted over the freshly written file
            params_tmp = path / f"params-{stamp}.npz.tmp"
            save_params(params_tmp, params)
            os.replace(
                params_tmp.with_suffix(params_tmp.suffix + ".npz"),
                path / f"params-{stamp}.npz",
            )
            opt_tmp = path / f"opt_state-{stamp}.pkl.tmp"
            opt_hash = hashlib.sha256()
            with open(opt_tmp, "wb") as f:
                # the opt state is the big file under ZeRO-1 — hash it
                # while writing instead of a second full read
                pickle.dump(host_opt, _HashingWriter(f, opt_hash))
            os.replace(opt_tmp, path / f"opt_state-{stamp}.pkl")
            # load() re-hashes exactly what it is about to read, so any
            # torn/truncated byte shows up
            meta["digests"] = {
                f"params-{stamp}.npz": _sha256_file(path / f"params-{stamp}.npz"),
                f"opt_state-{stamp}.pkl": opt_hash.hexdigest(),
            }
            text = json.dumps(meta, indent=2)
            # per-generation meta first (enables fallback), pointer last
            # (atomic commit of "this is the newest generation")
            gen_tmp = path / f"train_meta-{stamp}.json.tmp"
            gen_tmp.write_text(text, encoding="utf8")
            os.replace(gen_tmp, path / f"train_meta-{stamp}.json")
            tmp = path / "train_meta.json.tmp"
            tmp.write_text(text, encoding="utf8")
            os.replace(tmp, path / "train_meta.json")

        retry_io("checkpoint-write", write_files)
        # retention: the generation just written plus the newest keep-1
        # committed generations BELOW it. Stamps ABOVE the one just
        # written are an abandoned lineage (a restart WITHOUT --resume
        # re-counts steps from 0 into the same directory) — retaining
        # them would let load()'s newest-stamp-first fallback silently
        # resume the abandoned run's state, so they are deleted. A crash
        # before this cleanup only leaves extra files behind.
        committed = sorted(
            s
            for s in (_gen_stamp(p) for p in path.glob("train_meta-*.json"))
            if s is not None and s < stamp
        )
        retained = set(committed[-(keep - 1):]) if keep > 1 else set()
        retained.add(stamp)
        for pattern, suffix in (
            ("params-*.npz", ".npz"),
            ("opt_state-*.pkl", ".pkl"),
            ("train_meta-*.json", ".json"),
        ):
            prefix = pattern.split("*", 1)[0]
            for old in path.glob(pattern):
                try:
                    old_stamp = int(old.name[len(prefix):-len(suffix)])
                except ValueError:
                    continue
                if old_stamp not in retained:
                    old.unlink(missing_ok=True)
        # tmp stragglers from crashed earlier saves (params-*.npz.tmp.npz,
        # *.pkl.tmp, *.json.tmp): this save's own tmps were all promoted
        # above, so anything still wearing a tmp suffix is garbage — on a
        # crash-looping fleet these are full-size params/opt_state copies
        for pattern in ("*.tmp", "*.tmp.npz"):
            for stray in path.glob(pattern):
                stray.unlink(missing_ok=True)

    # -- loading ------------------------------------------------------

    @staticmethod
    def _read_meta(meta_path: Path) -> Dict[str, Any]:
        try:
            meta = json.loads(meta_path.read_text(encoding="utf8"))
        except (OSError, ValueError) as e:
            raise CheckpointCorrupt(
                f"unreadable checkpoint meta {meta_path}: {e}"
            ) from e
        if not isinstance(meta, dict) or "step" not in meta:
            raise CheckpointCorrupt(
                f"malformed checkpoint meta {meta_path}: not a train_meta dict"
            )
        return meta

    @staticmethod
    def _load_generation(path: Path, meta: Dict[str, Any]) -> Dict[str, Any]:
        """Load one generation described by ``meta``; verify digests when
        present. EVERY failure mode — missing file, torn npz/pickle, digest
        mismatch, missing meta key — raises :class:`CheckpointCorrupt`."""
        import jax.numpy as jnp

        stamp = meta.get("stamp")
        if stamp is not None:
            params_file = path / f"params-{int(stamp)}.npz"
            opt_file = path / f"opt_state-{int(stamp)}.pkl"
        else:  # pre-stamping checkpoints (round <= 4 layouts): no digests
            params_file = path / "params.npz"
            opt_file = path / "opt_state.pkl"
        for f in (params_file, opt_file):
            if not f.exists():
                raise CheckpointCorrupt(f"checkpoint file missing: {f}")
        digests = meta.get("digests") or {}
        for f in (params_file, opt_file):
            expect = digests.get(f.name)
            if expect is not None and _sha256_file(f) != expect:
                raise CheckpointCorrupt(
                    f"checkpoint digest mismatch: {f} (torn or tampered write)"
                )
        try:
            params = load_params(params_file)
            with open(opt_file, "rb") as fh:
                opt_state = pickle.load(fh)
            opt_state = jax.tree_util.tree_map(jnp.asarray, opt_state)
            return {
                "params": params,
                "opt_state": opt_state,
                "step": meta["step"],
                "epoch": meta["epoch"],
                "rng": jnp.asarray(np.array(meta["rng"], dtype=np.uint32)),
                "best_score": meta["best_score"],
                "best_step": meta["best_step"],
                "extra": meta.get("extra", {}),
            }
        except CheckpointCorrupt:
            raise
        except Exception as e:
            # torn zip, truncated pickle, missing meta key, bad rng shape —
            # one typed error for every partial-checkpoint shape
            raise CheckpointCorrupt(
                f"corrupt checkpoint generation "
                f"{'stamp ' + str(stamp) if stamp is not None else '(pre-stamping)'} "
                f"in {path}: {type(e).__name__}: {e}"
            ) from e

    @staticmethod
    def generation_stamps(path) -> List[int]:
        """Stamps of every generation whose per-generation meta exists in
        ``path``, ascending. Presence of the meta means the writer
        COMMITTED the generation (array files + digests land before it —
        see :meth:`save`); intactness is still verified at load time."""
        return sorted(
            s
            for s in (
                _gen_stamp(p) for p in Path(path).glob("train_meta-*.json")
            )
            if s is not None
        )

    @staticmethod
    def load(path) -> Optional[Dict[str, Any]]:
        """Load the newest INTACT generation.

        Candidates are the pointer meta plus every per-generation meta,
        newest first; a corrupt generation logs a warning and falls back to
        the next. Returns None when the directory holds no checkpoint at
        all (fresh start); raises :class:`CheckpointCorrupt` only when
        every present generation is corrupt.
        """
        path = Path(path)
        candidates: List[Tuple[int, Path]] = []
        for meta_path in path.glob("train_meta-*.json"):
            stamp = _gen_stamp(meta_path)
            if stamp is not None:
                candidates.append((stamp, meta_path))
        candidates.sort(key=lambda c: c[0], reverse=True)
        pointer = path / "train_meta.json"
        if pointer.exists():
            # pointer first: it names the generation the last completed
            # save committed (and is the ONLY meta in pre-history layouts)
            candidates.insert(0, (-1, pointer))
        elif candidates:
            # generations exist but the pointer vanished: still loadable
            # via the stamped metas, but something deleted files out from
            # under us — say so rather than silently resuming older state
            log_event(
                "checkpoint-fallback",
                f"pointer meta train_meta.json missing in {path}; scanning "
                "generation metas",
                path=str(path),
            )
        if not candidates:
            return None
        tried: set = set()
        last_err: Optional[CheckpointCorrupt] = None
        for _, meta_path in candidates:
            try:
                meta = TrainCheckpoint._read_meta(meta_path)
                stamp = meta.get("stamp")
                if stamp in tried:
                    continue
                tried.add(stamp)
                state = TrainCheckpoint._load_generation(path, meta)
            except CheckpointCorrupt as e:
                last_err = e
                log_event(
                    "checkpoint-fallback",
                    f"{e} — trying the previous generation",
                    path=str(path),
                )
                continue
            if last_err is not None:
                log_event(
                    "checkpoint-fallback",
                    f"recovered from generation stamp {meta.get('stamp')} "
                    f"(step {state['step']}) in {path}",
                    path=str(path),
                    step=int(state["step"]),
                )
            return state
        raise CheckpointCorrupt(
            f"no intact checkpoint generation in {path} "
            f"(last error: {last_err})"
        )


class Checkpoints:
    """Read-only view of a :class:`TrainCheckpoint` directory for a
    CONCURRENT reader (the live-serving checkpoint watcher) while a
    training process keeps writing into it.

    The reader-vs-writer contract it relies on — the writer side is
    :meth:`TrainCheckpoint.save`, and every property below is load-
    bearing for a reader that races it:

    1. **Array files land before their meta.** ``params-{stamp}.npz``
       and ``opt_state-{stamp}.pkl`` are fully written (tmp +
       ``os.replace``) BEFORE ``train_meta-{stamp}.json`` appears, so a
       per-generation meta's existence means its array files are
       complete on disk (modulo torn writes, which digests catch).
    2. **Every rename is atomic.** A reader never observes a
       half-written file under a final name — only a missing file
       (generation not committed yet / already retired) or a complete
       one. Torn bytes can only come from the filesystem itself, and
       the SHA-256 digests in the meta catch exactly that.
    3. **Retention deletes oldest-first, after the new generation is
       committed.** A reader holding a stamp may find its files deleted
       on the NEXT access (the generation aged out) — that surfaces as
       :class:`CheckpointCorrupt` ("file missing"), which callers treat
       as "move on to a newer generation", never as data corruption.

    Verification policy: :meth:`load_generation` re-hashes the exact
    bytes it is about to deserialize, the same rule ``load()`` applies —
    a torn or mid-retirement generation raises one typed
    :class:`CheckpointCorrupt` and the caller falls back/retries; it is
    never loaded and never a crash.
    """

    def __init__(self, path) -> None:
        self.path = Path(path)

    def generations(self) -> List[int]:
        """Committed generation stamps, ascending (cheap: directory scan
        only, no digest work)."""
        return TrainCheckpoint.generation_stamps(self.path)

    def _meta_for(self, stamp: int) -> Dict[str, Any]:
        return TrainCheckpoint._read_meta(
            self.path / f"train_meta-{int(stamp)}.json"
        )

    def verify_generation(self, stamp: int, *, params_only: bool = False) -> None:
        """Digest-verify one generation's files without deserializing
        them; raises :class:`CheckpointCorrupt` on any missing/torn
        piece. Cheaper than a load (no unpickle, no jnp conversion) —
        the watcher's "is it worth loading?" probe. ``params_only``
        skips the opt_state file entirely (the serving-swap question:
        for Adam that file is ~2x the param bytes of pure hash I/O a
        swap would then discard)."""
        meta = self._meta_for(stamp)
        digests = meta.get("digests") or {}
        files = [self.path / f"params-{int(stamp)}.npz"]
        if not params_only:
            files.append(self.path / f"opt_state-{int(stamp)}.pkl")
        for f in files:
            if not f.exists():
                raise CheckpointCorrupt(f"checkpoint file missing: {f}")
            expect = digests.get(f.name)
            if expect is not None and _sha256_file(f) != expect:
                raise CheckpointCorrupt(
                    f"checkpoint digest mismatch: {f} (torn or tampered "
                    "write)"
                )

    def latest_intact_generation(
        self, *, params_only: bool = False
    ) -> Optional[int]:
        """Newest stamp whose files digest-verify, or None when the
        directory holds no verifiable generation. A torn newest
        generation falls back to the next, the same walk ``load()``
        does — one fallback policy, two consumers. ``params_only``
        applies the serving-swap verification scope (see
        :meth:`verify_generation`)."""
        for stamp in sorted(self.generations(), reverse=True):
            try:
                self.verify_generation(stamp, params_only=params_only)
            except CheckpointCorrupt:
                continue
            return stamp
        return None

    def load_generation(self, stamp: int) -> Dict[str, Any]:
        """Load one specific generation (params/opt_state/step/... — the
        ``load()`` state dict), digest-verified. Raises
        :class:`CheckpointCorrupt` when torn, missing, or retired."""
        meta = self._meta_for(stamp)
        if meta.get("stamp") != int(stamp):
            raise CheckpointCorrupt(
                f"generation meta train_meta-{stamp}.json carries stamp "
                f"{meta.get('stamp')!r} (directory rewritten under us?)"
            )
        return TrainCheckpoint._load_generation(self.path, meta)

    def load_generation_params(self, stamp: int) -> Dict[str, Any]:
        """Load ONLY one generation's param tree, digest-verified —
        the serving hot-swap path. Deliberately narrower than
        :meth:`load_generation`: it never touches ``opt_state`` (which
        a swap discards anyway — for Adam that is ~2x the param bytes
        of load + hash + host->device churn per swap) and therefore
        never runs ``pickle.load`` at all, which matters because the
        ``/admin/swap`` route is network-reachable. Returns
        ``{"params": tree, "step": stamp}``; raises
        :class:`CheckpointCorrupt` on any torn/missing/retired piece."""
        meta = self._meta_for(stamp)
        if meta.get("stamp") != int(stamp):
            raise CheckpointCorrupt(
                f"generation meta train_meta-{stamp}.json carries stamp "
                f"{meta.get('stamp')!r} (directory rewritten under us?)"
            )
        params_file = self.path / f"params-{int(stamp)}.npz"
        if not params_file.exists():
            raise CheckpointCorrupt(f"checkpoint file missing: {params_file}")
        expect = (meta.get("digests") or {}).get(params_file.name)
        if expect is not None and _sha256_file(params_file) != expect:
            raise CheckpointCorrupt(
                f"checkpoint digest mismatch: {params_file} (torn or "
                "tampered write)"
            )
        try:
            params = load_params(params_file)
        except Exception as e:
            raise CheckpointCorrupt(
                f"corrupt checkpoint generation stamp {stamp} in "
                f"{self.path}: {type(e).__name__}: {e}"
            ) from e
        return {"params": params, "step": int(meta.get("step", stamp))}
