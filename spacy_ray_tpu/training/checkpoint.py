"""Checkpoint / resume: params + optimizer state + loop position.

The reference defines a save path but never wires it (reference
worker.py:219-222 ``save_checkpoint``; ``--output`` dropped with a TODO at
train_cli.py:41 — SURVEY.md §2.4 "Checkpointing unreachable"), and has no
resume at all (SURVEY.md §5.4). Here both are first-class:

* ``save_params`` / ``load_params``: portable .npz of the flattened params
  pytree ('/'-joined stable path keys) — the exported-model format.
* ``TrainCheckpoint``: full training state (params, optax opt_state, step,
  epoch, rng, best score/step, data position) for exact resume.

The on-disk layout is the CANONICAL UNSHARDED logical state: whatever mesh
the run was sharded over, ``load()`` returns full host arrays, and resume
re-shards them under the CURRENT mesh (``shard_opt_state`` /
``place_replicated``) — which is what makes checkpoints mesh-shape
portable (elastic resume: preempted at 8 devices, resume at 4 or 1).

Format v2 (``meta["format"] == 2``): when the optimizer state is sharded
on device (``update_sharding = "zero1" | "full"``), each owner shard is
written as its own sequentially-pickled, hash-while-write part file
(``opt_state-{stamp}.part{k}of{K}.pkl``) and the canonical layout is
REASSEMBLED at load — the writer never materializes the full opt_state on
one host (the old path allgathered every ZeRO-1 shard through every host
before hashing; arXiv:2004.13336's sharded-state regime makes that the
biggest single allocation of a save). Unsharded state (host trees, single
device, replicated mode) keeps the v1 single-pickle layout byte-for-byte,
and v1 generations stay loadable forever (regression-tested).

Integrity + history (the resilience subsystem's torn-checkpoint story):
every generation's files are SHA-256-stamped in its meta, the last
``keep`` generations are retained, and ``load()`` verifies digests and
falls back generation-by-generation to the newest INTACT one — a torn,
truncated, or missing file is a warning and an older generation, never a
crash and never a silently-wrong resume (with ZeRO-1-sharded opt state a
desynced params/opt_state pair is undetectable downstream, cf.
arXiv:2004.13336). All corrupt/partial paths raise one typed
:class:`CheckpointCorrupt`, which the fallback logic catches.
"""

from __future__ import annotations

import hashlib
import json
import pickle
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from .resilience import log_event, maybe_fail, retry_io


class CheckpointCorrupt(RuntimeError):
    """A checkpoint generation is torn, truncated, or missing pieces.

    The ONE error type every corrupt/partial-checkpoint path raises —
    including pre-stamping layouts whose ``opt_state.pkl`` vanished (which
    used to surface as an opaque KeyError/pickle error) — so fallback and
    resume logic can catch exactly "this generation is bad" and nothing
    else."""


def _sha256_file(path: Path) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


class _HashingWriter:
    """File tee that hashes bytes as they are written — valid ONLY for
    sequential writers (pickle). Zip-based writers (np.savez) seek back to
    patch entry headers, which would desync digest from file bytes; the
    .npz digest therefore comes from a read-back of the written file."""

    __slots__ = ("_f", "_h")

    def __init__(self, f, h):
        self._f = f
        self._h = h

    def write(self, b):
        self._h.update(b)
        return self._f.write(b)


# checkpoint layout version written by TrainCheckpoint.save when the opt
# state is device-sharded; absent/1 = the single-pickle legacy layout
CHECKPOINT_FORMAT = 2


def _opt_part_name(stamp: int, k: int, parts: int) -> str:
    return f"opt_state-{int(stamp)}.part{k}of{parts}.pkl"


def _opt_file_names(meta: Dict[str, Any], stamp: int) -> List[str]:
    """The opt-state file names one generation's meta commits to: the v2
    part files, or the single v1 pickle."""
    if int(meta.get("format", 1) or 1) >= 2:
        parts = int(meta.get("opt_shards", 1) or 1)
        return [_opt_part_name(stamp, k, parts) for k in range(parts)]
    return [f"opt_state-{int(stamp)}.pkl"]


def _index_key(index: Tuple, shape: Tuple[int, ...]) -> Tuple:
    """Normalize a shard's index (tuple of slices) into a hashable,
    sortable, picklable ((start, stop), ...) per axis."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = int(dim) if sl.stop is None else int(sl.stop)
        out.append((start, stop))
    return tuple(out)


def _shard_plan(leaves: List[Any]):
    """Decide the save layout for a flattened opt state.

    Returns None when nothing is device-sharded (v1 single-pickle path),
    else ``(parts, infos)`` where ``infos[i]`` is None for a
    replicated/host leaf (written once, into part 0) or a list of
    ``(part_ordinal, shard)`` for THIS process's owned (replica-0)
    shards — part ordinal = the shard's rank along the sharded axis, so
    part k holds every leaf's k-th owner shard and the part count is the
    data-axis size of the save-time mesh. The shard→part mapping is
    derived from the arrays' own shardings; nothing here assumes which
    mesh axis (or how many) the state was sharded over.
    """
    infos: List[Any] = []
    parts = 1
    any_sharded = False
    for leaf in leaves:
        sharding = getattr(leaf, "sharding", None)
        if (
            not isinstance(leaf, jax.Array)
            or sharding is None
            or sharding.is_fully_replicated
        ):
            infos.append(None)
            continue
        index_map = sharding.devices_indices_map(tuple(leaf.shape))
        unique = sorted({_index_key(ix, leaf.shape) for ix in index_map.values()})
        if len(unique) <= 1:
            infos.append(None)
            continue
        any_sharded = True
        parts = max(parts, len(unique))
        ordinal_of = {key: k for k, key in enumerate(unique)}
        owned = [
            (ordinal_of[_index_key(s.index, leaf.shape)], s)
            for s in leaf.addressable_shards
            if s.replica_id == 0
        ]
        infos.append(owned)
    if not any_sharded:
        return None
    return parts, infos


def _exchange_part_digests(
    local: Dict[int, str], parts: int, process_count: int
) -> Dict[int, str]:
    """Collect every opt-state part's SHA-256 onto every rank.

    Each part is written by exactly one process (its owner-shard's
    devices' host); rank 0 needs all of them for the meta. Encoded as a
    fixed-shape uint8 allgather (flag byte + 32 digest bytes per part)
    so every rank contributes the same-shaped array."""
    if process_count == 1:
        missing = [k for k in range(parts) if k not in local]
        if missing:
            raise RuntimeError(
                f"opt-state part(s) {missing} were not written (single "
                "process must own every shard)"
            )
        return dict(local)
    from jax.experimental import multihost_utils

    buf = np.zeros((parts, 33), np.uint8)
    for k, hexdigest in local.items():
        buf[k, 0] = 1
        buf[k, 1:] = np.frombuffer(bytes.fromhex(hexdigest), np.uint8)
    gathered = np.asarray(multihost_utils.process_allgather(buf)).reshape(
        -1, parts, 33
    )
    out: Dict[int, str] = {}
    for p in range(gathered.shape[0]):
        for k in range(parts):
            if gathered[p, k, 0]:
                out[k] = gathered[p, k, 1:].tobytes().hex()
    missing = [k for k in range(parts) if k not in out]
    if missing:
        raise RuntimeError(
            f"no process owned opt-state part(s) {missing} — mesh/sharding "
            "changed mid-save?"
        )
    return out


def gather_to_host(tree: Any) -> Any:
    """Fetch a (possibly cross-host-sharded) pytree to host numpy.

    ZeRO-1 opt state is sharded over the data axis; on multi-host meshes its
    shards span non-addressable devices, where a bare device_get raises —
    gather via multihost_utils first.
    """
    def fetch(x):
        if hasattr(x, "is_fully_addressable") and not x.is_fully_addressable:
            from jax.experimental import multihost_utils

            return np.asarray(multihost_utils.process_allgather(x, tiled=True))
        return np.asarray(jax.device_get(x))

    return jax.tree_util.tree_map(fetch, tree)


def _flatten(tree: Any, prefix: str = "") -> Dict[str, np.ndarray]:
    out: Dict[str, np.ndarray] = {}
    if isinstance(tree, dict):
        for k in sorted(tree):
            sub = f"{prefix}/{k}" if prefix else str(k)
            out.update(_flatten(tree[k], sub))
    else:
        out[prefix] = np.asarray(jax.device_get(tree))
    return out


def _unflatten(flat: Dict[str, np.ndarray]) -> Dict[str, Any]:
    root: Dict[str, Any] = {}
    for path, arr in flat.items():
        parts = path.split("/")
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr
    return root


def save_params(path, params: Any) -> None:
    flat = _flatten(params)
    np.savez(str(path), **flat)


def load_params(path) -> Dict[str, Any]:
    with np.load(str(path)) as data:
        flat = {k: data[k] for k in data.files}
    import jax.numpy as jnp

    return jax.tree_util.tree_map(jnp.asarray, _unflatten(flat))


def _assemble_opt_parts(files: List[Path]) -> Any:
    """Reassemble a format-v2 opt state from its digest-verified part
    files into the canonical unsharded host tree. Part 0's header carries
    the structure skeleton; every record fills (ordinal, index) into a
    full-shape array. Any inconsistency raises
    :class:`CheckpointCorrupt`."""
    skeleton = None
    n_leaves: Optional[int] = None
    slots: Dict[int, np.ndarray] = {}
    for f in files:
        try:
            with open(f, "rb") as fh:
                header = pickle.load(fh)
                if not isinstance(header, dict) or "n_leaves" not in header:
                    raise CheckpointCorrupt(
                        f"malformed opt-state part header in {f}"
                    )
                n_leaves = int(header["n_leaves"])
                if "skeleton" in header:
                    skeleton = header["skeleton"]
                while True:
                    try:
                        rec = pickle.load(fh)
                    except EOFError:
                        break
                    _tag, ordinal, index, gshape, dtype, piece = rec
                    if index is None:
                        slots[int(ordinal)] = np.asarray(piece)
                    else:
                        arr = slots.get(int(ordinal))
                        if arr is None:
                            arr = slots[int(ordinal)] = np.empty(
                                tuple(gshape), np.dtype(dtype)
                            )
                        arr[tuple(slice(a, b) for a, b in index)] = piece
        except CheckpointCorrupt:
            raise
        except Exception as e:
            raise CheckpointCorrupt(
                f"corrupt opt-state part {f}: {type(e).__name__}: {e}"
            ) from e
    if skeleton is None or n_leaves is None or len(slots) != n_leaves:
        raise CheckpointCorrupt(
            f"opt-state parts incomplete: have {len(slots)} of "
            f"{n_leaves if n_leaves is not None else '?'} leaves "
            f"(skeleton {'present' if skeleton is not None else 'MISSING'})"
        )
    try:
        leaves = [slots[i] for i in range(n_leaves)]
        return jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(skeleton), leaves
        )
    except KeyError as e:
        raise CheckpointCorrupt(
            f"opt-state parts missing leaf ordinal {e}"
        ) from e


def _write_params_npz(path: Path, stamp: int, params: Any) -> str:
    """Write ``params-{stamp}.npz`` via tmp + atomic replace; returns its
    SHA-256 (np.savez seeks back to patch zip headers, so the digest is a
    read-back of the final file — see :class:`_HashingWriter`)."""
    import os

    # np.savez ALWAYS appends .npz to a non-.npz name, so the written
    # file is deterministically params-{stamp}.npz.tmp.npz — never branch
    # on exists(): a stale literal .tmp left by other tooling would be
    # promoted over the freshly written file
    params_tmp = path / f"params-{stamp}.npz.tmp"
    save_params(params_tmp, params)
    os.replace(
        params_tmp.with_suffix(params_tmp.suffix + ".npz"),
        path / f"params-{stamp}.npz",
    )
    return _sha256_file(path / f"params-{stamp}.npz")


def _commit_meta(path: Path, stamp: int, meta: Dict[str, Any]) -> None:
    """Per-generation meta first (enables fallback), pointer last (atomic
    commit of "this is the newest generation")."""
    import os

    text = json.dumps(meta, indent=2)
    gen_tmp = path / f"train_meta-{stamp}.json.tmp"
    gen_tmp.write_text(text, encoding="utf8")
    os.replace(gen_tmp, path / f"train_meta-{stamp}.json")
    tmp = path / "train_meta.json.tmp"
    tmp.write_text(text, encoding="utf8")
    os.replace(tmp, path / "train_meta.json")


def _retention_sweep(path: Path, stamp: int, keep: int) -> None:
    """Retention: the generation just written plus the newest ``keep``-1
    committed generations BELOW it. Stamps ABOVE the one just written are
    an abandoned lineage (a restart WITHOUT --resume re-counts steps from
    0 into the same directory) — retaining them would let load()'s
    newest-stamp-first fallback silently resume the abandoned run's
    state, so they are deleted. Also sweeps tmp stragglers from crashed
    earlier saves. A crash before this cleanup only leaves extra files
    behind."""
    committed = sorted(
        s
        for s in (_gen_stamp(p) for p in path.glob("train_meta-*.json"))
        if s is not None and s < stamp
    )
    retained = set(committed[-(keep - 1):]) if keep > 1 else set()
    retained.add(stamp)
    for pattern, suffix in (
        ("params-*.npz", ".npz"),
        ("opt_state-*.pkl", ".pkl"),
        ("train_meta-*.json", ".json"),
    ):
        prefix = pattern.split("*", 1)[0]
        for old in path.glob(pattern):
            core = old.name[len(prefix):-len(suffix)]
            try:
                # "123" (v1) or "123.part0of8" (v2 opt shard)
                old_stamp = int(core.split(".", 1)[0])
            except ValueError:
                continue
            if old_stamp not in retained:
                old.unlink(missing_ok=True)
    # tmp stragglers (params-*.npz.tmp.npz, *.pkl.tmp, *.json.tmp): the
    # completed save's own tmps were all promoted, so anything still
    # wearing a tmp suffix is garbage — on a crash-looping fleet these
    # are full-size params/opt_state copies
    for pattern in ("*.tmp", "*.tmp.npz"):
        for stray in path.glob(pattern):
            stray.unlink(missing_ok=True)


def write_fleet_opt_part(
    path,
    *,
    stamp: int,
    part: int,
    parts: int,
    n_leaves: int,
    records,
    skeleton: Any = None,
) -> str:
    """One trainer-fleet process writes ITS owner-shard part file —
    ``opt_state-{stamp}.part{part}of{parts}.pkl``, byte-layout identical
    to the in-mesh v2 writer's (header + ``("leaf", ordinal, index,
    gshape, dtype, piece)`` records) so :func:`_assemble_opt_parts`
    reassembles fleet and in-mesh generations through the same code.

    ``records`` is an iterable of ``(ordinal, index, gshape, dtype,
    piece)`` (``index=None`` = whole leaf — part 0 only); ``skeleton``
    rides part 0's header. Returns the part's hash-while-write SHA-256
    for the meta the committing process (worker 0) writes.
    """
    import os

    path = Path(path)
    stamp = int(stamp)

    def write() -> str:
        maybe_fail("checkpoint-write")
        path.mkdir(parents=True, exist_ok=True)
        name = _opt_part_name(stamp, part, parts)
        tmp = path / (name + ".tmp")
        h = hashlib.sha256()
        with open(tmp, "wb") as f:
            w = _HashingWriter(f, h)
            header: Dict[str, Any] = {
                "part": int(part), "parts": int(parts),
                "n_leaves": int(n_leaves), "stamp": stamp,
            }
            if skeleton is not None:
                header["skeleton"] = skeleton
            pickle.dump(header, w)
            for ordinal, index, gshape, dtype, piece in records:
                pickle.dump(
                    (
                        "leaf", int(ordinal),
                        tuple(tuple(p) for p in index)
                        if index is not None else None,
                        tuple(int(d) for d in gshape), str(dtype),
                        np.asarray(piece),
                    ),
                    w,
                )
        os.replace(tmp, path / name)
        return h.hexdigest()

    return retry_io("checkpoint-write", write)


def commit_fleet_generation(
    path,
    *,
    params: Any,
    step: int,
    epoch: int,
    rng: Any,
    best_score: float,
    best_step: int,
    opt_shards: int,
    opt_digests: Dict[int, str],
    extra: Optional[Dict[str, Any]] = None,
    keep: int = 2,
) -> None:
    """Worker 0's half of a fleet checkpoint: the opt-state part files
    are ALREADY on disk (each written by its owning process via
    :func:`write_fleet_opt_part`; their digests arrive over the fleet's
    HTTP plane instead of the in-mesh digest allgather) — write the
    assembled params, the format-v2 meta naming every part's digest, the
    pointer, then run the shared retention sweep. The committed
    generation is indistinguishable from an in-mesh v2 save, which is
    what lets a single-process synchronous run ``--resume`` it."""
    path = Path(path)
    keep = max(int(keep), 1)
    stamp = int(step)
    extra = dict(extra or {})
    fleet_extra = extra.get("fleet")
    if isinstance(fleet_extra, dict) and (
        "epoch" in fleet_extra or "active" in fleet_extra
    ):
        # normalize the elastic-membership block BEFORE it hits disk: a
        # malformed epoch/active here would poison every later resume's
        # membership restore (worker.py falls back to the full original
        # fleet on a bad block, silently undoing a failover)
        fleet_extra = dict(fleet_extra)
        m_epoch = int(fleet_extra.get("epoch", 0))
        active = sorted(int(w) for w in fleet_extra.get("active") or [])
        if m_epoch < 0:
            raise ValueError(
                f"fleet membership epoch {m_epoch} is negative"
            )
        if not active or len(set(active)) != len(active) or active[0] < 0:
            raise ValueError(
                f"fleet membership active set {active!r} must be "
                "non-empty, unique, non-negative worker ids"
            )
        fleet_extra["epoch"] = m_epoch
        fleet_extra["active"] = active
        extra["fleet"] = fleet_extra
    meta: Dict[str, Any] = {
        "step": int(step),
        "epoch": int(epoch),
        "rng": np.asarray(rng).tolist(),
        "best_score": float(best_score),
        "best_step": int(best_step),
        "extra": extra,
        "stamp": stamp,
        "format": CHECKPOINT_FORMAT,
        "opt_shards": int(opt_shards),
    }

    def write_files() -> None:
        maybe_fail("checkpoint-write")
        path.mkdir(parents=True, exist_ok=True)
        digests = {
            f"params-{stamp}.npz": _write_params_npz(path, stamp, params)
        }
        for k, digest in opt_digests.items():
            digests[_opt_part_name(stamp, int(k), int(opt_shards))] = digest
        meta["digests"] = digests
        _commit_meta(path, stamp, meta)

    retry_io("checkpoint-write", write_files)
    _retention_sweep(path, stamp, keep)


def _gen_stamp(meta_path: Path) -> Optional[int]:
    """Stamp encoded in a per-generation meta filename, or None."""
    name = meta_path.name
    if not (name.startswith("train_meta-") and name.endswith(".json")):
        return None
    try:
        return int(name[len("train_meta-"):-len(".json")])
    except ValueError:
        return None


class TrainCheckpoint:
    """Full training-state checkpoint directory with generation history.

    Layout per generation ``stamp`` (= the step it was written at):
    ``params-{stamp}.npz``, ``opt_state-{stamp}.pkl`` (optax states are
    nested namedtuples whose structure the restore side reconstructs, so
    pickle of host numpy), and ``train_meta-{stamp}.json`` carrying the
    loop state plus SHA-256 digests of the two array files. The un-stamped
    ``train_meta.json`` — written LAST via atomic os.replace — is the
    pointer to the newest generation; the last ``keep`` generations are
    retained so a corrupt newest generation falls back, not crashes.
    """

    @staticmethod
    def save(
        path,
        *,
        params: Any,
        opt_state: Any,
        step: int,
        epoch: int,
        rng: Any,
        best_score: float,
        best_step: int,
        extra: Optional[Dict[str, Any]] = None,
        keep: int = 2,
    ) -> None:
        """Crash-safe write: array files are generation-stamped by step and
        the pointer meta — written LAST via atomic os.replace — names the
        generation it points at. A crash at ANY point leaves the previous
        complete generations loadable (a torn write of un-stamped files
        could pair an old meta with new params: silently wrong resume).

        Gathers/serialization happen once; only the file writes sit inside
        the transient-I/O retry (tmp + os.replace makes them idempotent).

        May be called from EVERY process of a multi-host run (rank gating
        is internal): with device-sharded opt state each process writes
        its OWN owner-shard part files (format v2) — no allgather of the
        full state through any host — then part digests are exchanged
        (one small collective) and rank 0 commits params + meta. Unsharded
        state keeps the v1 single-pickle layout, written by rank 0.
        """
        import os

        path = Path(path)
        keep = max(int(keep), 1)
        stamp = int(step)
        pidx = jax.process_index()
        pcnt = jax.process_count()
        opt_leaves, _ = jax.tree_util.tree_flatten(opt_state)
        plan = _shard_plan(opt_leaves)
        host_opt = None
        if plan is None:
            # v1: nothing sharded on device — ONE pickle of the host tree.
            # On multi-host this gather is a collective; every rank calls
            # save, so every rank reaches it.
            host_opt = gather_to_host(opt_state)
        meta = {
            "step": int(step),
            "epoch": int(epoch),
            "rng": np.asarray(jax.device_get(rng)).tolist(),
            "best_score": float(best_score),
            "best_step": int(best_step),
            "extra": extra or {},
            "stamp": stamp,
        }

        opt_digests: Dict[str, str] = {}
        if plan is not None:
            parts, infos = plan
            meta["format"] = CHECKPOINT_FORMAT
            meta["opt_shards"] = parts
            # structure-only skeleton: load reassembles the canonical full
            # tree by unflattening reassembled leaves into this treedef
            skeleton = jax.tree_util.tree_map(lambda _: 0, opt_state)
            by_part: Dict[int, List[Tuple[int, Any, Any]]] = {}
            for ordinal, (leaf, info) in enumerate(zip(opt_leaves, infos)):
                if info is None:
                    # replicated (or host) leaf: written once, by rank 0,
                    # into part 0
                    if pidx == 0:
                        by_part.setdefault(0, []).append((ordinal, None, leaf))
                else:
                    for k, shard in info:
                        by_part.setdefault(k, []).append(
                            (ordinal, _index_key(shard.index, leaf.shape), shard)
                        )
            local_digests: Dict[int, str] = {}

            def write_opt_parts() -> None:
                maybe_fail("checkpoint-write")
                path.mkdir(parents=True, exist_ok=True)
                local_digests.clear()
                for k in sorted(by_part):
                    name = _opt_part_name(stamp, k, parts)
                    tmp = path / (name + ".tmp")
                    h = hashlib.sha256()
                    with open(tmp, "wb") as f:
                        w = _HashingWriter(f, h)
                        header: Dict[str, Any] = {
                            "part": k, "parts": parts,
                            "n_leaves": len(opt_leaves), "stamp": stamp,
                        }
                        if k == 0:
                            header["skeleton"] = skeleton
                        pickle.dump(header, w)
                        for ordinal, index, data in by_part[k]:
                            # materialize ONE shard at a time: peak extra
                            # host memory is a single owner shard, never
                            # the full state
                            piece = np.asarray(
                                data.data if index is not None else data
                            )
                            pickle.dump(
                                (
                                    "leaf", ordinal, index,
                                    tuple(opt_leaves[ordinal].shape),
                                    str(piece.dtype), piece,
                                ),
                                w,
                            )
                    os.replace(tmp, path / name)
                    local_digests[k] = h.hexdigest()

            retry_io("checkpoint-write", write_opt_parts)
            # small collective: every rank learns every part's digest so
            # rank 0 can stamp the meta (NOT inside retry_io — a retry on
            # one rank only would desync the collective)
            for k, digest in _exchange_part_digests(
                local_digests, parts, pcnt
            ).items():
                opt_digests[_opt_part_name(stamp, k, parts)] = digest

        if pidx != 0:
            return

        def write_files() -> None:
            maybe_fail("checkpoint-write")
            path.mkdir(parents=True, exist_ok=True)
            # tmp + os.replace even for the stamped files: a restart WITHOUT
            # --resume can checkpoint at the same step the live meta already
            # points at, and an in-place rewrite of that file would reopen
            # the torn-write hole for exactly that generation
            digests = {
                f"params-{stamp}.npz": _write_params_npz(path, stamp, params)
            }
            if host_opt is not None:
                opt_tmp = path / f"opt_state-{stamp}.pkl.tmp"
                opt_hash = hashlib.sha256()
                with open(opt_tmp, "wb") as f:
                    # the opt state is the big file when state is big and
                    # unsharded — hash it while writing instead of a
                    # second full read
                    pickle.dump(host_opt, _HashingWriter(f, opt_hash))
                os.replace(opt_tmp, path / f"opt_state-{stamp}.pkl")
                digests[f"opt_state-{stamp}.pkl"] = opt_hash.hexdigest()
            else:
                digests.update(opt_digests)
            # load() re-hashes exactly what it is about to read, so any
            # torn/truncated byte shows up
            meta["digests"] = digests
            _commit_meta(path, stamp, meta)

        retry_io("checkpoint-write", write_files)
        _retention_sweep(path, stamp, keep)

    # -- loading ------------------------------------------------------

    @staticmethod
    def _read_meta(meta_path: Path) -> Dict[str, Any]:
        try:
            meta = json.loads(meta_path.read_text(encoding="utf8"))
        except (OSError, ValueError) as e:
            raise CheckpointCorrupt(
                f"unreadable checkpoint meta {meta_path}: {e}"
            ) from e
        if not isinstance(meta, dict) or "step" not in meta:
            raise CheckpointCorrupt(
                f"malformed checkpoint meta {meta_path}: not a train_meta dict"
            )
        return meta

    @staticmethod
    def _load_generation(path: Path, meta: Dict[str, Any]) -> Dict[str, Any]:
        """Load one generation described by ``meta``; verify digests when
        present. Format v2 generations reassemble the opt state's owner-
        shard part files back into the canonical unsharded layout (the
        caller re-shards under whatever mesh the resuming run built —
        mesh-shape-portable by construction). EVERY failure mode — missing
        file/part, torn npz/pickle, digest mismatch, missing meta key —
        raises :class:`CheckpointCorrupt`."""
        import jax.numpy as jnp

        fmt = int(meta.get("format", 1) or 1)
        stamp = meta.get("stamp")
        if stamp is not None:
            params_file = path / f"params-{int(stamp)}.npz"
            opt_files = [path / n for n in _opt_file_names(meta, int(stamp))]
        else:  # pre-stamping checkpoints (round <= 4 layouts): no digests
            params_file = path / "params.npz"
            opt_files = [path / "opt_state.pkl"]
        for f in (params_file, *opt_files):
            if not f.exists():
                raise CheckpointCorrupt(f"checkpoint file missing: {f}")
        digests = meta.get("digests") or {}
        for f in (params_file, *opt_files):
            expect = digests.get(f.name)
            if expect is not None and _sha256_file(f) != expect:
                raise CheckpointCorrupt(
                    f"checkpoint digest mismatch: {f} (torn or tampered write)"
                )
        try:
            params = load_params(params_file)
            if stamp is not None and fmt >= 2:
                opt_state = _assemble_opt_parts(opt_files)
            else:
                with open(opt_files[0], "rb") as fh:
                    opt_state = pickle.load(fh)
            opt_state = jax.tree_util.tree_map(jnp.asarray, opt_state)
            return {
                "params": params,
                "opt_state": opt_state,
                "step": meta["step"],
                "epoch": meta["epoch"],
                "rng": jnp.asarray(np.array(meta["rng"], dtype=np.uint32)),
                "best_score": meta["best_score"],
                "best_step": meta["best_step"],
                "extra": meta.get("extra", {}),
            }
        except CheckpointCorrupt:
            raise
        except Exception as e:
            # torn zip, truncated pickle, missing meta key, bad rng shape —
            # one typed error for every partial-checkpoint shape
            raise CheckpointCorrupt(
                f"corrupt checkpoint generation "
                f"{'stamp ' + str(stamp) if stamp is not None else '(pre-stamping)'} "
                f"in {path}: {type(e).__name__}: {e}"
            ) from e

    @staticmethod
    def generation_stamps(path) -> List[int]:
        """Stamps of every generation whose per-generation meta exists in
        ``path``, ascending. Presence of the meta means the writer
        COMMITTED the generation (array files + digests land before it —
        see :meth:`save`); intactness is still verified at load time."""
        return sorted(
            s
            for s in (
                _gen_stamp(p) for p in Path(path).glob("train_meta-*.json")
            )
            if s is not None
        )

    @staticmethod
    def load(path) -> Optional[Dict[str, Any]]:
        """Load the newest INTACT generation.

        Candidates are the pointer meta plus every per-generation meta,
        newest first; a corrupt generation logs a warning and falls back to
        the next. Returns None when the directory holds no checkpoint at
        all (fresh start); raises :class:`CheckpointCorrupt` only when
        every present generation is corrupt.
        """
        path = Path(path)
        candidates: List[Tuple[int, Path]] = []
        for meta_path in path.glob("train_meta-*.json"):
            stamp = _gen_stamp(meta_path)
            if stamp is not None:
                candidates.append((stamp, meta_path))
        candidates.sort(key=lambda c: c[0], reverse=True)
        pointer = path / "train_meta.json"
        if pointer.exists():
            # pointer first: it names the generation the last completed
            # save committed (and is the ONLY meta in pre-history layouts)
            candidates.insert(0, (-1, pointer))
        elif candidates:
            # generations exist but the pointer vanished: still loadable
            # via the stamped metas, but something deleted files out from
            # under us — say so rather than silently resuming older state
            log_event(
                "checkpoint-fallback",
                f"pointer meta train_meta.json missing in {path}; scanning "
                "generation metas",
                path=str(path),
            )
        if not candidates:
            return None
        tried: set = set()
        last_err: Optional[CheckpointCorrupt] = None
        for _, meta_path in candidates:
            try:
                meta = TrainCheckpoint._read_meta(meta_path)
                stamp = meta.get("stamp")
                if stamp in tried:
                    continue
                tried.add(stamp)
                state = TrainCheckpoint._load_generation(path, meta)
            except CheckpointCorrupt as e:
                last_err = e
                log_event(
                    "checkpoint-fallback",
                    f"{e} — trying the previous generation",
                    path=str(path),
                )
                continue
            if last_err is not None:
                log_event(
                    "checkpoint-fallback",
                    f"recovered from generation stamp {meta.get('stamp')} "
                    f"(step {state['step']}) in {path}",
                    path=str(path),
                    step=int(state["step"]),
                )
            return state
        raise CheckpointCorrupt(
            f"no intact checkpoint generation in {path} "
            f"(last error: {last_err})"
        )


class Checkpoints:
    """Read-only view of a :class:`TrainCheckpoint` directory for a
    CONCURRENT reader (the live-serving checkpoint watcher) while a
    training process keeps writing into it.

    The reader-vs-writer contract it relies on — the writer side is
    :meth:`TrainCheckpoint.save`, and every property below is load-
    bearing for a reader that races it:

    1. **Array files land before their meta.** ``params-{stamp}.npz``
       and ``opt_state-{stamp}.pkl`` are fully written (tmp +
       ``os.replace``) BEFORE ``train_meta-{stamp}.json`` appears, so a
       per-generation meta's existence means its array files are
       complete on disk (modulo torn writes, which digests catch).
    2. **Every rename is atomic.** A reader never observes a
       half-written file under a final name — only a missing file
       (generation not committed yet / already retired) or a complete
       one. Torn bytes can only come from the filesystem itself, and
       the SHA-256 digests in the meta catch exactly that.
    3. **Retention deletes oldest-first, after the new generation is
       committed.** A reader holding a stamp may find its files deleted
       on the NEXT access (the generation aged out) — that surfaces as
       :class:`CheckpointCorrupt` ("file missing"), which callers treat
       as "move on to a newer generation", never as data corruption.

    Verification policy: :meth:`load_generation` re-hashes the exact
    bytes it is about to deserialize, the same rule ``load()`` applies —
    a torn or mid-retirement generation raises one typed
    :class:`CheckpointCorrupt` and the caller falls back/retries; it is
    never loaded and never a crash.
    """

    def __init__(self, path) -> None:
        self.path = Path(path)

    def generations(self) -> List[int]:
        """Committed generation stamps, ascending (cheap: directory scan
        only, no digest work)."""
        return TrainCheckpoint.generation_stamps(self.path)

    def _meta_for(self, stamp: int) -> Dict[str, Any]:
        return TrainCheckpoint._read_meta(
            self.path / f"train_meta-{int(stamp)}.json"
        )

    def verify_generation(self, stamp: int, *, params_only: bool = False) -> None:
        """Digest-verify one generation's files without deserializing
        them; raises :class:`CheckpointCorrupt` on any missing/torn
        piece. Cheaper than a load (no unpickle, no jnp conversion) —
        the watcher's "is it worth loading?" probe. ``params_only``
        skips the opt_state file entirely (the serving-swap question:
        for Adam that file is ~2x the param bytes of pure hash I/O a
        swap would then discard)."""
        meta = self._meta_for(stamp)
        digests = meta.get("digests") or {}
        files = [self.path / f"params-{int(stamp)}.npz"]
        if not params_only:
            # v1 single pickle or v2 owner-shard parts — the meta says which
            files.extend(
                self.path / n for n in _opt_file_names(meta, int(stamp))
            )
        for f in files:
            if not f.exists():
                raise CheckpointCorrupt(f"checkpoint file missing: {f}")
            expect = digests.get(f.name)
            if expect is not None and _sha256_file(f) != expect:
                raise CheckpointCorrupt(
                    f"checkpoint digest mismatch: {f} (torn or tampered "
                    "write)"
                )

    def latest_intact_generation(
        self, *, params_only: bool = False
    ) -> Optional[int]:
        """Newest stamp whose files digest-verify, or None when the
        directory holds no verifiable generation. A torn newest
        generation falls back to the next, the same walk ``load()``
        does — one fallback policy, two consumers. ``params_only``
        applies the serving-swap verification scope (see
        :meth:`verify_generation`)."""
        for stamp in sorted(self.generations(), reverse=True):
            try:
                self.verify_generation(stamp, params_only=params_only)
            except CheckpointCorrupt:
                continue
            return stamp
        return None

    def load_generation(self, stamp: int) -> Dict[str, Any]:
        """Load one specific generation (params/opt_state/step/... — the
        ``load()`` state dict), digest-verified. Raises
        :class:`CheckpointCorrupt` when torn, missing, or retired."""
        meta = self._meta_for(stamp)
        if meta.get("stamp") != int(stamp):
            raise CheckpointCorrupt(
                f"generation meta train_meta-{stamp}.json carries stamp "
                f"{meta.get('stamp')!r} (directory rewritten under us?)"
            )
        return TrainCheckpoint._load_generation(self.path, meta)

    def load_generation_params(self, stamp: int) -> Dict[str, Any]:
        """Load ONLY one generation's param tree, digest-verified —
        the serving hot-swap path. Deliberately narrower than
        :meth:`load_generation`: it never touches ``opt_state`` (which
        a swap discards anyway — for Adam that is ~2x the param bytes
        of load + hash + host->device churn per swap) and therefore
        never runs ``pickle.load`` at all, which matters because the
        ``/admin/swap`` route is network-reachable. Returns
        ``{"params": tree, "step": stamp}``; raises
        :class:`CheckpointCorrupt` on any torn/missing/retired piece."""
        meta = self._meta_for(stamp)
        if meta.get("stamp") != int(stamp):
            raise CheckpointCorrupt(
                f"generation meta train_meta-{stamp}.json carries stamp "
                f"{meta.get('stamp')!r} (directory rewritten under us?)"
            )
        params_file = self.path / f"params-{int(stamp)}.npz"
        if not params_file.exists():
            raise CheckpointCorrupt(f"checkpoint file missing: {params_file}")
        expect = (meta.get("digests") or {}).get(params_file.name)
        if expect is not None and _sha256_file(params_file) != expect:
            raise CheckpointCorrupt(
                f"checkpoint digest mismatch: {params_file} (torn or "
                "tampered write)"
            )
        try:
            params = load_params(params_file)
        except Exception as e:
            raise CheckpointCorrupt(
                f"corrupt checkpoint generation stamp {stamp} in "
                f"{self.path}: {type(e).__name__}: {e}"
            ) from e
        return {"params": params, "step": int(meta.get("step", stamp))}
