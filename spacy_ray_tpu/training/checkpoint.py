"""Checkpoint / resume: params + optimizer state + loop position.

The reference defines a save path but never wires it (reference
worker.py:219-222 ``save_checkpoint``; ``--output`` dropped with a TODO at
train_cli.py:41 — SURVEY.md §2.4 "Checkpointing unreachable"), and has no
resume at all (SURVEY.md §5.4). Here both are first-class:

* ``save_params`` / ``load_params``: portable .npz of the flattened params
  pytree ('/'-joined stable path keys) — the exported-model format.
* ``TrainCheckpoint``: full training state (params, optax opt_state, step,
  epoch, rng, best score/step, data position) for exact resume.

Arrays are gathered to host before writing; restore re-shards by whatever
shardings the caller puts them under.
"""

from __future__ import annotations

import json
import pickle
from pathlib import Path
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np


def gather_to_host(tree: Any) -> Any:
    """Fetch a (possibly cross-host-sharded) pytree to host numpy.

    ZeRO-1 opt state is sharded over the data axis; on multi-host meshes its
    shards span non-addressable devices, where a bare device_get raises —
    gather via multihost_utils first.
    """
    def fetch(x):
        if hasattr(x, "is_fully_addressable") and not x.is_fully_addressable:
            from jax.experimental import multihost_utils

            return np.asarray(multihost_utils.process_allgather(x, tiled=True))
        return np.asarray(jax.device_get(x))

    return jax.tree_util.tree_map(fetch, tree)


def _flatten(tree: Any, prefix: str = "") -> Dict[str, np.ndarray]:
    out: Dict[str, np.ndarray] = {}
    if isinstance(tree, dict):
        for k in sorted(tree):
            sub = f"{prefix}/{k}" if prefix else str(k)
            out.update(_flatten(tree[k], sub))
    else:
        out[prefix] = np.asarray(jax.device_get(tree))
    return out


def _unflatten(flat: Dict[str, np.ndarray]) -> Dict[str, Any]:
    root: Dict[str, Any] = {}
    for path, arr in flat.items():
        parts = path.split("/")
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr
    return root


def save_params(path, params: Any) -> None:
    flat = _flatten(params)
    np.savez(str(path), **flat)


def load_params(path) -> Dict[str, Any]:
    with np.load(str(path)) as data:
        flat = {k: data[k] for k in data.files}
    import jax.numpy as jnp

    return jax.tree_util.tree_map(jnp.asarray, _unflatten(flat))


class TrainCheckpoint:
    """Full training-state checkpoint directory.

    Layout: state.pkl (opt_state pytree via pickle of host numpy),
    params.npz, meta.json. The opt_state is pickled because optax states are
    nested namedtuples whose structure the restore side reconstructs anyway;
    arrays inside are converted to numpy first.
    """

    @staticmethod
    def save(
        path,
        *,
        params: Any,
        opt_state: Any,
        step: int,
        epoch: int,
        rng: Any,
        best_score: float,
        best_step: int,
        extra: Optional[Dict[str, Any]] = None,
    ) -> None:
        """Crash-safe write: array files are generation-stamped by step and
        the meta file — written LAST via atomic os.replace — names the
        generation it points at. A crash at ANY point leaves the previous
        complete generation loadable (a torn write of un-stamped files
        could pair an old meta with new params: silently wrong resume)."""
        import os

        path = Path(path)
        path.mkdir(parents=True, exist_ok=True)
        stamp = int(step)
        # tmp + os.replace even for the stamped files: a restart WITHOUT
        # --resume can checkpoint at the same step the live meta already
        # points at, and an in-place rewrite of that file would reopen
        # the torn-write hole for exactly that generation
        # np.savez ALWAYS appends .npz to a non-.npz name, so the written
        # file is deterministically params-{stamp}.npz.tmp.npz — never
        # branch on exists(): a stale literal .tmp left by other tooling
        # would be promoted over the freshly written file
        params_tmp = path / f"params-{stamp}.npz.tmp"
        save_params(params_tmp, params)
        os.replace(
            params_tmp.with_suffix(params_tmp.suffix + ".npz"),
            path / f"params-{stamp}.npz",
        )
        host_opt = gather_to_host(opt_state)
        opt_tmp = path / f"opt_state-{stamp}.pkl.tmp"
        with open(opt_tmp, "wb") as f:
            pickle.dump(host_opt, f)
        os.replace(opt_tmp, path / f"opt_state-{stamp}.pkl")
        meta = {
            "step": int(step),
            "epoch": int(epoch),
            "rng": np.asarray(jax.device_get(rng)).tolist(),
            "best_score": float(best_score),
            "best_step": int(best_step),
            "extra": extra or {},
            "stamp": stamp,
        }
        tmp = path / "train_meta.json.tmp"
        tmp.write_text(json.dumps(meta, indent=2), encoding="utf8")
        os.replace(tmp, path / "train_meta.json")
        # previous generations are garbage once the meta points past them;
        # a crash before this cleanup only leaves extra files behind
        for old in path.glob("params-*.npz"):
            if old.name != f"params-{stamp}.npz":
                old.unlink(missing_ok=True)
        for old in path.glob("opt_state-*.pkl"):
            if old.name != f"opt_state-{stamp}.pkl":
                old.unlink(missing_ok=True)

    @staticmethod
    def load(path) -> Optional[Dict[str, Any]]:
        path = Path(path)
        if not (path / "train_meta.json").exists():
            return None
        import jax.numpy as jnp

        meta = json.loads((path / "train_meta.json").read_text(encoding="utf8"))
        stamp = meta.get("stamp")
        if stamp is not None:
            params_file = path / f"params-{int(stamp)}.npz"
            opt_file = path / f"opt_state-{int(stamp)}.pkl"
        else:  # pre-stamping checkpoints (round <= 4 layouts)
            params_file = path / "params.npz"
            opt_file = path / "opt_state.pkl"
        params = load_params(params_file)
        with open(opt_file, "rb") as f:
            opt_state = pickle.load(f)
        opt_state = jax.tree_util.tree_map(jnp.asarray, opt_state)
        return {
            "params": params,
            "opt_state": opt_state,
            "step": meta["step"],
            "epoch": meta["epoch"],
            "rng": jnp.asarray(np.array(meta["rng"], dtype=np.uint32)),
            "best_score": meta["best_score"],
            "best_step": meta["best_step"],
            "extra": meta.get("extra", {}),
        }
