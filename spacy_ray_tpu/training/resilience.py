"""Resilience subsystem: preemption-safe shutdown, hung-step watchdog,
retry-with-backoff for transient I/O, a deterministic fault-injection
harness, and the supervisor that relaunches a crashed training child.

The north-star is a trainer serving real TPU fleets, where preemption is
routine and a single wedged collective or torn checkpoint costs the whole
run. The reference has no fault story at all (SURVEY.md §2.4/§5.4:
checkpointing unreachable, no resume), and Ray's lineage-based fault
tolerance (Moritz et al., arXiv:1712.05889) is exactly the capability the
JAX port dropped with the actor runtime. This module restores it in SPMD
terms:

* :class:`ShutdownCoordinator` — SIGTERM/SIGINT set a flag the training
  loop polls at step boundaries; on multi-host the flag is allgathered so
  every rank checkpoints the SAME step, then the process exits with
  :data:`RC_PREEMPTED`.
* :class:`Watchdog` — a daemon thread fed a heartbeat after each completed
  step/eval. A desynced multi-host collective wedges forever with no
  exception to catch; the watchdog dumps every Python thread stack plus
  the input-pipeline stats to stderr and hard-exits :data:`RC_WATCHDOG`
  so the supervisor (or the cluster scheduler) can restart the run.
* :class:`RetryPolicy` / :func:`retry_io` — exponential backoff + jitter
  around transient I/O (corpus/DocBin opens, checkpoint writes), with an
  injectable clock/sleep/rng so tests never touch the wall clock.
* :class:`FaultPlan` — env/config-driven "fail site X on call N with
  error E" for the named sites in :data:`FAULT_SITES`; the resilience
  tests drive preemption, torn checkpoints, and retry paths with it
  deterministically.
* :class:`Supervisor` — ``train --max-restarts N`` wraps the training
  child: nonzero exits relaunch with ``--resume`` (recovering from the
  last intact checkpoint generation), relayed signals escalate
  SIGTERM → SIGKILL after a grace period (:func:`terminate_with_grace`).

Every event the subsystem emits goes through :func:`log_event`, which both
logs to the ``spacy_ray_tpu.training`` logger and queues a structured
record that the jsonl training logger drains into its next row — resume
anomalies and retries land in machine-readable logs, not just stderr.
"""

from __future__ import annotations

import logging
import os
import random
import signal
import subprocess
import sys
import threading
import time
import traceback
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "RC_PREEMPTED",
    "RC_WATCHDOG",
    "FAULT_SITES",
    "FAULT_PLAN_ENV",
    "ShutdownCoordinator",
    "Watchdog",
    "RetryPolicy",
    "retry_io",
    "set_default_retry_policy",
    "FaultInjected",
    "FaultPlan",
    "set_fault_plan",
    "get_fault_plan",
    "activate_env_fault_plan",
    "maybe_fail",
    "consume_poison",
    "consume_wire_fault",
    "partitioned",
    "corrupt_bytes",
    "terminate_with_grace",
    "Supervisor",
    "log_event",
    "drain_events",
]

# Distinct exit codes so supervisors/schedulers can tell outcomes apart:
# RC_PREEMPTED = clean preemption shutdown (checkpoint written at a step
# boundary, safe to resume); RC_WATCHDOG = hung step, state of the last
# checkpoint is intact but the process had to be hard-killed.
RC_PREEMPTED = 75  # EX_TEMPFAIL: transient by design — restart and resume
RC_WATCHDOG = 79

logger = logging.getLogger("spacy_ray_tpu.training")


# ----------------------------------------------------------------------
# Structured event log
# ----------------------------------------------------------------------

# bounded: a retry storm must not grow memory without bound before the
# next jsonl row drains it
_EVENTS: "deque[Dict[str, Any]]" = deque(maxlen=256)
_EVENTS_LOCK = threading.Lock()


def log_event(
    event: str, message: str, level: int = logging.WARNING, **fields: Any
) -> Dict[str, Any]:
    """Record a resilience event: the training logger (human path) plus a
    structured record the jsonl logger drains into its next row (machine
    path — resume anomalies and retries must be visible in jsonl logs,
    not only on a scrolled-away stderr)."""
    rec = {"event": event, "message": message, **fields}
    logger.log(level, "[%s] %s", event, message)
    with _EVENTS_LOCK:
        _EVENTS.append(rec)
    return rec


def drain_events() -> List[Dict[str, Any]]:
    """Return and clear the queued structured events (jsonl logger hook)."""
    with _EVENTS_LOCK:
        out = list(_EVENTS)
        _EVENTS.clear()
    return out


# ----------------------------------------------------------------------
# Preemption-aware shutdown
# ----------------------------------------------------------------------


class ShutdownCoordinator:
    """SIGTERM/SIGINT → a flag the training loop polls at step boundaries.

    The handler only sets an event (async-signal-safe); the loop decides
    when to act, so the checkpoint is always written at a step boundary
    with a consistent (params, opt_state, data-position) triple. On
    multi-host, :meth:`coordinated_stop` allgathers the flag so every rank
    stops — and checkpoints — the same step, even when the preemption
    notice only reached one host. A second SIGINT escalates to the
    previous handler (normally KeyboardInterrupt) for operators who really
    mean it.
    """

    SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self) -> None:
        self._flag = threading.Event()
        self._signum: Optional[int] = None
        self._prev: Dict[int, Any] = {}
        self._installed = False
        self._callbacks: List[Callable[[Optional[int]], Any]] = []

    # -- flag --------------------------------------------------------
    def add_callback(self, fn: Callable[[Optional[int]], Any]) -> None:
        """Register a hook fired from :meth:`request` (i.e. from the
        signal handler) — it must be async-signal-safe in practice: set
        an Event, flip a flag, never block. The serving front-end uses
        this to trip its drain gate the instant SIGTERM lands instead of
        waiting for the next admission poll."""
        self._callbacks.append(fn)

    def request(self, signum: Optional[int] = None) -> None:
        self._signum = signum
        self._flag.set()
        for cb in self._callbacks:
            try:
                cb(signum)
            except Exception:  # a broken hook must not break the handler
                pass

    @property
    def requested(self) -> bool:
        return self._flag.is_set()

    @property
    def signum(self) -> Optional[int]:
        return self._signum

    # -- signal wiring ------------------------------------------------
    def _handle(self, signum: int, frame: Any) -> None:
        if self._flag.is_set() and signum == signal.SIGINT:
            # second Ctrl-C: the operator wants OUT, not another graceful
            # lap — fall through to the previous handler
            prev = self._prev.get(signum)
            if callable(prev):
                prev(signum, frame)
                return
            raise KeyboardInterrupt
        self.request(signum)

    def install(self) -> "ShutdownCoordinator":
        """Install handlers (main thread only — elsewhere signal.signal
        raises, and a worker-thread train() can still poll a flag set by
        whoever owns the signals)."""
        if threading.current_thread() is not threading.main_thread():
            return self
        for signum in self.SIGNALS:
            try:
                self._prev[signum] = signal.signal(signum, self._handle)
            except (ValueError, OSError):  # pragma: no cover — exotic hosts
                pass
        self._installed = True
        return self

    def restore(self) -> None:
        if not self._installed:
            return
        for signum, prev in self._prev.items():
            try:
                signal.signal(signum, prev)
            except (ValueError, OSError):  # pragma: no cover
                pass
        self._prev.clear()
        self._installed = False

    # -- multi-host agreement -----------------------------------------
    def coordinated_stop(self, process_count: int = 1) -> bool:
        """Should the loop stop at THIS step boundary?

        Single-process: the local flag. Multi-host: allgather the flag —
        if ANY rank was signalled, every rank returns True at the same
        step, so all ranks write (rank 0) or participate in (all ranks,
        the opt-state gather is collective) the same checkpoint. This is
        one tiny allgather per step — noise next to the update's own
        collectives, and the price of never tearing a pod checkpoint.
        """
        if process_count <= 1:
            return self.requested
        import numpy as np
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(
            np.array([1 if self.requested else 0], np.int32)
        )
        return bool(int(np.max(flags)) > 0)


# ----------------------------------------------------------------------
# Hung-step watchdog
# ----------------------------------------------------------------------


class Watchdog:
    """Daemon thread that hard-exits the process when no heartbeat arrives
    within ``timeout_s``.

    A desynced multi-host collective (one rank crashed mid-allgather, a
    wedged relay tunnel) blocks inside compiled code with no exception to
    catch — the process sits forever and the whole pod's allocation burns.
    The watchdog's only job is to turn "wedged forever" into "dump
    diagnostics, exit :data:`RC_WATCHDOG`, let the supervisor resume from
    the last checkpoint".

    Diagnostics on fire: every Python thread's stack (the training thread
    shows WHERE it wedged) plus the input-pipeline stats snapshot. The
    exit is ``os._exit`` — a wedged collective ignores interpreter-level
    unwinding by definition.

    ``clock``/``sleep``/``exit_fn`` are injectable so tests drive the
    fire path with a fake clock and never wait on (or kill) anything real.
    """

    def __init__(
        self,
        timeout_s: float,
        *,
        stats_fn: Optional[Callable[[], Any]] = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        exit_fn: Optional[Callable[[int], None]] = None,
        stream: Any = None,
    ) -> None:
        if timeout_s <= 0:
            raise ValueError("watchdog timeout_s must be > 0 (0 disables it)")
        self.timeout_s = float(timeout_s)
        self._stats_fn = stats_fn
        self._clock = clock
        self._sleep = sleep
        self._exit_fn = exit_fn or (lambda rc: os._exit(rc))
        self._stream = stream
        self._last_beat = clock()
        self._stop = threading.Event()
        self._fired = False
        self._thread: Optional[threading.Thread] = None

    def beat(self) -> None:
        """Feed the watchdog — called after each completed step/eval."""
        self._last_beat = self._clock()

    def check(self) -> bool:
        """One poll: fire if the heartbeat is older than the timeout.
        Returns True when it fired (tests call this directly)."""
        if self._fired:
            return True
        if self._clock() - self._last_beat <= self.timeout_s:
            return False
        self._fired = True
        self._dump()
        self._exit_fn(RC_WATCHDOG)
        return True

    def _dump(self) -> None:
        stream = self._stream if self._stream is not None else sys.stderr
        stalled = self._clock() - self._last_beat
        lines = [
            f"[watchdog] no step heartbeat for {stalled:.1f}s "
            f"(timeout {self.timeout_s:.1f}s) — dumping threads and "
            f"exiting {RC_WATCHDOG}",
        ]
        frames = sys._current_frames()
        names = {t.ident: t.name for t in threading.enumerate()}
        for ident, frame in frames.items():
            lines.append(
                f"--- thread {names.get(ident, '?')} (ident {ident}) ---"
            )
            lines.append("".join(traceback.format_stack(frame)).rstrip())
        if self._stats_fn is not None:
            try:
                lines.append(f"[watchdog] input pipeline: {self._stats_fn()}")
            except Exception as e:  # diagnostics must never mask the exit
                lines.append(f"[watchdog] stats unavailable: {e!r}")
        try:
            stream.write("\n".join(lines) + "\n")
            stream.flush()
        except Exception:  # pragma: no cover — dead stderr
            pass

    def _run(self) -> None:
        poll = min(self.timeout_s / 4.0, 1.0)
        while not self._stop.is_set():
            if self.check():
                return
            self._sleep(poll)

    def start(self) -> "Watchdog":
        self._last_beat = self._clock()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="train-watchdog"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


# ----------------------------------------------------------------------
# Retry with exponential backoff + jitter
# ----------------------------------------------------------------------


class RetryPolicy:
    """Exponential backoff with jitter; clock-free and fully injectable.

    delay(attempt) = min(max_delay, base * 2**(attempt-1)) * (1 + U[0, jitter])

    Jitter decorrelates retries across ranks/workers hammering the same
    filesystem after a shared blip (the classic thundering-herd fix).
    """

    def __init__(
        self,
        max_retries: int = 3,
        base_delay: float = 0.5,
        max_delay: float = 8.0,
        jitter: float = 0.5,
        sleep: Callable[[float], None] = time.sleep,
        rng: Optional[random.Random] = None,
    ) -> None:
        self.max_retries = max(int(max_retries), 0)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.jitter = float(jitter)
        self.sleep = sleep
        self.rng = rng or random.Random()

    def delay(self, attempt: int) -> float:
        base = min(self.max_delay, self.base_delay * (2.0 ** max(attempt - 1, 0)))
        return base * (1.0 + self.jitter * self.rng.random())


_DEFAULT_RETRY = RetryPolicy()


def set_default_retry_policy(policy: RetryPolicy) -> RetryPolicy:
    """Install the process-wide default policy (the training loop sets it
    from ``[training] io_retries`` / ``io_retry_base_s``). Returns the
    previous policy so callers can restore it."""
    global _DEFAULT_RETRY
    prev = _DEFAULT_RETRY
    _DEFAULT_RETRY = policy
    return prev


def retry_io(
    site: str,
    fn: Callable[[], Any],
    policy: Optional[RetryPolicy] = None,
    retry_on: Tuple[type, ...] = (OSError,),
) -> Any:
    """Run ``fn`` retrying transient errors with backoff + jitter.

    OSError covers the transient family that matters on fleet storage
    (NFS/GCS-FUSE flakes, EIO, stale handles); everything else — corrupt
    data, logic errors — must NOT be retried into an infinite loop and
    propagates immediately. Deterministic config errors that merely WEAR
    an OSError (missing path, permissions) are exempted too: retrying a
    typo'd [paths] entry only delays the real message by the full backoff.
    """
    pol = policy or _DEFAULT_RETRY
    attempt = 0
    while True:
        try:
            return fn()
        except retry_on as e:
            if isinstance(
                e,
                (FileNotFoundError, NotADirectoryError, IsADirectoryError,
                 PermissionError),
            ):
                raise
            attempt += 1
            if attempt > pol.max_retries:
                raise
            d = pol.delay(attempt)
            log_event(
                "io-retry",
                f"{site}: {type(e).__name__}: {e} — retry "
                f"{attempt}/{pol.max_retries} in {d:.2f}s",
                site=site,
                attempt=attempt,
            )
            pol.sleep(d)


# ----------------------------------------------------------------------
# Fault-injection harness
# ----------------------------------------------------------------------

FAULT_SITES = (
    "corpus-read", "collate", "checkpoint-write", "step", "grad-push",
    "param-pull", "checkpoint-wire",
)
FAULT_PLAN_ENV = "SPACY_RAY_TPU_FAULT_PLAN"

_FAULT_KINDS = ("oserror", "runtime", "sigterm", "nan")

#: wire-chaos kinds (the PR 17 harness): they never raise — they queue
#: an ACTION the fleet's wire call sites consume via
#: :func:`consume_wire_fault`, or (partition/heal) flip a peer's
#: membership in the partitioned set read by :func:`partitioned`.
_WIRE_FAULT_KINDS = ("corrupt", "delay", "dup", "partition", "heal")

#: sites whose calls move bytes between fleet peers — the only sites a
#: wire-chaos kind may target (elsewhere it would be a silent no-op).
_WIRE_FAULT_SITES = ("grad-push", "param-pull", "checkpoint-wire")


class FaultInjected(RuntimeError):
    """Base marker for injected RuntimeErrors (so tests can catch exactly
    the injected failure and nothing else)."""


class FaultPlan:
    """Deterministic "fail site X on call N with error E" schedule.

    Spec grammar (env var :data:`FAULT_PLAN_ENV` or programmatic):

        spec     := rule ("," rule)*
        rule     := site ":" call ":" kind [":" arg]
        site     := one of FAULT_SITES
        call     := 1-based call number at that site
        kind     := "oserror" | "runtime" | "sigterm" | "nan"
                  | "corrupt" | "delay" | "dup" | "partition" | "heal"

    ``oserror`` raises OSError (the retryable family — exercises backoff),
    ``runtime`` raises :class:`FaultInjected` (non-retryable — exercises
    crash/restart), ``sigterm`` sends SIGTERM to this process (exercises
    the preemption path at an exact step), ``nan`` raises nothing but
    marks the site POISONED — the training loop polls
    :func:`consume_poison` after ``maybe_fail("step")`` and turns that
    step's reported loss into NaN, driving the telemetry NaN-loss
    anomaly detector end-to-end without corrupting real training math.

    The WIRE-CHAOS kinds (PR 17 harness; fleet wire sites only —
    ``grad-push``, ``param-pull``, ``checkpoint-wire``) never raise.
    They queue an action the wire call site consumes via
    :func:`consume_wire_fault` right where the bytes move:

    * ``corrupt`` — the next frame at the site has a byte flipped
      (:func:`corrupt_bytes`) → the receiver's :class:`WireError` path;
    * ``delay[:seconds]`` — the next call sleeps ``seconds`` (default
      1.0) first — injected latency past a step deadline;
    * ``dup`` — the next frame is delivered twice (exercises the
      buffer-overwrite / idempotent-pull semantics);
    * ``partition[:peer]`` — ALL traffic to/from ``peer`` (every peer
      when omitted) fails with OSError until a ``heal`` rule fires —
      call sites poll :func:`partitioned`;
    * ``heal[:peer]`` — lift a partition (all partitions when omitted).

    Counters are per-site and per-plan; activating a plan resets them.
    """

    def __init__(
        self, rules: Sequence[Tuple[str, int, str, Optional[str]]]
    ) -> None:
        normalized: List[Tuple[str, int, str, Optional[str]]] = []
        for rule in rules:
            site, call, kind = rule[0], rule[1], rule[2]
            arg = rule[3] if len(rule) > 3 else None
            if site not in FAULT_SITES:
                raise ValueError(
                    f"unknown fault site {site!r} (known: {', '.join(FAULT_SITES)})"
                )
            if kind not in _FAULT_KINDS and kind not in _WIRE_FAULT_KINDS:
                known = ", ".join(_FAULT_KINDS + _WIRE_FAULT_KINDS)
                raise ValueError(
                    f"unknown fault kind {kind!r} (known: {known})"
                )
            if call < 1:
                raise ValueError(f"fault call number must be >= 1, got {call}")
            if kind == "nan" and site != "step":
                # only the training loop's step site polls consume_poison;
                # a nan rule anywhere else would be a silent no-op — the
                # operator would conclude the NaN detector works (or is
                # broken) from a drill that never ran
                raise ValueError(
                    f"fault kind 'nan' is only wired at the 'step' site "
                    f"(got {site!r}): the loop polls consume_poison there"
                )
            if kind in _WIRE_FAULT_KINDS and site not in _WIRE_FAULT_SITES:
                # same silent-no-op discipline for the chaos kinds
                raise ValueError(
                    f"fault kind {kind!r} is only wired at the fleet wire "
                    f"sites {', '.join(_WIRE_FAULT_SITES)} (got {site!r})"
                )
            if arg is not None:
                if kind == "delay":
                    try:
                        float(arg)
                    except ValueError:
                        raise ValueError(
                            f"delay arg {arg!r} is not a number of seconds"
                        )
                elif kind in ("partition", "heal"):
                    try:
                        int(arg)
                    except ValueError:
                        raise ValueError(
                            f"{kind} arg {arg!r} is not a peer id"
                        )
                else:
                    raise ValueError(
                        f"fault kind {kind!r} takes no arg (got {arg!r})"
                    )
            normalized.append((site, call, kind, arg))
        self.rules = normalized
        self._counts: Dict[str, int] = {}
        self._poisoned: set = set()
        # site -> queued (kind, arg) wire actions, consumed FIFO by the
        # wire call sites; partitions live in a separate peer set
        self._wire_actions: Dict[str, List[Tuple[str, Optional[str]]]] = {}
        self._partitioned: set = set()
        self._partition_all = False
        self._lock = threading.Lock()

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        rules: List[Tuple[str, int, str, Optional[str]]] = []
        for chunk in spec.split(","):
            chunk = chunk.strip()
            if not chunk:
                continue
            parts = chunk.split(":")
            if len(parts) not in (3, 4):
                raise ValueError(
                    f"bad fault rule {chunk!r} (want site:call:kind[:arg])"
                )
            site, call_s, kind = parts[0], parts[1], parts[2]
            arg = parts[3].strip() if len(parts) == 4 else None
            try:
                call = int(call_s)
            except ValueError:
                raise ValueError(
                    f"bad fault rule {chunk!r}: call {call_s!r} is not an int"
                )
            rules.append((site.strip(), call, kind.strip().lower(), arg))
        return cls(rules)

    def check(self, site: str) -> None:
        """Count one call at ``site``; trigger any rule scheduled for it."""
        with self._lock:
            n = self._counts.get(site, 0) + 1
            self._counts[site] = n
        for rule in self.rules:
            r_site, r_call, r_kind, r_arg = rule
            if r_site == site and r_call == n:
                self._trigger(site, n, r_kind, r_arg)

    def _trigger(
        self, site: str, call: int, kind: str, arg: Optional[str] = None
    ) -> None:
        log_event(
            "fault-injected", f"{site} call {call}: {kind}",
            site=site, call=call, kind=kind,
            **({"arg": arg} if arg is not None else {}),
        )
        if kind == "oserror":
            raise OSError(f"injected fault: {site} call {call}")
        if kind == "runtime":
            raise FaultInjected(f"injected fault: {site} call {call}")
        if kind == "sigterm":
            os.kill(os.getpid(), signal.SIGTERM)
        if kind == "nan":
            with self._lock:
                self._poisoned.add(site)
        if kind in ("corrupt", "delay", "dup"):
            with self._lock:
                self._wire_actions.setdefault(site, []).append((kind, arg))
        if kind == "partition":
            with self._lock:
                if arg is None:
                    self._partition_all = True
                else:
                    self._partitioned.add(int(arg))
        if kind == "heal":
            with self._lock:
                if arg is None:
                    self._partition_all = False
                    self._partitioned.clear()
                else:
                    self._partitioned.discard(int(arg))

    def consume_poison(self, site: str) -> bool:
        """True exactly once per triggered ``nan`` rule at ``site``."""
        with self._lock:
            if site in self._poisoned:
                self._poisoned.discard(site)
                return True
        return False

    def consume_wire_fault(
        self, site: str
    ) -> Optional[Tuple[str, Optional[str]]]:
        """Pop the next queued ``(kind, arg)`` wire action at ``site``
        (corrupt/delay/dup), or None. FIFO; each triggered rule is
        consumed exactly once."""
        with self._lock:
            queue = self._wire_actions.get(site)
            if queue:
                return queue.pop(0)
        return None

    def partitioned(self, peer: Any) -> bool:
        """Is traffic to/from ``peer`` currently severed?"""
        with self._lock:
            if self._partition_all:
                return True
            try:
                return int(peer) in self._partitioned
            except (TypeError, ValueError):
                return False


_ACTIVE_PLAN: Optional[FaultPlan] = None


def set_fault_plan(plan: Optional[FaultPlan]) -> Optional[FaultPlan]:
    """Install (or clear, with None) the active plan. Returns the previous
    one so tests can restore it."""
    global _ACTIVE_PLAN
    prev = _ACTIVE_PLAN
    _ACTIVE_PLAN = plan
    return prev


def get_fault_plan() -> Optional[FaultPlan]:
    return _ACTIVE_PLAN


def activate_env_fault_plan() -> Optional[FaultPlan]:
    """(Re-)read :data:`FAULT_PLAN_ENV` and install the parsed plan with
    fresh counters — called at train() start so a supervisor-relaunched
    child picks the plan up from its environment."""
    spec = os.environ.get(FAULT_PLAN_ENV, "").strip()
    if not spec:
        return _ACTIVE_PLAN
    set_fault_plan(FaultPlan.parse(spec))
    return _ACTIVE_PLAN


def maybe_fail(site: str) -> None:
    """Fault hook compiled into the named sites; free when no plan is
    active (one global read)."""
    plan = _ACTIVE_PLAN
    if plan is not None:
        plan.check(site)


def consume_poison(site: str) -> bool:
    """Did a ``nan`` rule trigger at ``site`` since the last poll? Free
    when no plan is active (one global read) — the training loop polls
    this every step right after ``maybe_fail("step")``."""
    plan = _ACTIVE_PLAN
    if plan is not None:
        return plan.consume_poison(site)
    return False


def consume_wire_fault(site: str) -> Optional[Tuple[str, Optional[str]]]:
    """Next queued wire-chaos action (corrupt/delay/dup) at ``site``, or
    None. Free when no plan is active (one global read) — the fleet's
    wire call sites poll this right after ``maybe_fail(site)``."""
    plan = _ACTIVE_PLAN
    if plan is not None:
        return plan.consume_wire_fault(site)
    return None


def partitioned(peer: Any) -> bool:
    """Is ``peer`` behind an injected partition? Free when no plan is
    active — the fleet's wire call sites check this before every
    exchange and surface True as the same OSError a real severed link
    produces."""
    plan = _ACTIVE_PLAN
    if plan is not None:
        return plan.partitioned(peer)
    return False


def corrupt_bytes(body: bytes) -> bytes:
    """Deterministically flip one byte in the middle of a frame — the
    ``corrupt`` chaos kind's payload mutation. Applied to an SRTF1 frame
    it lands inside the header/data region (past the magic), so the
    receiver sees a :class:`~.fleet.wire.WireError`-shaped failure, not
    an unrecognized protocol."""
    if not body:
        return body
    b = bytearray(body)
    i = len(b) // 2
    b[i] ^= 0xFF
    return bytes(b)


# ----------------------------------------------------------------------
# Graceful termination + supervisor
# ----------------------------------------------------------------------


def terminate_with_grace(
    proc: "subprocess.Popen",
    grace_s: float = 10.0,
    kill_grace_s: float = 5.0,
) -> Optional[int]:
    """SIGTERM, wait ``grace_s``, then escalate to SIGKILL.

    SIGTERM-only shutdown hangs forever on a child that ignores or can't
    service the signal (wedged in a collective, masked handlers); a bare
    SIGKILL gives a healthy child no chance to finish its checkpoint. This
    is the one escalation sequence the relay probe and the supervisor
    share. Returns the child's returncode (None if it survived even
    SIGKILL, which means an unkillable D-state process).
    """
    if proc.poll() is not None:
        return proc.returncode
    try:
        proc.terminate()
    except OSError:  # already gone
        return proc.poll()
    try:
        return proc.wait(timeout=grace_s)
    except subprocess.TimeoutExpired:
        pass
    log_event(
        "shutdown-escalated",
        f"child pid {proc.pid} ignored SIGTERM for {grace_s:.1f}s — SIGKILL",
        pid=proc.pid,
    )
    try:
        proc.kill()
    except OSError:
        return proc.poll()
    try:
        return proc.wait(timeout=kill_grace_s)
    except subprocess.TimeoutExpired:  # pragma: no cover — D-state zombie
        return None


class Supervisor:
    """``--max-restarts N``: relaunch the training child on nonzero exit.

    ``build_cmd(attempt)`` returns the child argv for launch ``attempt``
    (0 = first); the CLI appends ``--resume`` for every relaunch so the
    child recovers from the last intact checkpoint generation. Signals
    received by the supervisor are relayed to the child with the
    SIGTERM → SIGKILL escalation, and a relayed shutdown is NOT restarted
    — the operator (or the scheduler) asked the whole tree to stop.

    A child that exits 0 ends supervision. A child that keeps dying past
    ``max_restarts`` propagates its final returncode.
    """

    def __init__(
        self,
        build_cmd: Callable[[int], List[str]],
        max_restarts: int,
        *,
        grace_s: float = 10.0,
        popen: Callable[..., "subprocess.Popen"] = subprocess.Popen,
        restart_delay_s: float = 1.0,
        sleep: Callable[[float], None] = time.sleep,
    ) -> None:
        self.build_cmd = build_cmd
        self.max_restarts = max(int(max_restarts), 0)
        self.grace_s = float(grace_s)
        self.popen = popen
        self.restart_delay_s = float(restart_delay_s)
        self.sleep = sleep
        self.restarts_used = 0
        self._shutdown = threading.Event()
        self._child: Optional["subprocess.Popen"] = None

    def _relay(self, signum: int, frame: Any) -> None:
        self._shutdown.set()
        child = self._child
        if child is not None and child.poll() is None:
            # escalate on a helper thread: a signal handler must not block
            # for the whole grace period
            threading.Thread(
                target=terminate_with_grace,
                args=(child, self.grace_s),
                daemon=True,
                name="supervisor-escalate",
            ).start()

    def request_shutdown(self) -> None:
        """Programmatic equivalent of a relayed signal, for a parent that
        multiplexes several supervisors on worker threads (the trainer-
        fleet coordinator): only the parent's MAIN thread can own signal
        handlers, so it fans the one OS signal out to each supervisor
        through this."""
        self._relay(signal.SIGTERM, None)

    def run(self) -> int:
        prev_handlers: Dict[int, Any] = {}
        in_main = threading.current_thread() is threading.main_thread()
        if in_main:
            for signum in (signal.SIGTERM, signal.SIGINT):
                try:
                    prev_handlers[signum] = signal.signal(signum, self._relay)
                except (ValueError, OSError):  # pragma: no cover
                    pass
        try:
            attempt = 0
            while True:
                if self._shutdown.is_set():
                    # a signal that arrived between children (e.g. during
                    # the restart delay) must not launch a fresh child
                    return RC_PREEMPTED
                cmd = self.build_cmd(attempt)
                self._child = self.popen(cmd)
                if self._shutdown.is_set():
                    # signal landed while popen was in flight: _relay saw
                    # only the previous (dead) child — escalate this one
                    # ourselves or wait() blocks for the child's whole run
                    threading.Thread(
                        target=terminate_with_grace,
                        args=(self._child, self.grace_s),
                        daemon=True,
                        name="supervisor-escalate",
                    ).start()
                rc = self._child.wait()
                if rc == 0:
                    return 0
                if self._shutdown.is_set():
                    # relayed shutdown: the child may have died on the
                    # escalated SIGKILL (negative waitpid code, which the
                    # shell would render as a meaningless 128+N) — report
                    # the tree's outcome, a clean preemption
                    return RC_PREEMPTED
                if self.restarts_used >= self.max_restarts:
                    log_event(
                        "supervisor-giving-up",
                        f"child exited rc={rc}; {self.restarts_used} restart(s) "
                        "used — giving up",
                        rc=rc,
                    )
                    return rc
                self.restarts_used += 1
                attempt += 1
                log_event(
                    "supervisor-restart",
                    f"child exited rc={rc} — restart "
                    f"{self.restarts_used}/{self.max_restarts} (resuming from "
                    "the last intact checkpoint)",
                    rc=rc,
                    restart=self.restarts_used,
                )
                if self.restart_delay_s > 0:
                    self.sleep(self.restart_delay_s)
        finally:
            self._child = None
            if in_main:
                for signum, prev in prev_handlers.items():
                    try:
                        signal.signal(signum, prev)
                    except (ValueError, OSError):  # pragma: no cover
                        pass
