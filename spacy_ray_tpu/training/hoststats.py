"""Host-resource truth: ``/proc``-based process sampling plus
cgroup-aware core accounting — the live resource signals every role in
the system exports and the bench's machine-derived contention stamp.

Two consumers, one module:

* **Live surfaces.** A :class:`ProcessSampler` owned by each role's
  telemetry facade (trainer ``Telemetry``, fleet peer, serving
  ``ServingTelemetry``, ``RouterTelemetry``) and ticked by the role's
  EXISTING observer/alert thread — no new thread anywhere. Its sample
  dict rides the JSON ``/metrics`` payload under a top-level
  ``"process"`` key, renders as the ``srt_process_*`` gauge family in
  the Prometheus exposition (one family name across all four surfaces,
  deliberately OUTSIDE the per-role ``srt_training``/``srt_serving``/
  ``srt_router`` prefixes), and is injected into alert-engine snapshots
  so the leak rules read ``process.rss_bytes`` / ``process.open_fds``
  with the same dotted-path grammar as every other rule.

* **The bench stamp.** ``bench.py`` used to hand-maintain
  ``cores_available`` / ``contended`` constants; :func:`effective_cores`
  (min of cpu_count, sched affinity, and the cgroup cpu quota — v2
  ``cpu.max`` or v1 ``cfs_quota_us``/``cfs_period_us``) and
  :func:`contention_probe` (core arithmetic + a short busy-spin
  efficiency check) mechanize them, and :func:`host_block` is the
  ``host`` dict every bench record now carries for the run ledger
  (``runledger.py``) to ingest.

Honesty rules, same as the exposition layer: a field whose ``/proc``
file is missing or unparsable is ``None`` (no-signal), never a fake 0 —
the Prometheus renderer already omits ``None`` gauges, and the alert
engine already treats a missing path as no-signal. ``cpu_percent`` is a
delta over the previous reading; the baseline is primed at
construction, so the first sample reports utilization since the facade
came up (never a meaningless since-boot average), and stays ``None``
only when no wall time has passed or ``stat`` is unreadable.

Stdlib-only and jax-free: importable by the router, ``telemetry top``,
and the ledger CLI without dragging in an accelerator runtime.
"""

from __future__ import annotations

import math
import os
import time
from typing import Any, Callable, Dict, Optional, Tuple

__all__ = [
    "ProcessSampler",
    "PROCESS_GAUGE_FIELDS",
    "add_process_family",
    "effective_cores",
    "contention_probe",
    "host_block",
]


# Sample-dict keys exported as ``srt_process_<key>`` gauges, with the
# unit discipline of the rest of the plane (bytes are bytes, percents
# are 0-100, totals are since-process-start). Order is exposition order.
PROCESS_GAUGE_FIELDS: Tuple[str, ...] = (
    "cpu_percent",
    "cpu_seconds_total",
    "rss_bytes",
    "rss_peak_bytes",
    "threads",
    "open_fds",
    "ctx_switches_voluntary",
    "ctx_switches_involuntary",
    "io_read_bytes",
    "io_write_bytes",
)


def _read_text(path: str) -> Optional[str]:
    try:
        with open(path, "r", encoding="ascii", errors="replace") as f:
            return f.read()
    except OSError:
        return None


class ProcessSampler:
    """Reads ``/proc/self/{stat,status,io}`` + the fd table into one
    flat dict of numbers.

    Internally rate-limited: callers cheaper than ``min_interval_s``
    apart get the cached sample, so both the /metrics handler threads
    and the observer tickers may call :meth:`sample` freely without
    multiplying ``/proc`` reads (and without a dedicated sampler
    thread). The clock is injected for the same reason the alert
    engine's is — deterministic tests.

    ``proc_root`` points at a fake ``/proc/self`` directory in tests;
    every field degrades independently to ``None`` when its file is
    absent there (or on a hostile real ``/proc``).
    """

    def __init__(
        self,
        *,
        proc_root: str = "/proc/self",
        clock: Callable[[], float] = time.monotonic,
        clk_tck: Optional[float] = None,
        min_interval_s: float = 1.0,
    ) -> None:
        self.proc_root = str(proc_root)
        self.clock = clock
        if clk_tck is None:
            try:
                clk_tck = float(os.sysconf("SC_CLK_TCK"))
            except (ValueError, OSError, AttributeError):
                clk_tck = 100.0
        self.clk_tck = float(clk_tck) or 100.0
        self.min_interval_s = float(min_interval_s)
        self._last_t: Optional[float] = None
        self._last_cpu_s: Optional[float] = None
        self._cached: Optional[Dict[str, Any]] = None
        # prime the cpu baseline: the first real sample then reports
        # utilization since construction instead of an honest-but-empty
        # None (scrape-once consumers never see the gauge otherwise)
        primed = self._read_stat().get("cpu_seconds_total")
        if primed is not None:
            self._last_cpu_s = primed
            self._last_t = self.clock()

    # -- field readers -------------------------------------------------
    def _read_stat(self) -> Dict[str, Any]:
        """utime/stime (ticks -> seconds) + thread count from
        ``stat``'s fixed-position fields; the comm field may contain
        spaces/parens, so split AFTER the last ``)``."""
        raw = _read_text(os.path.join(self.proc_root, "stat"))
        out: Dict[str, Any] = {
            "cpu_seconds_total": None,
            "threads": None,
        }
        if raw is None:
            return out
        rest = raw.rpartition(")")[2].split()
        # rest[0] is field 3 (state); utime/stime are fields 14/15,
        # num_threads field 20 (man proc(5), 1-based)
        try:
            utime = float(rest[11])
            stime = float(rest[12])
            out["cpu_seconds_total"] = (utime + stime) / self.clk_tck
        except (IndexError, ValueError):
            pass
        try:
            out["threads"] = int(rest[17])
        except (IndexError, ValueError):
            pass
        return out

    def _read_status(self) -> Dict[str, Any]:
        raw = _read_text(os.path.join(self.proc_root, "status"))
        out: Dict[str, Any] = {
            "rss_bytes": None,
            "rss_peak_bytes": None,
            "ctx_switches_voluntary": None,
            "ctx_switches_involuntary": None,
        }
        if raw is None:
            return out
        keymap = {
            "VmRSS": ("rss_bytes", 1024),
            "VmHWM": ("rss_peak_bytes", 1024),
            "voluntary_ctxt_switches": ("ctx_switches_voluntary", 1),
            "nonvoluntary_ctxt_switches": ("ctx_switches_involuntary", 1),
        }
        for line in raw.splitlines():
            name, sep, value = line.partition(":")
            if not sep or name not in keymap:
                continue
            field, scale = keymap[name]
            try:
                out[field] = int(value.split()[0]) * scale
            except (IndexError, ValueError):
                pass
        return out

    def _read_io(self) -> Dict[str, Any]:
        raw = _read_text(os.path.join(self.proc_root, "io"))
        out: Dict[str, Any] = {
            "io_read_bytes": None,
            "io_write_bytes": None,
        }
        if raw is None:
            return out
        for line in raw.splitlines():
            name, sep, value = line.partition(":")
            if not sep:
                continue
            key = {
                "read_bytes": "io_read_bytes",
                "write_bytes": "io_write_bytes",
            }.get(name.strip())
            if key is None:
                continue
            try:
                out[key] = int(value.strip())
            except ValueError:
                pass
        return out

    def _count_fds(self) -> Optional[int]:
        try:
            return len(os.listdir(os.path.join(self.proc_root, "fd")))
        except OSError:
            return None

    # -- the sample ----------------------------------------------------
    def sample(self, *, force: bool = False) -> Dict[str, Any]:
        """One flat dict of the :data:`PROCESS_GAUGE_FIELDS` numbers
        (cached inside ``min_interval_s`` unless ``force``)."""
        now = self.clock()
        if (
            not force
            and self._cached is not None
            and self._last_t is not None
            and now - self._last_t < self.min_interval_s
        ):
            return self._cached
        out: Dict[str, Any] = {}
        out.update(self._read_stat())
        out.update(self._read_status())
        out.update(self._read_io())
        out["open_fds"] = self._count_fds()
        cpu_s = out.get("cpu_seconds_total")
        cpu_pct: Optional[float] = None
        if (
            cpu_s is not None
            and self._last_cpu_s is not None
            and self._last_t is not None
        ):
            wall = now - self._last_t
            if wall > 0:
                cpu_pct = max(cpu_s - self._last_cpu_s, 0.0) / wall * 100.0
        out["cpu_percent"] = cpu_pct
        self._last_t = now
        if cpu_s is not None:
            self._last_cpu_s = cpu_s
        self._cached = out
        return out


def add_process_family(
    fam: Any,
    sample: Optional[Dict[str, Any]],
    labels: Optional[Dict[str, Any]] = None,
) -> None:
    """Render one sample as the ``srt_process_*`` gauge family onto a
    ``PromFamilies`` — the ONE exposition spelling all four surfaces
    share (the per-role snapshot prefixes would otherwise fragment the
    family into ``srt_serving_process_rss_bytes`` etc., and a fleet
    dashboard's leak panel would need a query per role)."""
    if not sample:
        return
    for key in PROCESS_GAUGE_FIELDS:
        fam.add(f"srt_process_{key}", "gauge", sample.get(key), labels)


# -- core accounting ---------------------------------------------------
def _cgroup_quota_cores(cgroup_root: str) -> Tuple[Optional[float], Optional[str]]:
    """(quota in cores, "v2"|"v1") — None where unlimited or unreadable."""
    raw = _read_text(os.path.join(cgroup_root, "cpu.max"))
    if raw is not None:
        parts = raw.split()
        if parts and parts[0] != "max":
            try:
                period = float(parts[1]) if len(parts) > 1 else 100000.0
                if period > 0:
                    return float(parts[0]) / period, "v2"
            except ValueError:
                pass
        if parts:
            return None, "v2"
    quota_raw = _read_text(os.path.join(cgroup_root, "cpu.cfs_quota_us"))
    period_raw = _read_text(os.path.join(cgroup_root, "cpu.cfs_period_us"))
    if quota_raw is not None and period_raw is not None:
        try:
            quota = float(quota_raw.split()[0])
            period = float(period_raw.split()[0])
        except (IndexError, ValueError):
            return None, "v1"
        if quota > 0 and period > 0:
            return quota / period, "v1"
        return None, "v1"
    return None, None


def effective_cores(
    *,
    cgroup_root: str = "/sys/fs/cgroup",
    affinity: Optional[int] = None,
    cpu_count: Optional[int] = None,
) -> Dict[str, Any]:
    """The cores this process can ACTUALLY burn: min of the visible CPU
    count, the sched affinity mask, and the cgroup cpu quota — with
    provenance, because the bench's ``host`` block records not just the
    number but why (a ``cores: 1`` from a cgroup quota on a 64-core box
    is a very different run from a real single-core host)."""
    if cpu_count is None:
        cpu_count = os.cpu_count()
    if affinity is None:
        try:
            affinity = len(os.sched_getaffinity(0))
        except (AttributeError, OSError):
            affinity = None
    quota, cg_version = _cgroup_quota_cores(cgroup_root)
    candidates = []
    if cpu_count:
        candidates.append((float(cpu_count), "cpu_count"))
    if affinity:
        candidates.append((float(affinity), "affinity"))
    if quota is not None:
        candidates.append((quota, "cgroup_quota"))
    if candidates:
        value, source = min(candidates, key=lambda c: c[0])
        cores = max(1, int(math.floor(value + 1e-9)))
    else:
        cores, source = 1, "unknown"
    return {
        "cores": cores,
        "source": source,
        "cpu_count": cpu_count,
        "affinity": affinity,
        "cgroup_quota": quota,
        "cgroup_version": cg_version,
    }


def contention_probe(
    cores_needed: int,
    *,
    cores: Optional[Dict[str, Any]] = None,
    cgroup_root: str = "/sys/fs/cgroup",
    spin_s: float = 0.05,
    efficiency_floor: float = 0.80,
    clock: Callable[[], float] = time.perf_counter,
    cpu_time: Callable[[], float] = time.process_time,
) -> Dict[str, Any]:
    """The machine-derived ``contended`` verdict: a run wanting
    ``cores_needed`` cores is contended when the host cannot grant them
    (core arithmetic) OR when a short single-thread busy-spin gets
    materially less cpu-time than wall-time (neighbors on the same
    core — the signal core counts can't see). Both clocks are injected
    so tests script the spin deterministically."""
    if cores is None:
        cores = effective_cores(cgroup_root=cgroup_root)
    n = int(cores.get("cores") or 1)
    out: Dict[str, Any] = {
        "contended": False,
        "reason": None,
        "cores": n,
        "cores_needed": int(cores_needed),
        "spin_efficiency": None,
    }
    if n < int(cores_needed):
        out["contended"] = True
        out["reason"] = (
            f"cores {n} < needed {int(cores_needed)} ({cores.get('source')})"
        )
        return out
    eff = _spin_efficiency(spin_s, clock, cpu_time)
    out["spin_efficiency"] = eff
    if eff is not None and eff < float(efficiency_floor):
        out["contended"] = True
        out["reason"] = (
            f"spin efficiency {eff:.2f} < {float(efficiency_floor):.2f}"
        )
    return out


def _spin_efficiency(
    spin_s: float,
    clock: Callable[[], float],
    cpu_time: Callable[[], float],
) -> Optional[float]:
    """cpu-time / wall-time of a short busy loop, clamped to [0, 1]."""
    try:
        t0 = clock()
        c0 = cpu_time()
        x = 0
        while clock() - t0 < spin_s:
            x += 1  # pure-python busy work; the GIL is held throughout
        wall = clock() - t0
        cpu = cpu_time() - c0
    except Exception:
        return None
    if wall <= 0:
        return None
    return max(0.0, min(cpu / wall, 1.0))


def host_block(
    *,
    cores_needed: Optional[int] = None,
    sampler: Optional[ProcessSampler] = None,
    cgroup_root: str = "/sys/fs/cgroup",
) -> Dict[str, Any]:
    """The ``host`` dict a bench record carries: machine-derived core
    accounting (+ the contention verdict when the caller says how many
    cores the arm wants) and the process RSS peak — everything the run
    ledger needs to decide whether a record is baseline-worthy."""
    cores = effective_cores(cgroup_root=cgroup_root)
    out: Dict[str, Any] = dict(cores)
    if cores_needed is not None:
        probe = contention_probe(
            int(cores_needed), cores=cores, cgroup_root=cgroup_root
        )
        out["contended"] = probe["contended"]
        out["contention_reason"] = probe["reason"]
        out["spin_efficiency"] = probe["spin_efficiency"]
    if sampler is None:
        sampler = ProcessSampler()
    s = sampler.sample(force=True)
    out["rss_peak_bytes"] = s.get("rss_peak_bytes")
    out["rss_bytes"] = s.get("rss_bytes")
    return out
