"""SLO-driven autoscaling: grow/shrink the replica count from the
telemetry the engines already emit.

The policy consumes one :class:`FleetObservation` per tick — worst
replica p99 over its rolling latency window, total queued docs, mean
batch occupancy, ready count — and answers "what replica count do we
want?". Two design rules keep it boring (boring is what you want in a
control loop):

* **Hysteresis, not thresholds.** A decision needs ``up_consecutive``
  (resp. ``down_consecutive``) CONSECUTIVE breaching observations; a
  single recovered tick resets the streak. An oscillating metric that
  crosses the threshold every other tick therefore never scales — the
  classic flapping failure of naive threshold scaling.
* **Cooldown after every action.** Scaling takes effect slowly (a new
  replica must boot + warm before it absorbs load; a drained one hands
  its load back); deciding again before the last decision has landed
  would double-count the same pressure. ``cooldown_s`` on an injected
  clock gates re-decisions; tests drive it deterministically.

Scale-up triggers on SLO pressure (p99 above target) OR queue pressure
(queued docs per ready replica above ``queue_high``); scale-down needs
BOTH a comfortable p99 (under ``down_frac`` × target) AND an idle-ish
fleet (occupancy under ``occupancy_low`` and near-empty queues) — the
asymmetry is deliberate: adding capacity cheaply fixes a wrong guess up,
while removing it wrongly burns the SLO.

Every decision is a structured ``log_event`` row (machine-readable — the
jsonl logger drains it) and, when fleet telemetry is attached, a trace
instant + counter; the disabled-telemetry path makes zero telemetry
calls, the repo-wide contract.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from ...training.resilience import log_event

__all__ = ["FleetObservation", "AutoscalerPolicy", "observation_from_snapshots"]


@dataclass
class FleetObservation:
    """One tick's worth of fleet SLO signal (already aggregated)."""

    ready: int                       # replicas currently taking traffic
    p99_s: Optional[float] = None    # worst replica request-latency p99
    queue_depth: float = 0.0         # total queued docs across replicas
    occupancy: Optional[float] = None  # mean batch occupancy


def observation_from_snapshots(
    snaps: List[Dict[str, Any]], ready: int
) -> FleetObservation:
    """Build an observation from scraped per-replica /metrics payloads
    (the ServingTelemetry.snapshot() schema). Missing pieces stay None —
    a replica with no traffic yet has no p99, and the policy treats
    no-signal as no-pressure.

    The p99 signal PREFERS the replica's ``slo_window`` block (latency
    percentiles over the last T seconds) over the run-lifetime-ish
    sample ring in ``slo``: a control loop must react to the load of
    the last half-minute, not a spike diluted across thousands of
    older samples (regression-tested with a fake clock in
    test_fleet.py). A window that is present but EMPTY (no requests in
    the last T seconds) is also no-signal — falling back to the stale
    ring there would re-report a long-gone spike forever."""
    p99s = []
    queue = 0.0
    occ_sum = occ_n = 0.0
    for snap in snaps:
        slo = snap.get("slo") or {}
        win = snap.get("slo_window")
        if isinstance(win, dict):
            p99 = (
                win.get("request_latency_p99")
                if int(win.get("samples") or 0) > 0 else None
            )
        else:
            p99 = slo.get("request_latency_p99")
        if isinstance(p99, (int, float)):
            p99s.append(float(p99))
        gauges = snap.get("gauges") or {}
        qd = gauges.get("queue_depth")
        if isinstance(qd, (int, float)):
            queue += float(qd)
        occ = slo.get("batch_occupancy_p50")
        if isinstance(occ, (int, float)):
            occ_sum += float(occ)
            occ_n += 1
    return FleetObservation(
        ready=int(ready),
        p99_s=max(p99s) if p99s else None,
        queue_depth=queue,
        occupancy=(occ_sum / occ_n) if occ_n else None,
    )


class AutoscalerPolicy:
    """Deterministic hysteresis policy: feed :meth:`observe` once per
    tick; it returns the desired replica count, or None for "hold".

    All timing runs on the injected ``clock`` — tests advance a fake
    clock and the policy's behaviour is exactly reproducible.
    """

    def __init__(
        self,
        *,
        min_replicas: int = 1,
        max_replicas: int = 4,
        p99_target_s: float = 0.5,
        queue_high: float = 32.0,
        down_frac: float = 0.5,
        occupancy_low: float = 2.0,
        up_consecutive: int = 3,
        down_consecutive: int = 10,
        cooldown_s: float = 30.0,
        step: int = 1,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if max_replicas < min_replicas:
            raise ValueError(
                f"max_replicas ({max_replicas}) must be >= min_replicas "
                f"({min_replicas})"
            )
        if up_consecutive < 1 or down_consecutive < 1:
            raise ValueError("hysteresis windows must be >= 1 observation")
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.p99_target_s = float(p99_target_s)
        self.queue_high = float(queue_high)
        self.down_frac = float(down_frac)
        self.occupancy_low = float(occupancy_low)
        self.up_consecutive = int(up_consecutive)
        self.down_consecutive = int(down_consecutive)
        self.cooldown_s = float(cooldown_s)
        self.step = max(int(step), 1)
        self.clock = clock
        self._up_streak = 0
        self._down_streak = 0
        self._last_action_at: Optional[float] = None
        self.decisions: List[Dict[str, Any]] = []  # bounded by caller usage

    # -- signal classification ------------------------------------------
    def _overloaded(self, obs: FleetObservation) -> bool:
        if obs.p99_s is not None and obs.p99_s > self.p99_target_s:
            return True
        per_replica_queue = obs.queue_depth / max(obs.ready, 1)
        return per_replica_queue > self.queue_high

    def _idle(self, obs: FleetObservation) -> bool:
        if obs.queue_depth > 0:
            return False
        if obs.p99_s is not None and obs.p99_s > self.down_frac * self.p99_target_s:
            return False
        if obs.occupancy is not None and obs.occupancy > self.occupancy_low:
            return False
        return True

    # -- the tick --------------------------------------------------------
    def observe(self, obs: FleetObservation) -> Optional[int]:
        """Classify the tick, advance the streaks, return the desired
        replica count when a streak completes outside the cooldown."""
        over = self._overloaded(obs)
        idle = self._idle(obs)
        now = self.clock()
        if (
            self._last_action_at is not None
            and now - self._last_action_at < self.cooldown_s
        ):
            # evidence observed during the cooldown is DISCARDED, not
            # banked: the last action has not finished landing (replica
            # still booting/draining), so these ticks measure a fleet in
            # transition — a post-cooldown decision must rebuild its
            # streak from fresh observations
            self._up_streak = self._down_streak = 0
            return None
        # streaks reset on ANY non-confirming tick — that is the whole
        # anti-flapping property
        self._up_streak = self._up_streak + 1 if over else 0
        self._down_streak = self._down_streak + 1 if idle else 0
        if over and self._up_streak >= self.up_consecutive:
            desired = min(obs.ready + self.step, self.max_replicas)
            if desired > obs.ready:
                self._record("up", obs, desired, now)
                return desired
            self._up_streak = 0  # pinned at max: don't re-fire every tick
            return None
        if idle and self._down_streak >= self.down_consecutive:
            desired = max(obs.ready - self.step, self.min_replicas)
            if desired < obs.ready:
                self._record("down", obs, desired, now)
                return desired
            self._down_streak = 0
            return None
        return None

    def _record(
        self, direction: str, obs: FleetObservation, desired: int, now: float
    ) -> None:
        self._last_action_at = now
        self._up_streak = 0
        self._down_streak = 0
        decision = {
            "direction": direction,
            "from": obs.ready,
            "to": desired,
            "p99_s": obs.p99_s,
            "p99_target_s": self.p99_target_s,
            "queue_depth": obs.queue_depth,
            "occupancy": obs.occupancy,
        }
        self.decisions.append(decision)
        log_event(
            f"autoscale-{direction}",
            f"scaling {obs.ready} -> {desired} replicas "
            f"(p99 {obs.p99_s if obs.p99_s is not None else 'n/a'} vs "
            f"target {self.p99_target_s}s, queue {obs.queue_depth:.0f}, "
            f"occupancy {obs.occupancy if obs.occupancy is not None else 'n/a'})",
            level=logging.INFO,
            **decision,
        )
