"""Multi-replica serving fleet: router, replica supervisor, SLO-driven
autoscaling.

One :class:`~.fleet.Fleet` process runs the HTTP router
(least-outstanding-requests over health-probed replicas, typed 503 when
none is ready, optional byte-capped response cache, aggregated fleet
``/metrics``), the :class:`~.replica.ReplicaSupervisor` (one ``serve``
subprocess per replica, backoff restarts on crash, drain-aware stops),
and the :class:`~.autoscaler.AutoscalerPolicy` (hysteresis scaling
between min/max replicas driven by the engines' own SLO telemetry).

Entry point: ``spacy-ray-tpu serve-fleet <model_dir>`` (cli.py);
load-tested by ``bench.py --serving --replicas N``.
"""

from .autoscaler import (
    AutoscalerPolicy,
    FleetObservation,
    observation_from_snapshots,
)
from .fleet import Fleet, FleetConfig
from .replica import ReplicaHandle, ReplicaSupervisor, build_serve_cmd
from .router import (
    GENERATION_MIXED,
    NoReplicaAvailable,
    ResponseCache,
    Router,
    RouterHTTPServer,
    RouterTelemetry,
)

__all__ = [
    "AutoscalerPolicy",
    "FleetObservation",
    "observation_from_snapshots",
    "Fleet",
    "FleetConfig",
    "ReplicaHandle",
    "ReplicaSupervisor",
    "build_serve_cmd",
    "NoReplicaAvailable",
    "ResponseCache",
    "GENERATION_MIXED",
    "Router",
    "RouterHTTPServer",
    "RouterTelemetry",
]
