"""Replica supervision for the serving fleet: spawn one ``serve``
process per replica, track its lifecycle, restart crashes with backoff.

The process-per-replica idiom is the repo's answer to Ray's actor pool
(Moritz et al., arXiv:1712.05889): each replica is a whole ``serve``
process with its own interpreter (no shared GIL), its own jit cache, and
its own device assignment — the horizontal unit the router balances
over. Supervision reuses the resilience primitives the trainer already
trusts: :class:`~...training.resilience.RetryPolicy` paces crash
restarts (exponential backoff + jitter — a crash-looping replica must
not spin the host), and :func:`~...training.resilience.terminate_with_grace`
performs the SIGTERM → SIGKILL escalation on shutdown, which on a
healthy replica triggers its own graceful drain (finish in-flight,
exit 0).

A replica's lifecycle::

    SPAWNED -- banner parsed --> ADDRESSED -- /healthz 200 --> (router: ready)
       |                             |
       +--- process exit (crash) ----+--> RESTARTING (backoff) --> SPAWNED
       |
       +--- stop()/drain --> STOPPING --> STOPPED   (never restarted)

The supervisor owns processes and restarts; READINESS is the router's
judgement (it probes ``/healthz`` — the supervisor only knows whether
the process is alive and where it listens).
"""

from __future__ import annotations

import http.client
import logging
import os
import re
import subprocess
import sys
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ...training.resilience import (
    RetryPolicy,
    log_event,
    terminate_with_grace,
)

__all__ = ["ReplicaHandle", "ReplicaSupervisor", "BANNER_RE"]

logger = logging.getLogger("spacy_ray_tpu.serving")

# the exact line server.py prints; the supervisor learns each replica's
# ephemeral port from it (one parseable contract, shared with operators)
BANNER_RE = re.compile(r"serving on http://([^:\s]+):(\d+)")


class ReplicaHandle:
    """One replica process as the fleet sees it: the subprocess, its
    parsed address, router-side accounting (outstanding requests, ready
    flag), and restart history. All mutable state is guarded by
    ``lock``; the router and the supervisor share the handle."""

    def __init__(self, replica_id: int, slot: Optional[int] = None) -> None:
        self.replica_id = int(replica_id)
        # resource slot: which device/core mask and base-port offset this
        # replica occupies. Ids grow monotonically forever (logs stay
        # unambiguous across scale cycles) but slots are RECYCLED — the
        # supervisor hands a new replica the lowest slot no live handle
        # holds, so after a scale-down/scale-up cycle two replicas can
        # never share a core/device mask while another mask sits idle.
        self.slot = self.replica_id if slot is None else int(slot)
        self.lock = threading.Lock()
        self.proc: Optional[subprocess.Popen] = None
        self.host: Optional[str] = None
        self.port: Optional[int] = None
        # router-maintained: a replica is ready only after ITS /healthz
        # answered 200 (warmup complete, not draining)
        self.ready = False
        # router-maintained from the /healthz body: which checkpoint
        # generation this replica's engine is serving (None = the model
        # as loaded from disk) and its flip count — the canary split and
        # the live fleet controller key on these
        self.generation: Optional[int] = None
        self.swap_count = 0
        # router-maintained from /healthz (multi-model serving only):
        # which models this replica currently hosts — model name →
        # {"generation", "swap_count", "warmed"} — and its configured
        # default. The router's pick() routes a named model WITHIN the
        # replicas hosting it, and the placement policy reads the same
        # facts; the probe loop keeps both fresh for free.
        self.resident_models: Dict[str, Dict[str, Any]] = {}
        self.default_model: Optional[str] = None
        # router-maintained: requests currently forwarded to this replica
        self.outstanding = 0
        self.restarts = 0
        self.stopping = False
        self.tail: "deque[str]" = deque(maxlen=40)  # crash diagnostics
        # router-maintained: the last few /healthz payloads this replica
        # answered — a crash postmortem's "what did the fleet last know"
        self.health_history: "deque[Dict[str, Any]]" = deque(maxlen=8)
        # supervisor-maintained: when the CURRENT process incarnation
        # was spawned (unix time; None for externally-managed handles)
        self.spawned_at_unix: Optional[float] = None
        # router-side pool of idle keep-alive connections to THIS replica.
        # A TCP handshake + thread spawn per forwarded request costs more
        # than small parses themselves; reuse makes the router hop cheap.
        # Guarded by its own lock: checkout happens on the hot path and
        # must not contend with the ready/outstanding bookkeeping above.
        self._pool_lock = threading.Lock()
        self._pool: List[http.client.HTTPConnection] = []
        self.pool_cap = 16
        # control-plane pool (health probes, metrics/exemplar scrapes):
        # SEPARATE from the hot-path pool because the two dial with
        # different timeouts — a probe reusing a forward's 60s-timeout
        # socket would take 60s to notice a hung replica, and a forward
        # reusing a probe's 5s socket would time out long parses. Small
        # cap: one prober + a couple of concurrent scrape passes.
        self._aux_pool: List[http.client.HTTPConnection] = []
        self.aux_pool_cap = 4

    def checkout_conn(self) -> Optional[http.client.HTTPConnection]:
        """Pop an idle keep-alive connection, or None (caller dials)."""
        with self._pool_lock:
            if self._pool:
                return self._pool.pop()
        return None

    def checkin_conn(self, conn: http.client.HTTPConnection) -> None:
        """Return a healthy connection for reuse; over-cap or stopping
        replicas just close it."""
        with self._pool_lock:
            if not self.stopping and len(self._pool) < self.pool_cap:
                self._pool.append(conn)
                return
        conn.close()

    def checkout_aux_conn(self) -> Optional[http.client.HTTPConnection]:
        """Pop an idle control-plane connection, or None (caller dials)."""
        with self._pool_lock:
            if self._aux_pool:
                return self._aux_pool.pop()
        return None

    def checkin_aux_conn(self, conn: http.client.HTTPConnection) -> None:
        with self._pool_lock:
            if not self.stopping and len(self._aux_pool) < self.aux_pool_cap:
                self._aux_pool.append(conn)
                return
        conn.close()

    def close_conns(self) -> None:
        """Drop every pooled connection — hot path and control plane
        (replica died, left rotation, or the fleet is draining — the
        replica-side handler threads see EOF instead of waiting on an
        idle socket)."""
        with self._pool_lock:
            pool, self._pool = self._pool, []
            aux, self._aux_pool = self._aux_pool, []
        for conn in pool + aux:
            try:
                conn.close()
            except OSError:
                pass

    @property
    def address(self) -> Optional[Tuple[str, int]]:
        with self.lock:
            if self.host is None or self.port is None:
                return None
            return self.host, self.port

    def set_address(self, host: str, port: int) -> None:
        with self.lock:
            self.host, self.port = host, int(port)

    def clear_address(self) -> None:
        with self.lock:
            self.host = self.port = None
            self.ready = False
            # a restarted replica boots from the on-disk model again —
            # its generation identity is re-learned from /healthz
            self.generation = None
            self.swap_count = 0
            # residency is re-learned too: the restarted process hosts
            # only its pinned default until traffic/placement reloads
            self.resident_models = {}
            self.default_model = None
        self.close_conns()

    @property
    def alive(self) -> bool:
        p = self.proc
        if p is None:
            # externally-managed handle (static replica sets in tests,
            # pre-registered remote endpoints): liveness is whatever the
            # health probe says, so "alive" just means "addressed"
            return self.host is not None
        return p.poll() is None

    def describe(self) -> Dict[str, Any]:
        proc = self.proc
        with self.lock:
            return {
                "id": self.replica_id,
                "slot": self.slot,
                "alive": self.alive,
                "ready": self.ready,
                "host": self.host,
                "port": self.port,
                "pid": proc.pid if proc is not None else None,
                "outstanding": self.outstanding,
                "restarts": self.restarts,
                "generation": self.generation,
                "swap_count": self.swap_count,
                "resident_models": sorted(self.resident_models),
                "default_model": self.default_model,
            }


class ReplicaSupervisor:
    """Spawn/monitor/restart/scale the replica processes.

    ``build_cmd(slot)`` returns the argv for one replica (the fleet
    config builds a ``python -m spacy_ray_tpu serve`` line; tests inject
    tiny stub scripts). ``build_env(slot)`` lets the config pin a
    device per replica (e.g. round-robin visible-device masks) without
    the supervisor knowing platform details. Both receive the replica's
    resource SLOT, not its id: slots are recycled across scale cycles
    (see :class:`ReplicaHandle`), so masks and base-port offsets stay
    within the configured layout no matter how many replicas have ever
    existed.

    Crash policy: an exit while not ``stopping`` is a crash. Restarts are
    paced by ``restart_policy`` (RetryPolicy backoff keyed on the
    replica's own restart count) and capped by ``max_restarts_per_replica``
    — a replica that keeps dying is removed from the active set (logged
    loudly) rather than crash-looping the host: the router stops routing
    to it, its slot frees up, and a later scale-up (autoscaler or
    operator) spawns a FRESH replica with its own restart budget instead
    of silently no-op'ing against a zombie handle.
    """

    def __init__(
        self,
        build_cmd: Callable[[int], List[str]],
        *,
        build_env: Optional[Callable[[int], Dict[str, str]]] = None,
        max_restarts_per_replica: int = 3,
        restart_policy: Optional[RetryPolicy] = None,
        grace_s: float = 30.0,
        popen: Callable[..., "subprocess.Popen"] = subprocess.Popen,
        clock: Callable[[], float] = time.monotonic,
        monitor_poll_s: float = 0.2,
        on_crash: Optional[Callable[[ReplicaHandle, int], None]] = None,
    ) -> None:
        self.build_cmd = build_cmd
        self.build_env = build_env
        self.max_restarts_per_replica = int(max_restarts_per_replica)
        self.restart_policy = restart_policy or RetryPolicy(
            max_retries=max_restarts_per_replica, base_delay=0.5, max_delay=15.0
        )
        self.grace_s = float(grace_s)
        self.popen = popen
        self.clock = clock
        self.monitor_poll_s = float(monitor_poll_s)
        # crash-postmortem hook (docs/OBSERVABILITY.md "Alerting &
        # incidents"): called once per observed crash, BEFORE the handle
        # is wiped for restart — the callback still sees the generation,
        # output tail, and health history the dead process had
        self.on_crash = on_crash
        self._lock = threading.Lock()
        self._handles: List[ReplicaHandle] = []
        self._next_id = 0
        self._draining = False
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        # restart sleeps happen on the monitor thread; an Event-based wait
        # (not time.sleep) lets shutdown interrupt a pending backoff
        self._restart_at: Dict[int, float] = {}

    # -- spawn / address parsing ---------------------------------------
    def _spawn(self, handle: ReplicaHandle) -> None:
        cmd = self.build_cmd(handle.slot)
        env = dict(os.environ)
        if self.build_env is not None:
            env.update(self.build_env(handle.slot))
        handle.clear_address()
        proc = self.popen(
            cmd,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        handle.proc = proc
        # wall-clock birth of THIS incarnation: the crash-bundle writer
        # compares it against the black box's written_unix so a
        # crash-looping successor can't inherit its predecessor's final
        # state as its own forensics
        handle.spawned_at_unix = time.time()
        log_event(
            "replica-spawn",
            f"replica {handle.replica_id} spawned (pid {proc.pid})",
            level=logging.INFO,
            replica=handle.replica_id,
            pid=proc.pid,
        )
        threading.Thread(
            target=self._read_stdout,
            args=(handle, proc),
            daemon=True,
            name=f"replica-{handle.replica_id}-stdout",
        ).start()

    def _read_stdout(
        self, handle: ReplicaHandle, proc: "subprocess.Popen"
    ) -> None:
        """Drain the replica's stdout forever (an unread PIPE would block
        the child), parsing the serving banner for the bound address and
        keeping a short tail for crash diagnostics."""
        try:
            assert proc.stdout is not None
            for line in proc.stdout:
                handle.tail.append(line.rstrip("\n"))
                m = BANNER_RE.search(line)
                if m and handle.proc is proc:
                    handle.set_address(m.group(1), int(m.group(2)))
                logger.debug("[replica %d] %s", handle.replica_id,
                             line.rstrip("\n"))
        except (ValueError, OSError):  # pipe closed mid-read
            pass

    def _alloc_slot(self) -> int:
        """Lowest slot no ACTIVE handle holds (caller holds ``_lock``).
        A stopping replica's slot is reusable immediately: its successor
        may briefly share the core/device while the drain finishes — a
        bounded handover — whereas waiting for the exit would wrap new
        replicas past the configured mask layout, pinning two LIVE
        replicas to one mask permanently."""
        used = {h.slot for h in self._handles if not h.stopping}
        slot = 0
        while slot in used:
            slot += 1
        return slot

    # -- lifecycle ------------------------------------------------------
    def start(self, n_replicas: int) -> List[ReplicaHandle]:
        with self._lock:
            for _ in range(int(n_replicas)):
                handle = ReplicaHandle(self._next_id, slot=self._alloc_slot())
                self._next_id += 1
                self._handles.append(handle)
                self._spawn(handle)
        self._monitor = threading.Thread(
            target=self._monitor_loop, daemon=True, name="fleet-monitor"
        )
        self._monitor.start()
        return self.handles()

    def handles(self) -> List[ReplicaHandle]:
        with self._lock:
            return [h for h in self._handles if not h.stopping]

    def all_handles(self) -> List[ReplicaHandle]:
        with self._lock:
            return list(self._handles)

    @property
    def replica_count(self) -> int:
        return len(self.handles())

    # -- crash monitoring / restart ------------------------------------
    def _monitor_loop(self) -> None:
        while not self._stop.is_set():
            now = self.clock()
            for handle in self.handles():
                if self._draining or handle.stopping:
                    continue
                proc = handle.proc
                if proc is None or proc.poll() is None:
                    continue
                due = self._restart_at.get(handle.replica_id)
                if due is None:
                    # fresh crash: schedule the restart after backoff
                    rc = proc.returncode
                    if self.on_crash is not None:
                        # forensics FIRST: clear_address() below wipes
                        # the generation; the bundle writer needs the
                        # handle as the dead process left it
                        try:
                            self.on_crash(handle, rc)
                        except Exception:
                            logger.exception(
                                "crash-incident hook failed for replica %d",
                                handle.replica_id,
                            )
                    handle.clear_address()
                    handle.restarts += 1
                    if handle.restarts > self.max_restarts_per_replica:
                        log_event(
                            "replica-giving-up",
                            f"replica {handle.replica_id} exited rc={rc} "
                            f"after {handle.restarts - 1} restart(s) — "
                            "removing it from the fleet",
                            replica=handle.replica_id,
                            rc=rc,
                        )
                        # terminal: leave the active set entirely, so
                        # replica_count is honest, scale_to can spawn a
                        # replacement (a zombie handle would make the
                        # autoscaler's scale-up a silent no-op while it
                        # keeps consuming decisions and cooldown), and
                        # the slot frees for that replacement
                        handle.stopping = True
                        with self._lock:
                            if handle in self._handles:
                                self._handles.remove(handle)
                        continue
                    delay = self.restart_policy.delay(handle.restarts)
                    tail = " | ".join(list(handle.tail)[-3:])
                    log_event(
                        "replica-crash",
                        f"replica {handle.replica_id} exited rc={rc} — "
                        f"restart {handle.restarts}/"
                        f"{self.max_restarts_per_replica} in {delay:.2f}s"
                        + (f" (last output: {tail})" if tail else ""),
                        replica=handle.replica_id,
                        rc=rc,
                        restart=handle.restarts,
                        delay_s=round(delay, 3),
                    )
                    self._restart_at[handle.replica_id] = now + delay
                elif now >= due:
                    del self._restart_at[handle.replica_id]
                    self._spawn(handle)
            self._stop.wait(self.monitor_poll_s)

    # -- scaling --------------------------------------------------------
    def scale_to(self, n: int) -> int:
        """Grow or shrink the fleet to ``n`` replicas. Growth spawns
        fresh processes (they join the router once their /healthz goes
        200); shrink SIGTERMs the highest-id replicas — each drains its
        in-flight work and exits 0 — without blocking this caller.
        Returns the new target count."""
        n = int(n)
        with self._lock:
            active = [h for h in self._handles if not h.stopping]
            delta = n - len(active)
            if delta > 0:
                for _ in range(delta):
                    handle = ReplicaHandle(
                        self._next_id, slot=self._alloc_slot()
                    )
                    self._next_id += 1
                    self._handles.append(handle)
                    self._spawn(handle)
            elif delta < 0:
                # stop the youngest first: oldest replicas have the
                # longest-warmed caches and proven stability
                for handle in sorted(
                    active, key=lambda h: h.replica_id, reverse=True
                )[: -delta]:
                    handle.stopping = True
                    handle.ready = False
                    threading.Thread(
                        target=self._stop_one,
                        args=(handle,),
                        daemon=True,
                        name=f"replica-{handle.replica_id}-stop",
                    ).start()
        return n

    def _stop_one(self, handle: ReplicaHandle) -> Optional[int]:
        proc = handle.proc
        if proc is None:
            return None
        rc = terminate_with_grace(proc, grace_s=self.grace_s)
        log_event(
            "replica-stopped",
            f"replica {handle.replica_id} stopped (rc={rc})",
            level=logging.INFO,
            replica=handle.replica_id,
            rc=rc,
        )
        with self._lock:
            if handle in self._handles:
                self._handles.remove(handle)
        return rc

    # -- fleet shutdown -------------------------------------------------
    def begin_drain(self) -> None:
        """Stop restarting crashed replicas; the fleet is going down."""
        self._draining = True

    def stop_all(self) -> bool:
        """SIGTERM every replica (their own graceful drain finishes
        admitted work), escalate stragglers, join the monitor. Returns
        True when every replica exited 0 — the fleet's clean-drain bit."""
        self._draining = True
        self._stop.set()
        handles = self.all_handles()
        for h in handles:
            h.stopping = True
            h.ready = False
        # parallel SIGTERM: replicas drain concurrently, so the fleet's
        # drain time is the slowest replica's, not the sum
        results: Dict[int, Optional[int]] = {}
        threads = []
        for h in handles:
            if h.proc is None:
                continue

            def stop(h: ReplicaHandle = h) -> None:
                results[h.replica_id] = terminate_with_grace(
                    h.proc, grace_s=self.grace_s
                )

            t = threading.Thread(target=stop, daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=self.grace_s + 10.0)
        if self._monitor is not None:
            self._monitor.join(timeout=5.0)
            self._monitor = None
        clean = all(rc == 0 for rc in results.values())
        log_event(
            "fleet-replicas-stopped",
            f"{len(results)} replica(s) stopped "
            f"({'all clean' if clean else 'NON-ZERO exits: ' + str(results)})",
            level=logging.INFO if clean else logging.WARNING,
            exits={str(k): v for k, v in results.items()},
        )
        return clean


def build_serve_cmd(
    model_path: str,
    *,
    device: str = "cpu",
    port: int = 0,
    host: str = "127.0.0.1",
    max_batch: Optional[int] = None,
    max_wait_ms: Optional[float] = None,
    queue_size: Optional[int] = None,
    timeout_ms: Optional[float] = None,
    max_doc_len: Optional[int] = None,
    drain_timeout_s: Optional[float] = None,
    batching: Optional[str] = None,
    precision: Optional[str] = None,
    swap_dir: Optional[str] = None,
    incidents_dir: Optional[str] = None,
    blackbox: Optional[str] = None,
    observe_interval_s: Optional[float] = None,
    no_telemetry: bool = False,
    model_manifest: Optional[str] = None,
    resident_models: Optional[int] = None,
    extra_args: Sequence[str] = (),
) -> List[str]:
    """The canonical replica argv: one place building the ``serve`` line
    so the CLI, the bench, and the tests can't drift on flag names."""
    cmd = [
        sys.executable, "-m", "spacy_ray_tpu", "serve", str(model_path),
        "--host", host, "--port", str(int(port)), "--device", device,
    ]
    if max_batch is not None:
        cmd += ["--max-batch", str(int(max_batch))]
    if max_wait_ms is not None:
        cmd += ["--max-wait-ms", str(float(max_wait_ms))]
    if queue_size is not None:
        cmd += ["--queue-size", str(int(queue_size))]
    if timeout_ms is not None:
        cmd += ["--timeout-ms", str(float(timeout_ms))]
    if max_doc_len is not None:
        cmd += ["--max-doc-len", str(int(max_doc_len))]
    if drain_timeout_s is not None:
        cmd += ["--drain-timeout-s", str(float(drain_timeout_s))]
    if batching is not None:
        cmd += ["--batching", str(batching)]
    if precision is not None:
        cmd += ["--precision", str(precision)]
    if swap_dir is not None:
        # the ONE directory this replica's /admin/swap may load from —
        # the fleet controller's rollouts; anything else is 403
        cmd += ["--swap-dir", str(swap_dir)]
    if incidents_dir is not None:
        # the replica's own alert firings dump flight-recorder bundles
        # into the fleet-shared incidents directory
        cmd += ["--incidents-dir", str(incidents_dir)]
    if blackbox is not None:
        # SIGKILL-survivable state: the replica persists its span ring +
        # metric snapshots here every observer tick; the supervisor
        # copies it into the crash bundle when this process dies
        cmd += ["--blackbox", str(blackbox)]
    if observe_interval_s is not None:
        cmd += ["--observe-interval-s", str(float(observe_interval_s))]
    if model_manifest is not None:
        # multi-model serving: the replica builds its own registry /
        # residency / admission stack from the shared manifest
        cmd += ["--model-manifest", str(model_manifest)]
    if resident_models is not None:
        cmd += ["--resident-models", str(int(resident_models))]
    if no_telemetry:
        cmd.append("--no-telemetry")
    cmd += list(extra_args)
    return cmd
