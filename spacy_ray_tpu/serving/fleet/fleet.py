"""Fleet orchestration: wire supervisor + router + autoscaler into one
process with one lifecycle.

Topology (one fleet process, N replica processes)::

            clients
               |
        RouterHTTPServer (:port)         <- this process
         /v1/parse  /healthz  /metrics[?format=prometheus]
         /trace  /admin/exemplars
               |
        Router (least-outstanding, health-probed, retry-on-crash)
          |         |          |
       serve #0  serve #1 ... serve #N-1  <- subprocesses (one engine each)
          ^---- ReplicaSupervisor (spawn / backoff-restart / scale)
                      ^---- AutoscalerPolicy (SLO telemetry -> scale_to)

Shutdown is the trainer's drain discipline applied at fleet scope, via
the same ``ShutdownCoordinator.add_callback`` hook the single-replica
server uses: SIGTERM →

1. the router stops admitting (``/v1/parse`` and ``/healthz`` go 503);
2. in-flight forwarded requests complete (router-side wait);
3. every replica gets SIGTERM and runs its OWN graceful drain
   (finish queued + in-flight batches, exit 0) — in parallel, so the
   fleet drains in max(replica drain), not sum;
4. the fleet exits 0 iff the router went quiet AND every replica
   exited 0 — the honest-failure contract everywhere else in the repo.
"""

from __future__ import annotations

import logging
import shutil
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ...training.resilience import ShutdownCoordinator, log_event
from .autoscaler import AutoscalerPolicy, observation_from_snapshots
from .replica import ReplicaSupervisor, build_serve_cmd
from .router import Router, RouterHTTPServer, RouterTelemetry

__all__ = ["FleetConfig", "Fleet"]

logger = logging.getLogger("spacy_ray_tpu.serving")


@dataclass
class FleetConfig:
    """Everything a fleet needs; CLI flags and bench specs both build
    one of these (one knob surface, no drift)."""

    model_path: str
    host: str = "127.0.0.1"
    port: int = 8090
    device: str = "cpu"
    replicas: int = 2                 # initial size
    min_replicas: int = 1
    max_replicas: int = 4
    # per-replica serving knobs (None = the serve command's defaults)
    max_batch: Optional[int] = None
    max_wait_ms: Optional[float] = None
    queue_size: Optional[int] = None
    timeout_ms: Optional[float] = None
    max_doc_len: Optional[int] = None
    # admission discipline + precision overlay policy, passed through to
    # every replica (None = the serve command's defaults: continuous
    # admission, precision "auto" — bf16 overlay on accelerators only)
    batching: Optional[str] = None
    precision: Optional[str] = None
    # multi-model serving (docs/SERVING.md "Multi-model fleet"): a model
    # manifest turns every replica into a multi-model host (registry +
    # residency + admission built per replica from the same file) and
    # teaches the router to resolve/route per model; resident_models
    # caps each replica's LRU hot set. None = single-model, bit-identical
    # to before the subsystem existed.
    model_manifest: Optional[str] = None
    resident_models: Optional[int] = None
    replica_drain_timeout_s: float = 30.0
    # replica port assignment: 0 = ephemeral (parsed from each banner);
    # nonzero = base_port + slot (fixed layouts for firewalls — slots
    # are recycled across scale cycles, so ports never drift)
    base_port: int = 0
    # per-replica device pinning: visible-device masks cycled by the
    # replica's SLOT, e.g. ["0", "1"] -> slot 0 sees device 0, slot 1
    # device 1 (slots recycle, so a scale cycle can't double-book one)
    visible_devices: Optional[List[str]] = None
    visible_devices_env: str = "CUDA_VISIBLE_DEVICES"
    # the CPU value of the same idea: ``taskset -c`` core masks cycled by
    # slot, e.g. ["0", "1"] -> slot 0 owns core 0. On CPU the
    # "device" a replica must not share IS its core set — co-scheduled
    # unmasked replicas each spawn an nproc-wide XLA pool and thrash
    # (measured NEGATIVE scaling on this container without masks).
    # "auto" in the CLI resolves to one core per replica round-robin.
    cpu_cores: Optional[List[str]] = None
    # router
    # router response cache: ARMED by default since PR 13 (generation
    # correctness landed in PR 11 — stamped entries, mixed-generation
    # bypass, promotion flush — and the Zipfian open-loop record proves
    # the hit-rate x p99 win on skewed traffic; 0 = off)
    cache_mb: float = 32.0
    probe_interval_s: float = 0.5
    # length-bucket affinity routing (docs/SERVING.md "Data plane"):
    # steer similar doc lengths to the same replica so device batches
    # fill one bucket shape instead of padding to the longest straggler.
    # Off by default — it pays on skewed length mixtures with >1
    # replica (docs/TUNING.md §24), and is a no-op otherwise.
    length_routing: bool = False
    # live continuous learning (docs/SERVING.md "Continuous learning"):
    # watch_dir = a TrainCheckpoint directory a training run writes into;
    # new intact generations are canaried onto canary_fraction of the
    # replicas (traffic split by generation), then promoted fleet-wide or
    # auto-rolled-back by the guard (error rate / window-p99 regression)
    watch_dir: Optional[str] = None
    watch_interval_s: float = 2.0
    canary_fraction: float = 0.25
    guard_p99_frac: float = 1.5
    guard_error_rate: float = 0.02
    guard_min_samples: int = 20
    guard_bad_consecutive: int = 2
    guard_good_consecutive: int = 3
    guard_verdict_timeout_s: float = 120.0
    # autoscaler (disabled unless autoscale=True)
    autoscale: bool = False
    p99_target_ms: float = 500.0
    autoscale_interval_s: float = 2.0
    up_consecutive: int = 3
    down_consecutive: int = 10
    cooldown_s: float = 30.0
    # diagnosis layer (docs/OBSERVABILITY.md "Alerting & incidents"):
    # incidents_dir arms the flight recorder fleet-wide — the router
    # keeps a snapshot ring and dumps it when an alert fires; every
    # replica gets --incidents-dir (its own alert-triggered dumps) and
    # --blackbox <incidents_dir>/blackbox/slot-N.json (the
    # SIGKILL-survivable copy the crash postmortem reads; rewrites are
    # rate-limited to ~10s, so it may lag the crash by that much); a dead
    # replica produces a crash bundle with exit status, stderr tail,
    # config, generation, health history, and both processes' span
    # rings. None = recorder off; the AlertEngine itself runs whenever
    # telemetry is on (alert state costs nothing to keep).
    incidents_dir: Optional[str] = None
    observe_interval_s: float = 2.0
    alert_slo: float = 0.99
    # lifecycle
    drain_timeout_s: float = 60.0
    ready_timeout_s: float = 300.0
    telemetry: bool = True
    extra_replica_args: List[str] = field(default_factory=list)

    def build_cmd(self, slot: int) -> List[str]:
        # keyed on the replica's recycled resource SLOT, not its
        # monotonically-growing id: after scale-down/scale-up cycles the
        # mask and port layout stay within the configured set instead of
        # drifting (two live replicas sharing one core while another
        # sits idle is exactly the co-scheduling collapse masking exists
        # to prevent)
        port = 0 if self.base_port == 0 else self.base_port + slot
        prefix: List[str] = []
        if self.cpu_cores and self.device == "cpu":
            taskset = shutil.which("taskset")
            if taskset is None:
                logger.warning(
                    "cpu_cores set but taskset is unavailable; replica "
                    "slot %d spawns unpinned", slot,
                )
            else:
                mask = self.cpu_cores[slot % len(self.cpu_cores)]
                prefix = [taskset, "-c", mask]
        incidents = (
            self.incidents_dir if self.telemetry else None
        )
        return prefix + build_serve_cmd(
            self.model_path,
            device=self.device,
            port=port,
            host="127.0.0.1",
            max_batch=self.max_batch,
            max_wait_ms=self.max_wait_ms,
            queue_size=self.queue_size,
            timeout_ms=self.timeout_ms,
            max_doc_len=self.max_doc_len,
            drain_timeout_s=self.replica_drain_timeout_s,
            batching=self.batching,
            precision=self.precision,
            swap_dir=self.watch_dir,
            incidents_dir=incidents,
            blackbox=(
                self.blackbox_path(slot) if incidents is not None else None
            ),
            observe_interval_s=(
                self.observe_interval_s if incidents is not None else None
            ),
            no_telemetry=not self.telemetry,
            model_manifest=self.model_manifest,
            resident_models=self.resident_models,
            extra_args=self.extra_replica_args,
        )

    def blackbox_path(self, slot: int) -> str:
        """One black-box file per resource SLOT (slots recycle with the
        core/port layout, so a successor's recorder takes over exactly
        the file its predecessor's crash bundle was copied from)."""
        from pathlib import Path

        return str(
            Path(self.incidents_dir) / "blackbox" / f"slot-{int(slot)}.json"
        )

    def build_env(self, slot: int) -> Dict[str, str]:
        env: Dict[str, str] = {}
        if self.device == "cpu":
            # pin the platform in the child's env too: images whose
            # sitecustomize imports jax at boot lock the platform before
            # the child's _setup_device runs
            env["JAX_PLATFORMS"] = "cpu"
        if self.visible_devices:
            mask = self.visible_devices[slot % len(self.visible_devices)]
            env[self.visible_devices_env] = mask
        return env


class Fleet:
    """One fleet lifecycle: ``run()`` for the CLI (signal handlers +
    banner), ``start()``/``request_shutdown()``/``wait()`` for tests and
    the bench — the same drain code either way, mirroring ``Server``."""

    def __init__(self, config: FleetConfig) -> None:
        self.config = config
        self.tel = RouterTelemetry() if config.telemetry else None
        # diagnosis layer: alert engine whenever telemetry is on, flight
        # recorder + crash postmortems only with an incidents_dir. With
        # telemetry OFF neither exists — zero rule evaluations, zero
        # ring writes, zero incident I/O, even if incidents_dir is set
        # (guard-tested).
        self.alerts = None
        self.recorder = None
        on_crash = None
        if config.telemetry:
            from pathlib import Path

            from ...alerting import AlertEngine, default_router_rules
            from ...incidents import FlightRecorder

            inc_dir = (
                Path(config.incidents_dir)
                if config.incidents_dir else None
            )
            if inc_dir is not None:
                self.recorder = FlightRecorder(
                    incident_dir=inc_dir,
                    process_name="router",
                )
            self.alerts = AlertEngine(
                default_router_rules(
                    p99_target_s=config.p99_target_ms / 1e3,
                    slo=config.alert_slo,
                ),
                sink_path=(
                    inc_dir / "alerts.jsonl" if inc_dir is not None else None
                ),
                on_firing=(
                    self.recorder.alert_hook()
                    if self.recorder is not None
                    else None
                ),
                source="router",
            )
            if self.recorder is not None:
                self.recorder.attach(
                    trace=self.tel.trace,
                    alerts_fn=self.alerts.states,
                )
                on_crash = self._on_replica_crash
        self.supervisor = ReplicaSupervisor(
            config.build_cmd,
            build_env=config.build_env,
            grace_s=config.replica_drain_timeout_s + 15.0,
            on_crash=on_crash,
        )
        # multi-model: one registry parse in the fleet process (each
        # replica re-parses the same manifest itself) — the router's
        # model resolution and the placement policy both read it
        self.registry = None
        if config.model_manifest:
            from ..multimodel import ModelRegistry

            self.registry = ModelRegistry.from_manifest(
                config.model_manifest
            )
        self.router = Router(
            self.supervisor.handles,
            telemetry=self.tel,
            cache_bytes=int(config.cache_mb * 1024 * 1024),
            probe_interval_s=config.probe_interval_s,
            length_routing=config.length_routing,
            # the split only activates while ready replicas actually
            # straddle two generations, i.e. during a controller rollout
            canary_fraction=(
                config.canary_fraction if config.watch_dir else 0.0
            ),
            registry=self.registry,
        )
        self.controller = None
        if config.watch_dir:
            from ..live import CanaryGuard, LiveFleetController

            self.controller = LiveFleetController(
                config.watch_dir,
                self.router,
                canary_fraction=config.canary_fraction,
                interval_s=config.watch_interval_s,
                guard=CanaryGuard(
                    p99_frac=config.guard_p99_frac,
                    error_rate_high=config.guard_error_rate,
                    min_window_samples=config.guard_min_samples,
                    min_canary_requests=config.guard_min_samples,
                    bad_consecutive=config.guard_bad_consecutive,
                    good_consecutive=config.guard_good_consecutive,
                ),
                verdict_timeout_s=config.guard_verdict_timeout_s,
            )
        self.policy: Optional[AutoscalerPolicy] = None
        if config.autoscale:
            self.policy = AutoscalerPolicy(
                min_replicas=config.min_replicas,
                max_replicas=config.max_replicas,
                p99_target_s=config.p99_target_ms / 1e3,
                up_consecutive=config.up_consecutive,
                down_consecutive=config.down_consecutive,
                cooldown_s=config.cooldown_s,
            )
        # placement-aware extension of the autoscaler: with a manifest
        # AND autoscaling on, each tick also decides WHICH models need
        # another host (per-model window p99 vs the tightest class
        # target), applied via POST /admin/models/load and appended to
        # the placement ledger (a CI failure artifact)
        self.placement_policy = None
        self._placement_ledger: Optional[str] = None
        if self.registry is not None and config.autoscale:
            from ..multimodel import PlacementPolicy

            self.placement_policy = PlacementPolicy(
                self.registry,
                default_p99_target_ms=config.p99_target_ms,
                breach_consecutive=config.up_consecutive,
                cooldown_s=config.cooldown_s,
            )
            if config.incidents_dir:
                from pathlib import Path

                inc = Path(config.incidents_dir)
                inc.mkdir(parents=True, exist_ok=True)
                self._placement_ledger = str(inc / "placement.jsonl")
        self.router.alerts = self.alerts
        self.router.recorder = self.recorder
        self.httpd = RouterHTTPServer((config.host, config.port), self.router)
        self._stop = threading.Event()
        self._serve_thread: Optional[threading.Thread] = None
        self._autoscale_thread: Optional[threading.Thread] = None
        self._observer_thread: Optional[threading.Thread] = None

    # -- diagnosis layer -------------------------------------------------
    def _on_replica_crash(self, handle: Any, rc: int) -> None:
        """Supervisor crash hook: one bundle per dead replica — exit
        status + signal, output tail, effective argv, generation, the
        router's last health payloads, the replica's black box (its
        pre-crash span ring), and the router's own flight payload so
        the postmortem timeline crosses the process boundary."""
        from ...incidents import write_crash_bundle

        write_crash_bundle(
            self.config.incidents_dir,
            process_name=f"replica-{handle.replica_id}",
            rc=rc,
            argv=self.config.build_cmd(handle.slot),
            output_tail=list(handle.tail),
            generation=handle.generation,
            health_history=list(handle.health_history),
            blackbox_path=self.config.blackbox_path(handle.slot),
            process_started_unix=handle.spawned_at_unix,
            extra_flights={"router": self.recorder.payload()},
            replica_id=handle.replica_id,
            slot=handle.slot,
        )

    def observe_tick(self) -> None:
        """One diagnosis tick (callable directly by tests): feed the
        router-side flight ring and evaluate the router rule set over a
        composite snapshot — router telemetry plus the replica roster.
        No replica scrapes here: everything these rules read, the
        router already knows."""
        snap = {
            "router": self.tel.snapshot(),
            "replicas": [h.describe() for h in self.supervisor.handles()],
            "scrape_failures": self.router.scrape_failure_stats(),
            # router-process host truth: what the process.* alert rules
            # (rss-growth, fd-leak) and the flight ring read
            "process": self.tel.hoststats.sample(),
        }
        if self.recorder is not None:
            self.recorder.record(snap)
        if self.alerts is not None:
            self.alerts.evaluate(snap)

    def _observe_loop(self) -> None:
        while True:
            try:
                self.observe_tick()
            except Exception:  # the diagnosis loop must survive anything
                logger.exception("fleet observer tick failed")
            if self._stop.wait(self.config.observe_interval_s):
                return

    @property
    def address(self) -> Tuple[str, int]:
        host, port = self.httpd.server_address[:2]
        return str(host), int(port)

    # -- lifecycle ------------------------------------------------------
    def start(self) -> Tuple[str, int]:
        self.supervisor.start(self.config.replicas)
        self.router.start()
        self._serve_thread = threading.Thread(
            target=self.httpd.serve_forever,
            kwargs={"poll_interval": 0.1},
            name="fleet-http",
            daemon=True,
        )
        self._serve_thread.start()
        if self.policy is not None:
            self._autoscale_thread = threading.Thread(
                target=self._autoscale_loop,
                name="fleet-autoscaler",
                daemon=True,
            )
            self._autoscale_thread.start()
        if self.alerts is not None or self.recorder is not None:
            self._observer_thread = threading.Thread(
                target=self._observe_loop,
                name="fleet-observer",
                daemon=True,
            )
            self._observer_thread.start()
        if self.controller is not None:
            self.controller.start()
        return self.address

    def wait_ready(
        self, n: Optional[int] = None, timeout_s: Optional[float] = None
    ) -> bool:
        """Block until ``n`` replicas (default: all initial) are ready —
        warmup done, /healthz 200. The prober runs on its own cadence;
        this just polls its verdict."""
        want = self.config.replicas if n is None else int(n)
        deadline = time.monotonic() + (
            self.config.ready_timeout_s if timeout_s is None else timeout_s
        )
        while time.monotonic() < deadline:
            if len(self.router.ready_handles()) >= want:
                return True
            if self._stop.is_set():
                return False
            time.sleep(0.1)
        return False

    # -- autoscaling ----------------------------------------------------
    def _autoscale_loop(self) -> None:
        interval = self.config.autoscale_interval_s
        while not self._stop.wait(interval):
            if self.router.draining:
                return
            try:
                self.autoscale_tick()
            except Exception:  # the control loop must survive anything
                logger.exception("autoscaler tick failed")

    def autoscale_tick(self) -> Optional[int]:
        """One observe-decide-act cycle (callable directly by tests)."""
        assert self.policy is not None
        snaps = self.router.scrape_replica_metrics()
        obs = observation_from_snapshots(
            snaps, ready=len(self.router.ready_handles())
        )
        desired = self.policy.observe(obs)
        if desired is not None:
            if self.tel is not None:
                self.tel.trace.add_instant(
                    "autoscale", cat="fleet",
                    args={"from": obs.ready, "to": desired},
                )
                self.tel.registry.counter("autoscale_decisions").inc()
            self.supervisor.scale_to(desired)
        if self.placement_policy is not None:
            self.placement_tick(snaps)
        return desired

    def placement_tick(self, snaps: Optional[List[Dict[str, Any]]] = None):
        """Placement half of the scaling loop: per-model window p99 from
        the merged ``by_model`` view → which models need another host →
        apply via ``/admin/models/load`` + append to the ledger. Returns
        the decisions applied (callable directly by tests)."""
        assert self.placement_policy is not None
        from ...training.telemetry import merge_serving_snapshots

        if snaps is None:
            snaps = self.router.scrape_replica_metrics()
        merged = merge_serving_snapshots(snaps)
        by_model: Dict[str, Dict[str, Any]] = {}
        for name, sub in (merged.get("by_model") or {}).items():
            win = (sub or {}).get("slo_window") or {}
            by_model[name] = {
                "p99": win.get("request_latency_p99"),
                "samples": win.get("samples"),
            }
        decisions = self.placement_policy.observe(
            by_model,
            self.router.placement(),
            [h.replica_id for h in self.router.ready_handles()],
        )
        for d in decisions:
            try:
                status, _ = self.router.load_model(d.replica_id, d.model)
            except Exception as exc:
                status = None
                logger.warning(
                    "placement: load %r onto replica %d failed: %r",
                    d.model, d.replica_id, exc,
                )
            log_event(
                "placement-move",
                f"model {d.model!r} -> replica {d.replica_id} "
                f"(status {status}): {d.reason}",
                level=logging.INFO,
                model=d.model, replica=d.replica_id, status=status,
            )
            if self.tel is not None:
                self.tel.trace.add_instant(
                    "placement", cat="fleet",
                    args={"model": d.model, "replica": d.replica_id},
                )
                self.tel.registry.counter("placement_decisions").inc()
            if self._placement_ledger is not None:
                import json

                try:
                    with open(self._placement_ledger, "a") as fh:
                        fh.write(json.dumps({
                            "unix_time": round(time.time(), 3),
                            "model": d.model,
                            "replica_id": d.replica_id,
                            "status": status,
                            "reason": d.reason,
                        }) + "\n")
                except OSError:
                    logger.exception("placement ledger append failed")
        return decisions

    # -- shutdown -------------------------------------------------------
    def request_shutdown(self, signum: Optional[int] = None) -> None:
        """Signal-handler-safe (flag writes + Event set only, like
        Server.request_shutdown): the admission gate flips instantly;
        the waiting thread performs the actual drain."""
        self.router.draining = True
        self._stop.set()

    def wait(self) -> int:
        self._stop.wait()
        self.router.begin_drain()
        self.supervisor.begin_drain()  # a crash during drain stays down
        if self.controller is not None:
            self.controller.stop()  # no swaps into a draining fleet
        log_event(
            "fleet-drain",
            "shutdown requested — draining router, then "
            f"{self.supervisor.replica_count} replica(s)",
            level=logging.INFO,
        )
        router_quiet = self.router.wait_inflight(self.config.drain_timeout_s)
        self.router.stop()
        replicas_clean = self.supervisor.stop_all()
        self.httpd.shutdown()
        self.httpd.server_close()
        clean = router_quiet and replicas_clean
        if not clean:
            log_event(
                "fleet-drain-failed",
                f"router_quiet={router_quiet} replicas_clean={replicas_clean}",
            )
        return 0 if clean else 1

    def run(self, *, banner: bool = True) -> int:
        coordinator = ShutdownCoordinator()
        coordinator.add_callback(self.request_shutdown)
        coordinator.install()
        try:
            host, port = self.start()
            if banner:
                # parseable, like the single-replica banner: tests and
                # operator scripts read the router address from it
                print(
                    f"fleet serving on http://{host}:{port} "
                    f"({self.config.replicas} replica(s), device "
                    f"{self.config.device})",
                    flush=True,
                )
            if self.wait_ready():
                if banner:
                    print(
                        f"fleet ready: {len(self.router.ready_handles())} "
                        "replica(s) warmed", flush=True,
                    )
            elif not self._stop.is_set():
                print(
                    "fleet NOT ready within "
                    f"{self.config.ready_timeout_s:.0f}s — serving with "
                    f"{len(self.router.ready_handles())} ready replica(s)",
                    flush=True,
                )
            return self.wait()
        finally:
            coordinator.restore()
