"""Fleet router: one HTTP front-end load-balancing ``/v1/parse`` over N
engine replicas.

Balancing policy is least-outstanding-requests: among READY replicas,
pick the one with the fewest requests currently forwarded to it. With
homogeneous replicas this is the classic supermarket rule — it tracks
the real signal (how busy a replica is NOW, including slow batches)
rather than round-robin's assumption that every request costs the same.

Readiness is probed, never assumed: a background prober GETs each
replica's ``/healthz`` — 200 marks it ready, 503 (``warming`` during
the bucket compile sweep, ``draining`` during shutdown) or a connection
error marks it out. A forward that fails at the socket level marks the
replica unready IMMEDIATELY (no waiting for the next probe) and retries
the request on another replica — a replica crash under load costs the
in-flight retry, never a client-visible 5xx. When no replica is ready,
admission fails with a typed 503 ``no_replica`` instantly (shed, don't
queue blind).

The router deliberately does NOT parse request/response JSON on the hot
path — it forwards bytes. The single exception is the optional response
cache (``cache_bytes > 0``): a byte-capped LRU keyed by the hash of the
request's input texts (the ``CollateCache`` identity-key pattern from
the input pipeline, applied at the serving edge — heavy real traffic is
Zipfian), serving repeat bodies without touching a replica.

``/metrics`` on the router is the FLEET view: each ready replica's SLO
snapshot is scraped and merged (``training/telemetry.py:
merge_serving_snapshots``) with the router's own counters — one scrape
for the whole fleet instead of N.
"""

from __future__ import annotations

import http.client
import json
import logging
import threading
import time
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from ...training.resilience import log_event
from ..batcher import (
    Draining,
    REQUEST_ID_HEADER,
    ServingError,
    UnknownModel,
    cache_key_for,
    clean_request_id,
    etag_for,
    if_none_match_hit,
    mint_request_id,
)
from ..multimodel.registry import TENANT_HEADER
from .replica import ReplicaHandle

__all__ = [
    "NoReplicaAvailable",
    "ResponseCache",
    "GENERATION_MIXED",
    "RouterTelemetry",
    "Router",
    "RouterHTTPServer",
]

logger = logging.getLogger("spacy_ray_tpu.serving")

MAX_BODY_BYTES = 8 << 20  # same abuse cap as the single-replica server


class NoReplicaAvailable(ServingError):
    """Zero ready replicas (all warming, crashed, or draining): a typed
    503 the instant it is known — queueing the request blind would just
    convert an outage into a timeout storm."""

    http_status = 503
    code = "no_replica"


# sentinel for "the ready replicas straddle generations" (mid-rollout /
# mid-promotion): no single generation can vouch for a cached body, so
# the cache is bypassed entirely until the fleet converges
GENERATION_MIXED = object()


def _length_bucket_hint(texts: List[str]) -> int:
    """Coarse length-bucket index for affinity routing. The router does
    not tokenize; a whitespace word count approximates token count well
    enough to BUCKET — the buckets are powers of two, so a near-boundary
    miss lands one bucket off, which only weakens affinity, never
    correctness. Keyed on the MAX text (the shape the device batch pads
    to), same rule as the engine's dispatch assembly."""
    from ...training.batcher import DEFAULT_LENGTH_BUCKETS

    est = max(len(t.split()) for t in texts)
    for i, bucket in enumerate(DEFAULT_LENGTH_BUCKETS):
        if est <= bucket:
            return i
    return len(DEFAULT_LENGTH_BUCKETS) - 1


class ResponseCache:
    """Byte-capped LRU of successful ``/v1/parse`` response bodies,
    keyed by a digest of the request's input texts AND stamped with the
    checkpoint generation that produced them.

    Unlike the input pipeline's ``CollateCache`` (which keys on object
    identity because the corpus re-yields the same Examples), the edge
    sees texts by VALUE over the wire — so the key is a content hash.
    Responses are deterministic given the loaded params — which is
    exactly why the generation stamp exists: a PR 8 hot-swap promotion
    CHANGES the loaded params, and a hit is only exact *for the
    generation that computed it*. ``get`` therefore takes the
    generation the caller expects (the one every ready replica serves);
    an entry stamped with any other generation is dropped on access and
    counted as a stale invalidation, never served. ``flush`` clears the
    whole cache (the promotion hook — versioned keys make staleness
    impossible, the flush just reclaims the dead generation's bytes).
    The cached ``batch`` shape info still reflects the batch the
    ORIGINAL request ran in. Entries are only stored for status-200
    bodies.

    Thread-safe; hit/miss/eviction/stale/flush counters feed
    ``/metrics``.
    """

    def __init__(self, max_bytes: int) -> None:
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[bytes, Tuple[Any, bytes]]" = OrderedDict()
        self._nbytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.stale_invalidations = 0
        self.flushes = 0
        # conditional responses answered body-less (304): the client
        # already held the exact body this cache (or a replica) would
        # have sent — hit-adjacent, but zero bytes moved
        self.not_modified = 0
        # per-model hit/miss ledger (multi-model serving): the model
        # name is a key dimension, so two models' identical texts never
        # collide, and the hit-rate story is attributable per model
        self.by_model: Dict[str, Dict[str, int]] = {}

    # the digest lives in batcher.cache_key_for so the replica's ETag
    # and the router's cache key can never disagree about identity —
    # the ETag is that key plus the generation (docs/SERVING.md)
    key_for = staticmethod(cache_key_for)

    def _tally(self, model: Optional[str], field: str) -> None:
        """Caller holds ``_lock``."""
        if model is None:
            return
        ledger = self.by_model.setdefault(
            model, {"hits": 0, "misses": 0, "stale_invalidations": 0}
        )
        # not_modified joins a ledger lazily (first 304 for that model)
        # so the legacy ledger shape is unchanged for models that never
        # see a conditional request
        ledger[field] = ledger.get(field, 0) + 1

    def get(
        self, key: bytes, generation: Any = None,
        model: Optional[str] = None,
    ) -> Optional[bytes]:
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.misses += 1
                self._tally(model, "misses")
                return None
            stored_gen, body = entry
            if stored_gen != generation:
                # a promotion happened since this body was cached: it
                # holds the OLD generation's annotations — drop it, so
                # the miss path re-parses on the new weights
                del self._entries[key]
                self._nbytes -= len(body)
                self.stale_invalidations += 1
                self.misses += 1
                self._tally(model, "stale_invalidations")
                self._tally(model, "misses")
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            self._tally(model, "hits")
            return body

    def put(self, key: bytes, body: bytes, generation: Any = None) -> None:
        if len(body) > self.max_bytes:
            return  # one oversized response must not flush the cache
        with self._lock:
            if key in self._entries:
                old_gen, old_body = self._entries[key]
                if old_gen == generation:
                    return
                # same texts, newer generation: replace the stale entry
                self._nbytes -= len(old_body)
                del self._entries[key]
            self._entries[key] = (generation, body)
            self._nbytes += len(body)
            while self._nbytes > self.max_bytes and len(self._entries) > 1:
                _, (_, evicted) = self._entries.popitem(last=False)
                self._nbytes -= len(evicted)
                self.evictions += 1

    def count_not_modified(self, model: Optional[str] = None) -> None:
        with self._lock:
            self.not_modified += 1
            self._tally(model, "not_modified")

    def flush(self) -> int:
        """Drop every entry; returns how many. Called on promotion —
        the old generation's bodies can never hit again (their stamp no
        longer matches), so their bytes are reclaimed eagerly."""
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            self._nbytes = 0
            if n:
                self.flushes += 1
        return n

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Any] = {
                "cache_hits": self.hits,
                "cache_misses": self.misses,
                "cache_evictions": self.evictions,
                "cache_stale_invalidations": self.stale_invalidations,
                "cache_flushes": self.flushes,
                "cache_not_modified": self.not_modified,
                "cache_entries": len(self._entries),
                "cache_bytes": self._nbytes,
            }
            if self.by_model:
                out["by_model"] = {
                    m: dict(ledger)
                    for m, ledger in sorted(self.by_model.items())
                }
            return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


class RouterTelemetry:
    """Router-side SLO surface over the shared telemetry primitives:
    fleet latency histogram (admission at the router to response),
    routed/retried/rejected counters, ready-replica gauge, and a trace
    instant per routing anomaly. Nullable like every telemetry facade in
    this repo — when absent, the router makes ZERO telemetry calls."""

    def __init__(
        self,
        *,
        clock: Callable[[], float] = time.perf_counter,
        trace_max_events: int = 100_000,
    ) -> None:
        from ...training.telemetry import (
            LATENCY_BUCKETS,
            MetricsRegistry,
            TraceBuffer,
        )

        self.registry = MetricsRegistry(clock=clock)
        self.trace = TraceBuffer(clock=clock, max_events=trace_max_events)
        # host-resource truth for the router PROCESS itself (replicas
        # report their own via their snapshots); facade-owned so the
        # telemetry-off fleet constructs no sampler
        from ...training.hoststats import ProcessSampler

        self.hoststats = ProcessSampler(clock=clock)
        self._latency = self.registry.histogram(
            "router_latency_seconds", 2048, buckets=LATENCY_BUCKETS
        )
        self._requests = self.registry.counter("requests")
        self._routed = self.registry.counter("routed")
        self._retries = self.registry.counter("retries")
        self._rej_no_replica = self.registry.counter("rejected_no_replica")
        self._rej_draining = self.registry.counter("rejected_draining")
        # multi-model routing: requests naming a model the registry does
        # not know (typed 404 at the edge, never forwarded)
        self._rej_unknown_model = self.registry.counter(
            "rejected_unknown_model"
        )
        self._cache_hits = self.registry.counter("cache_hits")
        self._ready = self.registry.gauge("ready_replicas")
        self._replicas = self.registry.gauge("replicas")
        # satellite of the fleet /metrics contract: a ready replica
        # whose scrape fails is COUNTED, not silently dropped from the
        # aggregate — a fleet view quietly missing its slowest replica
        # is how an SLO breach hides
        self._scrape_failures = self.registry.counter("scrape_failures")
        # generation-split accounting: how many picks went to the canary
        # vs baseline side while a rollout was in flight — the exact
        # ratio the deterministic accumulator promises is auditable here
        self._canary_picks = self.registry.counter("routed_canary")
        self._baseline_picks = self.registry.counter("routed_baseline")
        # length-affinity accounting (data plane): how often the policy
        # placed a request on its bucket's replica vs spilled to
        # least-outstanding because that replica was already loaded —
        # a high spill share means the mixture defeats the affinity map
        self._affinity_picks = self.registry.counter("length_affinity_picks")
        self._affinity_spills = self.registry.counter(
            "length_affinity_spills"
        )

    def now(self) -> float:
        return self.trace.now()

    def request(self) -> None:
        self._requests.inc()

    def routed(
        self,
        latency_s: float,
        *,
        request_id: Optional[str] = None,
        t0: Optional[float] = None,
        replica_id: Optional[int] = None,
    ) -> None:
        self._routed.inc()
        self._latency.observe(latency_s)
        if t0 is not None:
            # the router-side half of the distributed request trace: one
            # ``route`` span per forwarded request, carrying the SAME
            # request id the replica's ``request`` span carries — the
            # collector's merged timeline shows the hop
            args: Dict[str, Any] = {}
            if request_id is not None:
                args["request_id"] = request_id
            if replica_id is not None:
                args["replica"] = replica_id
            self.trace.add_span(
                "route", t0, max(self.now() - t0, 0.0), cat="fleet",
                args=args or None,
            )

    def retry(
        self, replica_id: int, error: str, request_id: Optional[str] = None
    ) -> None:
        self._retries.inc()
        args = {"replica": replica_id, "error": error}
        if request_id is not None:
            args["request_id"] = request_id
        self.trace.add_instant("reroute", cat="fleet", args=args)

    def rejected(
        self, error: ServingError, request_id: Optional[str] = None
    ) -> None:
        if isinstance(error, Draining):
            self._rej_draining.inc()
        elif isinstance(error, UnknownModel):
            self._rej_unknown_model.inc()
        else:
            self._rej_no_replica.inc()
        args = {"error": str(error)}
        if request_id is not None:
            args["request_id"] = request_id
        self.trace.add_instant(
            f"reject:{error.code}", cat="fleet", args=args
        )

    def scrape_failed(self, replica_id: int) -> None:
        self._scrape_failures.inc()

    def cache_hit(self) -> None:
        self._cache_hits.inc()

    def split_pick(self, canary: bool) -> None:
        (self._canary_picks if canary else self._baseline_picks).inc()

    def affinity_pick(self, *, spilled: bool) -> None:
        (self._affinity_spills if spilled else self._affinity_picks).inc()

    def replica_counts(self, ready: int, total: int) -> None:
        self._ready.set(ready)
        self._replicas.set(total)

    def snapshot(self) -> Dict[str, Any]:
        snap = self.registry.snapshot()
        snap["slo"] = {
            "router_latency_p50": self._latency.percentile(0.50),
            "router_latency_p95": self._latency.percentile(0.95),
            "router_latency_p99": self._latency.percentile(0.99),
        }
        return snap


class Router:
    """Balancing + health state over a set of :class:`ReplicaHandle`.

    ``replicas`` is a zero-arg callable returning the current handles —
    the supervisor's live view, so scale-up/down is visible to the
    router without any registration protocol. Tests pass a lambda over
    a static list pointed at stub servers.
    """

    def __init__(
        self,
        replicas: Callable[[], List[ReplicaHandle]],
        *,
        telemetry: Optional[RouterTelemetry] = None,
        cache_bytes: int = 0,
        probe_interval_s: float = 0.5,
        probe_timeout_s: float = 5.0,
        forward_timeout_s: float = 60.0,
        canary_fraction: float = 0.0,
        registry: Optional[Any] = None,
        length_routing: bool = False,
        affinity_slack: int = 2,
    ) -> None:
        self.replicas = replicas
        self.tel = telemetry
        # length-bucket affinity (docs/SERVING.md "Data plane"): off by
        # default — the pad-share win only exists with >1 replica and a
        # skewed length mixture, and the policy costs a texts parse on
        # the otherwise byte-proxy hot path
        self.length_routing = bool(length_routing)
        self.affinity_slack = int(affinity_slack)
        # multi-model serving (``--model-manifest``): a ModelRegistry
        # lets the router resolve WHICH model a request names (path >
        # header > default) and route within the replicas hosting it;
        # None keeps the single-model path bit-identical
        self.registry = registry
        self.cache = ResponseCache(cache_bytes) if cache_bytes > 0 else None
        self.probe_interval_s = float(probe_interval_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.forward_timeout_s = float(forward_timeout_s)
        # generation traffic splitting (docs/SERVING.md "Continuous
        # learning"): active ONLY while a rollout controller has
        # declared a canary generation (``canary_generation`` set by
        # LiveFleetController at canary start, cleared at
        # promote/rollback/abort) — mere generation heterogeneity is
        # NOT a split trigger, because a crash-restarted replica serving
        # the disk model would otherwise become a one-node "baseline"
        # absorbing 1-fraction of all traffic. While active, this
        # fraction of requests routes to the canary generation's
        # replicas and the rest to everyone else. The split is a
        # deterministic error-diffusion accumulator, not a coin flip —
        # an exact long-run ratio the guard's sample-count math can
        # rely on, and reproducible tests.
        self.canary_fraction = float(canary_fraction)
        self.canary_generation: Optional[int] = None
        self._split_lock = threading.Lock()
        self._split_acc = 0.0
        # diagnosis layer (docs/OBSERVABILITY.md "Alerting & incidents"):
        # the Fleet wires an AlertEngine (served on /admin/alerts and in
        # the /metrics alerts block) and a FlightRecorder here when
        # telemetry is on; both stay None otherwise (zero-calls contract)
        self.alerts: Optional[Any] = None
        self.recorder: Optional[Any] = None
        self._stop = threading.Event()
        self._prober: Optional[threading.Thread] = None
        # per-replica scrape-failure ledger (fleet /metrics): replica_id
        # -> failed scrape count, alongside the telemetry counter — the
        # aggregate names WHO it is missing, not just that it is missing
        self._scrape_lock = threading.Lock()
        self.scrape_failures: Dict[int, int] = {}
        # mixed-generation cache bypasses: requests that skipped the
        # cache because the ready replicas straddled generations (a
        # rollout/promotion window). Counted at the ROUTER (the bypass
        # is a routing decision, not a cache event), surfaced next to
        # the cache's own hit/miss ledger in /metrics and as
        # ``srt_router_cache_mixed_generation_bypasses_total``.
        self._cache_bypass_lock = threading.Lock()
        self.cache_mixed_bypasses = 0
        # drain gate + in-flight accounting for the fleet's own drain
        self.draining = False
        self._inflight_lock = threading.Lock()
        self._inflight = 0
        self._idle = threading.Condition(self._inflight_lock)

    # -- health probing --------------------------------------------------
    def probe_once(self) -> int:
        """Probe every addressed replica's /healthz; update ready flags.
        Returns the number of ready replicas. Called by the prober loop
        and directly by tests (deterministic, no thread needed)."""
        handles = self.replicas()
        n_ready = 0
        for h in handles:
            addr = h.address
            if addr is None or h.stopping or not h.alive:
                self._mark_unready(h, "no address" if addr is None else "down")
                continue
            try:
                status, raw = self._get_aux(
                    h, addr, "/healthz", self.probe_timeout_s
                )
                ok = status == 200
            except OSError:
                ok = False
                raw = b""
            if ok:
                # the healthz body carries the replica's live-serving
                # identity (generation + swap_count) — the canary split
                # and the fleet controller read it from the handle, so
                # it must be as fresh as readiness itself
                try:
                    health = json.loads(raw)
                except ValueError:
                    health = {}
                if isinstance(health, dict):
                    gen = health.get("generation")
                    swaps = health.get("swap_count")
                    resident = health.get("resident_models")
                    default_model = health.get("default_model")
                    with h.lock:
                        h.generation = gen if isinstance(gen, int) else None
                        if isinstance(swaps, int):
                            h.swap_count = swaps
                        # residency advertisement (multi-model replicas
                        # only): the probe loop IS the placement
                        # discovery protocol — no registration RPC
                        h.resident_models = (
                            {
                                str(m): (info if isinstance(info, dict)
                                         else {})
                                for m, info in resident.items()
                            }
                            if isinstance(resident, dict) else {}
                        )
                        h.default_model = (
                            default_model
                            if isinstance(default_model, str) else None
                        )
                        # short health history: a crash postmortem's
                        # "what did the router last know about it"
                        h.health_history.append(
                            {
                                "unix_time": round(time.time(), 3),
                                "health": health,
                            }
                        )
                self._mark_ready(h)
                n_ready += 1
            else:
                self._mark_unready(h, "healthz != 200")
        if self.tel is not None:
            self.tel.replica_counts(n_ready, len(handles))
        return n_ready

    def _mark_ready(self, h: ReplicaHandle) -> None:
        with h.lock:
            was = h.ready
            h.ready = True
        if not was:
            log_event(
                "replica-ready",
                f"replica {h.replica_id} ready at "
                f"{h.host}:{h.port}",
                level=logging.INFO,
                replica=h.replica_id,
            )

    def _mark_unready(self, h: ReplicaHandle, reason: str) -> None:
        with h.lock:
            was = h.ready
            h.ready = False
        h.close_conns()  # pooled conns to a gone replica are all stale
        if was:
            log_event(
                "replica-unready",
                f"replica {h.replica_id} removed from rotation ({reason})",
                replica=h.replica_id,
                reason=reason,
            )

    def _probe_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.probe_once()
            except Exception:  # the prober must survive anything
                logger.exception("health probe pass failed")
            self._stop.wait(self.probe_interval_s)

    def start(self) -> "Router":
        self._prober = threading.Thread(
            target=self._probe_loop, daemon=True, name="fleet-prober"
        )
        self._prober.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._prober is not None:
            self._prober.join(timeout=5.0)
            self._prober = None
        for h in self.replicas():
            h.close_conns()

    # -- response cache generation discipline ---------------------------
    def cache_generation(self, model: Optional[str] = None) -> Any:
        """The generation a cache hit must match: the ONE generation
        every ready replica serves (learned from /healthz; None = the
        disk model is itself a valid generation). When ready replicas
        straddle generations — a canary rollout, a mid-promotion window,
        a crash-restarted straggler — returns :data:`GENERATION_MIXED`
        and the caller bypasses the cache: no single stamp could vouch
        for which replica a forward would hit.

        With ``model`` (multi-model serving), the discipline applies to
        the replicas HOSTING that model: their per-model generation from
        the /healthz resident set. A model resident nowhere yet (first
        request triggers the cold load) also yields the mixed sentinel —
        nothing can vouch for a body before placement is known."""
        if model is not None:
            hosts = [
                h for h in self.ready_handles()
                if model in h.resident_models
            ]
            if not hosts:
                return GENERATION_MIXED
            gens = {
                h.resident_models[model].get("generation") for h in hosts
            }
            if len(gens) == 1:
                return next(iter(gens))
            return GENERATION_MIXED
        gens = {h.generation for h in self.ready_handles()}
        if len(gens) == 1:
            return next(iter(gens))
        return GENERATION_MIXED

    def count_cache_bypass(self) -> None:
        with self._cache_bypass_lock:
            self.cache_mixed_bypasses += 1

    def cache_stats(self) -> Optional[Dict[str, Any]]:
        """The cache's own counters plus the router-side mixed-generation
        bypass count — ONE ledger for every surface (JSON /metrics,
        the Prometheus ``srt_router_cache_*`` series, ``telemetry top``,
        and the Zipfian bench record all read this)."""
        if self.cache is None:
            return None
        stats = self.cache.stats()
        with self._cache_bypass_lock:
            stats["cache_mixed_generation_bypasses"] = self.cache_mixed_bypasses
        return stats

    def flush_cache(self, reason: str = "") -> int:
        """Drop the whole response cache (the promotion hook — the live
        controller calls this whenever the fleet's current generation
        changes). No-op without a cache."""
        if self.cache is None:
            return 0
        n = self.cache.flush()
        if n:
            log_event(
                "cache-flush",
                f"response cache flushed ({n} entr(ies))"
                + (f": {reason}" if reason else ""),
                level=logging.INFO,
                entries=n,
                reason=reason,
            )
        return n

    # -- balancing -------------------------------------------------------
    def ready_handles(self) -> List[ReplicaHandle]:
        return [
            h for h in self.replicas()
            if h.ready and not h.stopping and h.address is not None
        ]

    def pick(
        self,
        model: Optional[str] = None,
        length_bucket: Optional[int] = None,
    ) -> ReplicaHandle:
        """Least-outstanding-requests over the ready set; ties broken by
        lowest id (deterministic, and it keeps warm caches warm).

        With ``length_routing`` armed and a ``length_bucket`` hint
        (docs/SERVING.md "Data plane"), a deterministic bucket→replica
        affinity runs WITHIN the final candidate pool — after model
        narrowing and the canary split, never instead of them — so
        similar doc lengths land on the same replica and its device
        batches fill one bucket shape instead of padding to the longest
        straggler. Affinity is advisory: when the affinity replica is
        already ``affinity_slack`` requests above the pool's
        least-loaded, the pick spills to least-outstanding — a skewed
        length mixture must never starve or overload a replica. With
        the flag off, a single-replica pool, or no hint, the pick is
        bit-identical to plain least-outstanding.

        With ``model`` (multi-model serving), least-outstanding runs
        WITHIN the subset of ready replicas whose probe-learned resident
        set includes that model — a request never pays another model's
        cold load when a warm host exists. When NO ready replica hosts
        it yet, the full ready set is the pool: the chosen replica's
        residency manager cold-loads on arrival, and the next probe
        teaches the router the new placement.

        With ``canary_fraction > 0`` and an ACTIVE rollout
        (``canary_generation`` set by the controller), the ready set
        first splits into canary (replicas on that generation) vs
        baseline (everyone else), the accumulator picks the side, and
        least-outstanding runs WITHIN it — load stays balanced inside
        each generation while the cross-generation ratio stays exact.
        Outside a rollout there is never a split, no matter how
        heterogeneous the observed generations are."""
        ready = self.ready_handles()
        if not ready:
            raise NoReplicaAvailable(
                "no replica is ready (all warming, draining, or down)"
            )
        if model is not None:
            hosting = [h for h in ready if model in h.resident_models]
            if hosting:
                ready = hosting
        pool = ready
        target = self.canary_generation
        if self.canary_fraction > 0.0 and target is not None:
            canary = [h for h in ready if h.generation == target]
            baseline = [h for h in ready if h.generation != target]
            if canary and baseline:
                with self._split_lock:
                    self._split_acc += min(self.canary_fraction, 1.0)
                    take_canary = self._split_acc >= 1.0 - 1e-9
                    if take_canary:
                        self._split_acc -= 1.0
                pool = canary if take_canary else baseline
                if self.tel is not None:
                    self.tel.split_pick(take_canary)
        if (
            self.length_routing
            and length_bucket is not None
            and len(pool) > 1
        ):
            ordered = sorted(pool, key=lambda h: h.replica_id)
            target = ordered[length_bucket % len(ordered)]
            floor = min(h.outstanding for h in pool)
            if target.outstanding <= floor + self.affinity_slack:
                if self.tel is not None:
                    self.tel.affinity_pick(spilled=False)
                return target
            if self.tel is not None:
                self.tel.affinity_pick(spilled=True)
        return min(
            pool, key=lambda h: (h.outstanding, h.replica_id)
        )

    # -- forwarding --------------------------------------------------------
    def forward_parse(
        self,
        body: bytes,
        timeout_s: Optional[float] = None,
        request_id: Optional[str] = None,
        *,
        model: Optional[str] = None,
        explicit_model: bool = False,
        tenant: Optional[str] = None,
        length_bucket: Optional[int] = None,
        if_none_match: Optional[str] = None,
    ) -> Tuple[int, bytes, Optional[int], Optional[str]]:
        """Route one ``/v1/parse`` body: pick → forward → on socket
        failure mark the replica unready and retry on another. The retry
        budget is one attempt per distinct ready replica (+1): a body
        that fails everywhere means the fleet is down, not the request.
        Returns ``(status, payload, replica_id, etag)`` — ``etag`` is
        the replica's ``ETag`` response header (None when absent);
        ``request_id`` (when given) is forwarded in the
        ``X-SRT-Request-Id`` header so the replica's spans and response
        carry the router's id. ``length_bucket`` is the affinity hint
        ``pick`` consumes; ``if_none_match`` rides through to the
        replica so ITS conditional check can answer a body-less 304
        even when the router's own cache could not.

        ``model`` (multi-model serving) narrows ``pick`` to the replicas
        hosting it; when the client NAMED the model (``explicit_model``,
        via path or header) the forward goes to the normalized
        ``/v1/models/<name>/parse`` path, while an implicit default stays
        on the legacy ``/v1/parse`` wire shape. ``tenant`` is forwarded
        in ``X-SRT-Tenant`` — quota enforcement lives at the replica's
        admission edge, the router only carries the identity.

        Replica-level HTTP errors (429/504/...) are passed through
        verbatim — they are per-replica admission decisions the client
        must see, not routing failures. The exception is a replica's own
        503 ``draining``/``warming``: that replica is leaving (or has not
        yet joined) rotation — e.g. a scale-down SIGTERM landed between
        ``pick()`` and the forward — so the request retries on another
        replica (safe: ``/v1/parse`` is pure) instead of leaking a 5xx
        to a client other replicas could have served.
        """
        if self.draining:
            raise Draining("fleet is draining; not admitting requests")
        path = (
            f"/v1/models/{model}/parse"
            if model is not None and explicit_model else "/v1/parse"
        )
        extra_headers: Optional[Dict[str, str]] = None
        if tenant or if_none_match:
            extra_headers = {}
            if tenant:
                extra_headers[TENANT_HEADER] = tenant
            if if_none_match:
                extra_headers["If-None-Match"] = if_none_match
        with self._inflight_lock:
            self._inflight += 1
        try:
            attempts = 0
            max_attempts = max(len(self.ready_handles()), 1) + 1
            last_err: Optional[Exception] = None
            while attempts < max_attempts:
                attempts += 1
                # raises NoReplicaAvailable on empty ready set
                h = self.pick(model, length_bucket=length_bucket)
                addr = h.address
                if addr is None:
                    continue
                with h.lock:
                    h.outstanding += 1
                try:
                    status, payload, etag = self._post(
                        h, addr, path, body,
                        timeout_s or self.forward_timeout_s,
                        request_id=request_id,
                        extra_headers=extra_headers,
                    )
                    if status == 503 and self._replica_unavailable(payload):
                        # the replica itself says it can't take traffic
                        # (draining out of a scale-down, or still
                        # warming): out of rotation, retry elsewhere
                        last_err = OSError(
                            f"replica {h.replica_id} answered 503 "
                            "(draining/warming)"
                        )
                        self._mark_unready(h, "replica 503 draining/warming")
                        if self.tel is not None:
                            self.tel.retry(
                                h.replica_id, "Replica503", request_id
                            )
                        continue
                    return status, payload, h.replica_id, etag
                except OSError as e:
                    # crashed or restarting mid-request: out of rotation
                    # NOW; the prober re-adds it when /healthz recovers
                    last_err = e
                    self._mark_unready(h, f"forward failed: {e!r}")
                    if self.tel is not None:
                        self.tel.retry(
                            h.replica_id, type(e).__name__, request_id
                        )
                finally:
                    with h.lock:
                        h.outstanding -= 1
            raise NoReplicaAvailable(
                f"request failed on {attempts} replica attempt(s); "
                f"last error: {last_err!r}"
            )
        finally:
            with self._inflight_lock:
                self._inflight -= 1
                self._idle.notify_all()

    @staticmethod
    def _replica_unavailable(payload: bytes) -> bool:
        """True when a 503 body is the replica's own not-in-rotation
        signal (typed ``draining``/``warming`` from server.py) — the only
        replica statuses the router retries rather than passes through.
        Off the hot path: only 503 bodies are ever parsed."""
        try:
            err = json.loads(payload)
        except ValueError:
            return False
        return (
            isinstance(err, dict)
            and err.get("error") in ("draining", "warming")
        )

    @staticmethod
    def _post(
        h: ReplicaHandle, addr: Tuple[str, int], path: str, body: bytes,
        timeout_s: float, request_id: Optional[str] = None,
        extra_headers: Optional[Dict[str, str]] = None,
    ) -> Tuple[int, bytes]:
        """POST over a pooled keep-alive connection to the replica.

        A fresh TCP dial + replica-side handler-thread spawn per forward
        costs more than a small parse itself, so idle connections are
        pooled per handle. A pooled connection can have gone stale (the
        replica restarted, or closed it while idle) — and when one is,
        usually ALL of them are: a restart severs the whole pool at
        once. A stale failure therefore retries on the NEXT pooled
        connection (draining the severed pool one checkout at a time)
        and finally on a freshly dialed connection before the error
        propagates — safe to resend because ``/v1/parse`` is pure.
        Failures on a fresh dial surface as OSError (the contract
        ``forward_parse``'s replica-level retry loop keys on).

        Returns ``(status, payload, etag)`` — the replica's ``ETag``
        response header rides along so the edge can propagate it to the
        client without parsing the body.
        """
        headers = {"Content-Type": "application/json"}
        if request_id is not None:
            headers[REQUEST_ID_HEADER] = request_id
        if extra_headers:
            headers.update(extra_headers)
        conn = h.checkout_conn()
        while True:
            fresh = conn is None
            if fresh:
                conn = http.client.HTTPConnection(
                    addr[0], addr[1], timeout=timeout_s
                )
            try:
                conn.request("POST", path, body, headers)
                resp = conn.getresponse()
                payload = resp.read()
            except (OSError, http.client.HTTPException) as e:
                conn.close()
                if not fresh:
                    # try the next pooled conn; None → one fresh dial
                    conn = h.checkout_conn()
                    continue
                if not isinstance(e, OSError):
                    raise OSError(f"replica HTTP protocol error: {e!r}")
                raise
            if resp.will_close:
                conn.close()
            else:
                h.checkin_conn(conn)
            return resp.status, payload, resp.getheader("ETag")

    @staticmethod
    def _get_aux(
        h: ReplicaHandle, addr: Tuple[str, int], path: str, timeout_s: float
    ) -> Tuple[int, bytes]:
        """GET over a pooled control-plane connection. Probes and
        scrapes repeat every ``probe_interval_s`` forever — dialing
        fresh each pass adds up to more control-plane TCP churn than
        the data plane's, for sockets to the very same replicas. Same
        stale discipline as ``_post``: a stale pooled socket retries on
        the next pooled one, then one fresh dial; failures surface as
        OSError (what every caller already treats as "unhealthy")."""
        conn = h.checkout_aux_conn()
        while True:
            fresh = conn is None
            if fresh:
                conn = http.client.HTTPConnection(
                    addr[0], addr[1], timeout=timeout_s
                )
            try:
                conn.request("GET", path)
                resp = conn.getresponse()
                payload = resp.read()
            except (OSError, http.client.HTTPException) as e:
                conn.close()
                if not fresh:
                    conn = h.checkout_aux_conn()
                    continue
                if not isinstance(e, OSError):
                    raise OSError(f"replica HTTP protocol error: {e!r}")
                raise
            if resp.will_close:
                conn.close()
            else:
                h.checkin_aux_conn(conn)
            return resp.status, payload

    # -- placement (multi-model) -----------------------------------------
    def placement(self) -> Dict[int, List[str]]:
        """Probe-learned placement: replica_id → resident model names
        (every addressed replica, ready or not — the placement policy
        filters by its own ready list)."""
        return {
            h.replica_id: sorted(h.resident_models)
            for h in self.replicas()
        }

    def load_model(
        self, replica_id: int, model: str, timeout_s: Optional[float] = None
    ) -> Tuple[int, bytes]:
        """Apply one placement decision: POST ``/admin/models/load`` to
        the chosen replica (a fresh connection — admin traffic must not
        touch the hot-path pool). Raises ``NoReplicaAvailable`` when the
        replica has no address."""
        handle = next(
            (h for h in self.replicas() if h.replica_id == replica_id),
            None,
        )
        addr = handle.address if handle is not None else None
        if addr is None:
            raise NoReplicaAvailable(
                f"replica {replica_id} is not addressable"
            )
        body = json.dumps({"model": model}).encode("utf8")
        conn = http.client.HTTPConnection(
            addr[0], addr[1],
            timeout=timeout_s or self.forward_timeout_s,
        )
        try:
            conn.request(
                "POST", "/admin/models/load", body,
                {"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            payload = resp.read()
        finally:
            conn.close()
        if resp.status == 200 and handle is not None:
            # teach the router immediately (the next probe would too,
            # but pick() should see the new host without the probe gap)
            with handle.lock:
                handle.resident_models.setdefault(model, {})
        return resp.status, payload

    # -- fleet metrics ----------------------------------------------------
    def scrape_replica_metrics(self) -> List[Dict[str, Any]]:
        """GET /metrics from every ready replica (best-effort: a replica
        that fails the scrape is skipped, not fatal).

        Scrapes run CONCURRENTLY, one thread per replica: a single hung
        replica bounds the whole pass at max(timeout), not sum — this is
        on the caller's thread for both client ``/metrics`` requests and
        the autoscaler tick, which must keep its cadence exactly when
        replicas are unhealthy and scaling decisions matter most."""
        handles = [h for h in self.ready_handles() if h.address is not None]
        results: List[Optional[Dict[str, Any]]] = [None] * len(handles)

        def scrape(i: int, h: ReplicaHandle) -> None:
            addr = h.address
            if addr is None:
                return
            try:
                status, raw = self._get_aux(
                    h, addr, "/metrics", self.probe_timeout_s
                )
                if status == 200:
                    snap = json.loads(raw)
                    if isinstance(snap, dict):
                        snap["replica_id"] = h.replica_id
                        results[i] = snap
            except (OSError, ValueError):
                pass

        if len(handles) == 1:  # no thread churn for the common small case
            scrape(0, handles[0])
        elif handles:
            threads = [
                threading.Thread(
                    target=scrape, args=(i, h), daemon=True,
                    name=f"scrape-replica-{h.replica_id}",
                )
                for i, h in enumerate(handles)
            ]
            for t in threads:
                t.start()
            deadline = time.monotonic() + self.probe_timeout_s + 1.0
            for t in threads:
                t.join(timeout=max(deadline - time.monotonic(), 0.0))
        # snapshot the results ONCE past the join deadline: a straggler
        # thread landing its payload after this point must not be merged
        # while also being counted as a failure — the ledger and the
        # aggregate have to tell the same story about who was present
        final = list(results)
        # a READY replica that failed its scrape is an observability
        # gap, not a routine miss: count it per replica (and in the
        # scrape_failures counter) so the aggregate says whose numbers
        # it is missing instead of silently shrinking the fleet view
        for h, snap in zip(handles, final):
            if snap is None:
                with self._scrape_lock:
                    self.scrape_failures[h.replica_id] = (
                        self.scrape_failures.get(h.replica_id, 0) + 1
                    )
                if self.tel is not None:
                    self.tel.scrape_failed(h.replica_id)
        return [snap for snap in final if snap is not None]

    def scrape_failure_stats(self) -> Dict[str, int]:
        with self._scrape_lock:
            return {str(k): v for k, v in sorted(self.scrape_failures.items())}

    def scrape_replica_exemplars(self) -> List[Dict[str, Any]]:
        """GET /admin/exemplars from every ready replica (best-effort,
        sequential — this is a diagnostic pull, not the hot path);
        each replica's payload is tagged with its id."""
        out: List[Dict[str, Any]] = []
        for h in self.ready_handles():
            addr = h.address
            if addr is None:
                continue
            try:
                status, raw = self._get_aux(
                    h, addr, "/admin/exemplars", self.probe_timeout_s
                )
                if status == 200:
                    payload = json.loads(raw)
                    if isinstance(payload, dict):
                        payload["replica_id"] = h.replica_id
                        out.append(payload)
            except (OSError, ValueError):
                continue
        return out

    def fleet_metrics(self) -> Dict[str, Any]:
        """The aggregated /metrics payload: per-replica snapshots merged
        into one fleet view + the router's own counters + cache stats +
        the per-replica scrape-failure ledger (a replica missing from
        the merge is NAMED, never silently dropped)."""
        from ...training.telemetry import merge_serving_snapshots

        merged = merge_serving_snapshots(self.scrape_replica_metrics())
        out: Dict[str, Any] = {"fleet": merged}
        out["replicas"] = [h.describe() for h in self.replicas()]
        out["scrape_failures"] = self.scrape_failure_stats()
        if self.registry is not None:
            # the placement view the policy (and `telemetry top`) reads:
            # which replicas host which models, per the last probe pass
            out["placement"] = {
                str(rid): models
                for rid, models in sorted(self.placement().items())
            }
            out["models"] = self.registry.names()
            out["default_model"] = self.registry.default_model
        if self.tel is not None:
            out["router"] = self.tel.snapshot()
            # the router process's own host truth (each replica's rides
            # inside its snapshot under fleet/replica entries)
            out["process"] = self.tel.hoststats.sample()
        if self.alerts is not None:
            out["alerts"] = self.alerts.summary()
        cache_stats = self.cache_stats()
        if cache_stats is not None:
            out["cache"] = cache_stats
        return out

    def prometheus_metrics(self) -> str:
        """The router's ``/metrics?format=prometheus`` body, assembled
        from three honest layers:

        * per-replica serving series labeled ``replica_id`` — counters
          and cumulative ``_bucket`` histograms are exact per replica,
          and a scraper may sum them across replicas exactly (the
          aggregation story Prometheus is built for);
        * fleet-level percentile gauges from the count-weighted
          ``merge_serving_snapshots`` view (``_worst`` alongside) —
          percentiles do NOT sum, so the merge rule is applied here and
          labeled as the fleet view, with the generation-split window
          p99s carrying a ``generation`` label (the canary signal);
        * the router's own counters/gauges under ``srt_router``,
          including ``srt_router_replica_scrape_failures_total`` per
          replica.
        """
        from ...training.prometheus import PromFamilies
        from ...training.telemetry import merge_serving_snapshots

        snaps = self.scrape_replica_metrics()
        merged = merge_serving_snapshots(snaps)
        fam = PromFamilies()
        for snap in snaps:
            labels = {"replica_id": snap.get("replica_id")}
            fam.add_snapshot(snap, prefix="srt_serving", labels=labels)
            gen = snap.get("generation")
            if gen is not None:
                fam.add("srt_serving_generation_id", "gauge", gen, labels)
        win = merged.get("slo_window")
        if isinstance(win, dict):
            for q in ("p50", "p95", "p99"):
                for suffix in ("", "_worst"):
                    fam.add(
                        "srt_fleet_request_latency_window_seconds",
                        "gauge",
                        win.get(f"request_latency_{q}{suffix}"),
                        {
                            "quantile": q.replace("p", "0."),
                            "aggregate": (
                                "worst_replica" if suffix
                                else "count_weighted_mean"
                            ),
                        },
                    )
        by_gen = merged.get("by_generation")
        if isinstance(by_gen, dict):
            for gen_key, sub in sorted(by_gen.items()):
                sub_win = (sub or {}).get("slo_window")
                if isinstance(sub_win, dict):
                    fam.add(
                        "srt_fleet_generation_request_latency_window_seconds",
                        "gauge",
                        sub_win.get("request_latency_p99"),
                        {"generation": gen_key, "quantile": "0.99"},
                    )
        by_model = merged.get("by_model")
        if isinstance(by_model, dict):
            # per-model fleet series (multi-model serving): counters sum
            # exactly across replicas so the model-labeled snapshot walk
            # is honest; window percentiles follow the same merge rule
            # as the fleet-level gauges, labeled per model — the
            # placement policy's breach signal and the per-class SLO
            # story both read these
            for model_name, sub in sorted(by_model.items()):
                if not isinstance(sub, dict):
                    continue
                fam.add_snapshot(
                    sub, prefix="srt_fleet_model",
                    labels={"model": model_name},
                )
                sub_win = sub.get("slo_window")
                if isinstance(sub_win, dict):
                    for q in ("p50", "p95", "p99"):
                        fam.add(
                            "srt_fleet_model_request_latency_window_seconds",
                            "gauge",
                            sub_win.get(f"request_latency_{q}"),
                            {
                                "model": model_name,
                                "quantile": q.replace("p", "0."),
                            },
                        )
        if self.tel is not None:
            tel_snap = self.tel.snapshot()
            if self.cache is not None:
                # the cache's own ledger below is the canonical
                # srt_router_cache_* source; dropping the telemetry twin
                # avoids a duplicate unlabeled series in the same family
                (tel_snap.get("counters") or {}).pop("cache_hits", None)
            fam.add_snapshot(tel_snap, prefix="srt_router")
            from ...training.hoststats import add_process_family

            # the ROUTER's own srt_process_* family, unlabeled; the
            # replicas' families live on their own scrape endpoints
            # (labeling them into this body would double-count RSS in
            # any sum() a scraper writes)
            add_process_family(fam, self.tel.hoststats.sample())
        for rid, n in self.scrape_failure_stats().items():
            fam.add(
                "srt_router_replica_scrape_failures_total", "counter", n,
                {"replica_id": rid},
            )
        if self.alerts is not None:
            self.alerts.add_prometheus(fam)
        cache_stats = self.cache_stats()
        if cache_stats is not None:
            # event tallies are counters (scrapers may rate() them —
            # the Zipfian hit-rate signal); entry/byte occupancy stays a
            # gauge (a level, not an event count)
            for key in (
                "cache_hits", "cache_misses", "cache_evictions",
                "cache_stale_invalidations", "cache_flushes",
                "cache_mixed_generation_bypasses",
                "cache_not_modified",
            ):
                fam.add(
                    f"srt_router_{key}_total", "counter",
                    cache_stats.get(key),
                )
            for key in ("cache_entries", "cache_bytes"):
                fam.add(f"srt_router_{key}", "gauge", cache_stats.get(key))
            # per-model cache ledger under its own family name — mixing
            # model-labeled samples into the unlabeled totals above
            # would double-count any sum() a scraper writes
            for model_name, ledger in sorted(
                (cache_stats.get("by_model") or {}).items()
            ):
                for key in (
                    "hits", "misses", "stale_invalidations",
                    "not_modified",
                ):
                    fam.add(
                        f"srt_router_model_cache_{key}_total", "counter",
                        ledger.get(key), {"model": model_name},
                    )
        fam.add("srt_fleet_replicas", "gauge", merged.get("replicas"))
        return fam.render()

    # -- drain -------------------------------------------------------------
    def begin_drain(self) -> None:
        self.draining = True

    def wait_inflight(self, timeout_s: float) -> bool:
        """Block until every in-flight forwarded request completed (the
        replicas behind them are still up — the fleet drain stops THEM
        only after the router is quiet). False on timeout."""
        deadline = time.monotonic() + float(timeout_s)
        with self._idle:
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._idle.wait(timeout=min(remaining, 0.1))
        return True


class RouterHTTPServer(ThreadingHTTPServer):
    """Handler threads do byte-level proxying only; all JSON work stays
    on the replicas (the router must not become the GIL bottleneck the
    fleet exists to remove)."""

    daemon_threads = True

    def __init__(self, addr: Tuple[str, int], router: Router) -> None:
        super().__init__(addr, _RouterHandler)
        self.router = router


class _RouterHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    # loopback is immune, but over a real link Nagle + delayed ACK can
    # add ~40ms between the header write and the body write
    disable_nagle_algorithm = True
    server: RouterHTTPServer

    def log_message(self, fmt: str, *args: Any) -> None:
        logger.debug("%s " + fmt, self.address_string(), *args)

    def _reply_bytes(
        self,
        status: int,
        body: bytes,
        request_id: Optional[str] = None,
        content_type: str = "application/json",
        etag: Optional[str] = None,
    ) -> None:
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        if request_id is not None:
            self.send_header(REQUEST_ID_HEADER, request_id)
        if etag is not None:
            self.send_header("ETag", etag)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if body:
            self.wfile.write(body)

    def _reply_not_modified(
        self, etag: Optional[str], request_id: Optional[str] = None
    ) -> None:
        """Body-less 304 from the edge: the client's cached body is
        still exact for the fleet's converged generation."""
        self.send_response(304)
        if etag:
            self.send_header("ETag", etag)
        if request_id is not None:
            self.send_header(REQUEST_ID_HEADER, request_id)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def _reply(
        self,
        status: int,
        payload: Dict[str, Any],
        request_id: Optional[str] = None,
    ) -> None:
        self._reply_bytes(
            status, json.dumps(payload).encode("utf8"), request_id
        )

    def _reply_error(
        self, err: ServingError, request_id: Optional[str] = None
    ) -> None:
        self._reply(
            err.http_status, {"error": err.code, "message": str(err)},
            request_id,
        )

    # -- GET ------------------------------------------------------------
    def do_GET(self) -> None:  # noqa: N802
        router = self.server.router
        parsed = urlparse(self.path)
        self.path = parsed.path
        if self.path == "/healthz":
            replicas = [h.describe() for h in router.replicas()]
            n_ready = sum(1 for r in replicas if r["ready"])
            payload: Dict[str, Any] = {"replicas": replicas}
            if router.tel is not None:
                # clock anchor for the cross-process trace collector —
                # same contract as the replica/trainer /healthz
                payload["anchor"] = router.tel.trace.anchor()
            if router.draining:
                self._reply(503, {"status": "draining", **payload})
            elif n_ready == 0:
                self._reply(
                    503,
                    {"status": "unavailable", "ready": 0, **payload},
                )
            else:
                self._reply(
                    200, {"status": "ok", "ready": n_ready, **payload}
                )
        elif self.path == "/metrics":
            fmt = (parse_qs(parsed.query).get("format") or [""])[0]
            if fmt == "prometheus":
                from ...training.prometheus import EXPOSITION_CONTENT_TYPE

                self._reply_bytes(
                    200,
                    router.prometheus_metrics().encode("utf8"),
                    content_type=EXPOSITION_CONTENT_TYPE,
                )
                return
            from ...training.telemetry import sanitize_json

            self._reply(200, sanitize_json(router.fleet_metrics()))
        elif self.path == "/trace":
            if router.tel is None:
                self._reply(200, {"trace": "disabled"})
                return
            from ...training.telemetry import sanitize_json

            payload = router.tel.trace.payload()
            payload["anchor"] = router.tel.trace.anchor()
            payload["role"] = "router"
            self._reply(200, sanitize_json(payload))
        elif self.path == "/admin/exemplars":
            from ...training.telemetry import sanitize_json

            self._reply(
                200,
                sanitize_json(
                    {"replicas": router.scrape_replica_exemplars()}
                ),
            )
        elif self.path == "/admin/alerts":
            if router.alerts is None:
                self._reply(200, {"alerts": "disabled"})
                return
            from ...training.telemetry import sanitize_json

            self._reply(
                200, sanitize_json({"alerts": router.alerts.states()})
            )
        else:
            self._reply(404, {"error": "not_found", "message": self.path})

    # -- POST -----------------------------------------------------------
    def do_POST(self) -> None:  # noqa: N802
        router = self.server.router
        try:
            length = int(self.headers.get("Content-Length") or 0)
        except ValueError:
            length = -1
        if length < 0 or length > MAX_BODY_BYTES:
            self.close_connection = True
            self._reply(
                400,
                {
                    "error": "bad_request",
                    "message": f"Content-Length must be 0..{MAX_BODY_BYTES}",
                },
            )
            return
        body = self.rfile.read(length)  # consume BEFORE any early reply
        registry = router.registry
        if self.path != "/v1/parse" and not (
            registry is not None and self.path.startswith("/v1/models/")
        ):
            # without a registry, /v1/models/... keeps the legacy 404
            # not_found — the typed unknown_model vocabulary only exists
            # once multi-model serving is configured
            self._reply(404, {"error": "not_found", "message": self.path})
            return
        # the router MINTS the fleet-wide request id (honoring a valid
        # client-supplied one): the same id is forwarded to the replica,
        # stamped on the router's route span, and echoed back in the
        # response header whatever the outcome — the one key that joins
        # client log, router trace, and replica trace
        request_id = clean_request_id(
            self.headers.get(REQUEST_ID_HEADER)
        ) or mint_request_id()
        if router.tel is not None:
            router.tel.request()
        # multi-model resolution at the edge (path > X-SRT-Model header
        # > manifest default): an unknown or malformed model name is a
        # typed 404 BEFORE any forward — no replica pays for it
        model_name: Optional[str] = None
        explicit_model = False
        tenant: Optional[str] = None
        if registry is not None:
            try:
                model_name, explicit_model = registry.resolve_model(
                    self.path, self.headers
                )
            except UnknownModel as e:
                if router.tel is not None:
                    router.tel.rejected(e, request_id)
                self._reply_error(e, request_id)
                return
            tenant = self.headers.get(TENANT_HEADER)
        if router.draining:
            err = Draining("fleet is draining; not admitting requests")
            if router.tel is not None:
                router.tel.rejected(err, request_id)
            self._reply_error(err, request_id)
            return
        # response cache: only when enabled does the router parse JSON —
        # the disabled path stays a pure byte proxy. Generation
        # discipline (ROADMAP 3b): a hit must match the one generation
        # every ready replica serves; while the fleet straddles
        # generations (rollout/promotion in flight) the cache is
        # bypassed entirely — a stale cached annotation must never
        # outlive a promotion
        # texts are parsed ONLY when a policy needs them (the response
        # cache, the length-affinity hint, or a conditional request to
        # validate) — otherwise the router stays a pure byte proxy
        inm = self.headers.get("If-None-Match")
        texts: Optional[List[str]] = None
        if router.cache is not None or router.length_routing:
            texts = self._texts_from(body)
        length_bucket = (
            _length_bucket_hint(texts)
            if router.length_routing and texts is not None else None
        )
        cache_key: Optional[bytes] = None
        cache_gen: Any = GENERATION_MIXED
        if router.cache is not None:
            # with a model resolved, the generation discipline runs per
            # model over the replicas hosting it — each model's entries
            # live under their own (model, generation, texts) key
            cache_gen = router.cache_generation(model_name)
            # parsing happens on BOTH generation verdicts: the bypass
            # counter must only tally requests the cache would actually
            # have served (a texts-free/malformed body skips the cache
            # on the converged path too, so it is not a "bypass"), and
            # the parse cost during a rollout window equals what the
            # converged path already pays per cacheable request
            if texts is not None:
                if cache_gen is GENERATION_MIXED:
                    # the bypass the generation discipline mandates —
                    # and a counted event, so a rollout window's
                    # cache-miss cost is attributable in /metrics
                    # rather than looking like an unexplained hit-rate
                    # dip. Counted ONLY when ready replicas actually
                    # straddle generations: an empty ready set also
                    # yields GENERATION_MIXED, but that request is
                    # about to be rejected no_replica — tallying it as
                    # a "rollout window" would inflate the counter
                    # during startup and outages with bypasses that
                    # never happened. The conditional check is bypassed
                    # on exactly the same verdict: no single generation
                    # can vouch for a client's cached body either, so
                    # If-None-Match is neither answered here nor
                    # forwarded (satellite of the PR 11 discipline).
                    if router.ready_handles():
                        router.count_cache_bypass()
                        inm = None
                else:
                    # converged fleet: the ETag is a pure function of
                    # (texts, model, generation), all known HERE — a
                    # matching If-None-Match is a body-less 304 with no
                    # forward at all, even when the cache never stored
                    # this body (the CLIENT holds it; the tag alone
                    # vouches for its freshness)
                    edge_etag = etag_for(
                        texts, model_name or "", cache_gen
                    )
                    if if_none_match_hit(inm, edge_etag):
                        router.cache.count_not_modified(model_name)
                        self._reply_not_modified(edge_etag, request_id)
                        return
                    cache_key = ResponseCache.key_for(
                        texts, model=model_name or ""
                    )
                    hit = router.cache.get(
                        cache_key, cache_gen, model=model_name
                    )
                    if hit is not None:
                        if router.tel is not None:
                            router.tel.cache_hit()
                        self._reply_bytes(
                            200, hit, request_id, etag=edge_etag
                        )
                        return
        t0 = time.perf_counter()
        span_t0 = router.tel.now() if router.tel is not None else None
        try:
            status, payload, replica_id, fwd_etag = router.forward_parse(
                body, request_id=request_id,
                model=model_name, explicit_model=explicit_model,
                tenant=tenant,
                length_bucket=length_bucket,
                if_none_match=inm,
            )
        except ServingError as e:
            if router.tel is not None:
                router.tel.rejected(e, request_id)
            self._reply_error(e, request_id)
            return
        if router.tel is not None:
            router.tel.routed(
                time.perf_counter() - t0,
                request_id=request_id,
                t0=span_t0,
                replica_id=replica_id,
            )
        if status == 200 and cache_key is not None:
            # stamp the entry with the serving replica's probe-learned
            # generation (a handle lookup, NOT a parse of the response
            # body — responses dwarf requests and the router must stay a
            # byte proxy on the hot path). Probe freshness caveat: a
            # swap landing between the last probe and this forward can
            # stamp a NEWER body with the old generation — the entry
            # then serves the new weights' annotations until the next
            # probe drops it, and the promotion flush clears any such
            # residue; it can never serve STALE (pre-promotion)
            # annotations, which is the contract that matters.
            serving = next(
                (
                    h for h in router.replicas()
                    if h.replica_id == replica_id
                ),
                None,
            )
            if serving is None:
                gen = cache_gen
            elif model_name is not None:
                # per-model stamp: the serving replica's probe-learned
                # generation FOR THIS MODEL (its fleet-level generation
                # may belong to a different resident model's rollout)
                gen = (
                    serving.resident_models.get(model_name) or {}
                ).get("generation")
            else:
                gen = serving.generation
            router.cache.put(cache_key, payload, gen)
        if status == 304:
            # a replica's own conditional check fired (the cache-off or
            # registry-less edge still honors If-None-Match end to end);
            # counted in the cache ledger when one exists — the 304
            # share must be one number however it was answered
            if router.cache is not None:
                router.cache.count_not_modified(model_name)
            self._reply_not_modified(fwd_etag, request_id)
            return
        self._reply_bytes(status, payload, request_id, etag=fwd_etag)

    @staticmethod
    def _texts_from(body: bytes) -> Optional[List[str]]:
        try:
            payload = json.loads(body or b"{}")
        except ValueError:
            return None
        texts = payload.get("texts") if isinstance(payload, dict) else None
        if isinstance(texts, list) and texts and all(
            isinstance(t, str) for t in texts
        ):
            return texts
        return None
