"""Online inference engine: device-resident params, a compiled-program
warmup sweep over the (B, T) padding buckets, and ONE dispatch thread
executing coalesced batches through the ``predict_docs`` path.

Why one thread: under jit every distinct (B, T) is one cached XLA
program; a single dispatcher serializes device access (no interpreter-
level contention on the params or the jit cache) while the
ThreadingHTTPServer handler threads do the embarrassingly parallel host
work (tokenization, JSON). That is the same host/device split the
training loop uses (collation pool feeds one device thread,
training/collate_pool.py) — serving reuses the split rather than
inventing a second concurrency model.

Warmup (:func:`warmup_buckets`) compiles the forward program for every
bucket shape the admission rules can produce, so steady-state serving
never pays a compile on a live request — the same reasoning as the
trainer's shape bucketing (SURVEY.md §7), and the bucket tables are the
trainer's own (``training/batcher.py``). ``bench.py --serving`` imports
the same sweep, so load tests exercise exactly the warmed shapes.

Telemetry is a nullable :class:`ServingTelemetry` facade over
``training/telemetry.py``'s registry + trace buffer: request-latency
histograms (p50/p95/p99), queue-depth and batch-occupancy gauges,
reject/timeout counters, per-request and per-batch trace spans. When
disabled the engine holds None and makes ZERO telemetry calls — the
contract the training loop enforces, test-enforced here too.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..training.batcher import (
    DEFAULT_LENGTH_BUCKETS,
    bucket_batch_size,
    bucket_length,
)
from ..training.resilience import log_event
from .batcher import (
    DeadlineExceeded,
    DynamicBatcher,
    Draining,
    RequestTooLarge,
    ServeRequest,
    ServingError,
    SwapFailed,
)

__all__ = [
    "ServingTelemetry",
    "InferenceEngine",
    "warmup_buckets",
    "SERVING_DEFAULTS",
]

# One place for the serving knob defaults: the CLI, the bench load specs,
# and the tests read these — a bench that "agrees with serve" must not
# restate numbers that can drift. ``batching`` defaults to continuous
# admission (the window discipline survives behind the knob for A/Bs and
# for operators who want to trade latency for bigger batches);
# ``max_wait_s`` only applies in window mode. ``precision`` is the
# serving overlay policy (serving/overlay.py — "auto" arms bf16 on
# accelerators only). ``slo_window_s`` is the sliding window the SLO
# percentiles are additionally reported over (recent load, not lifetime).
SERVING_DEFAULTS: Dict[str, Any] = {
    "max_batch_docs": 16,
    "max_wait_s": 0.005,
    "max_queue_docs": 128,
    "timeout_s": 10.0,
    "max_doc_len": 64,
    "batching": "continuous",
    "precision": "auto",
    "slo_window_s": 30.0,
}


def warmup_buckets(
    max_batch_docs: int,
    max_doc_len: int,
    length_buckets: Sequence[int] = DEFAULT_LENGTH_BUCKETS,
) -> List[Tuple[int, int]]:
    """The (B, T) grid admission can produce: batch buckets from the
    trainer's ``bucket_batch_size`` chain up to the padded max batch,
    and EVERY length bucket ``bucket_length`` can emit for a doc of
    1..max_doc_len tokens — table buckets up to the cap plus, beyond the
    table's top, each multiple of the top bucket (that is
    ``bucket_length``'s overflow rule). Completeness is the contract: a
    live request must never meet a shape this sweep did not compile.
    Shared by the engine's warmup sweep and ``bench.py --serving`` so
    warmup and load tests agree on shapes by construction."""
    b_cap = bucket_batch_size(int(max_batch_docs))
    t_cap = bucket_length(int(max_doc_len), length_buckets)
    bs: List[int] = []
    b = 1
    while b <= b_cap:
        bs.append(bucket_batch_size(b))
        b = bucket_batch_size(b) + 1
    top = length_buckets[-1]
    ts = {b for b in length_buckets if b <= t_cap}
    m = 2 * top
    while m <= t_cap:  # overflow region: multiples of the top bucket
        ts.add(m)
        m += top
    ts.add(t_cap)
    return [(b, t) for b in bs for t in sorted(ts)]


class ServingTelemetry:
    """Serving's SLO surface over the shared registry/trace primitives.

    Instruments (resolved once, observed per request/batch):

    * ``request_latency_seconds`` histogram — admission to completion,
      the SLO number; p50/p95/p99 come from the shared nearest-rank
      percentile convention (one implementation, telemetry.py). The
      latency histogram also keeps a ``slo_window_s`` sliding TIME
      window: the ``slo_window`` snapshot block reports p50/p95/p99
      over the last N seconds only, so a control loop (the fleet
      autoscaler) sees a fresh load spike instead of the spike diluted
      across the whole run's samples.
    * ``queue_wait_seconds`` histogram — admission to batch-assembly
      pickup (time-in-queue).
    * ``dispatch_wait_seconds`` histogram — admission to the batch being
      handed to the device (time-to-first-dispatch). The gap between
      this and queue_wait is the coalescing-window tax; continuous
      batching exists to erase it, and this pair is the per-request
      proof.
    * ``batch_occupancy`` histogram + ``last_batch_occupancy`` gauge —
      docs per dispatched device batch; occupancy ≈ 1 under load means
      coalescing is broken (N serial batches of 1).
    * ``queue_depth`` gauge, ``requests``/``docs``/``batches`` counters,
      and one counter per typed reject (``rejected_queue_full``,
      ``rejected_draining``, ``deadline_exceeded``, ``errors``).
    * trace: one span per batch (cat ``serve``) with occupancy/B/T args,
      one span per request (admission → completion) on the caller's
      track, an instant per reject.
    """

    def __init__(
        self,
        *,
        clock: Callable[[], float] = time.perf_counter,
        process_index: int = 0,
        trace_max_events: int = 100_000,
        slo_window_s: float = SERVING_DEFAULTS["slo_window_s"],
        exemplar_capacity: int = 64,
    ) -> None:
        from ..training.telemetry import (
            LATENCY_BUCKETS,
            OCCUPANCY_BUCKETS,
            MetricsRegistry,
            TraceBuffer,
        )

        self.registry = MetricsRegistry(clock=clock)
        self.trace = TraceBuffer(
            clock=clock, pid=int(process_index), max_events=trace_max_events
        )
        # host-resource truth (docs/OBSERVABILITY.md "Host resources &
        # the run ledger"): inside the facade so telemetry-off serving
        # constructs no sampler; rate-limited internally, so the
        # observer loop, /metrics scrapes and router polls share one
        # cached /proc read
        from ..training.hoststats import ProcessSampler

        self.hoststats = ProcessSampler(clock=clock)
        # the SLO histograms carry cumulative Prometheus bucket tables
        # (telemetry.py LATENCY_BUCKETS — shared repo-wide so replica
        # series sum exactly at the router/scraper) on top of the
        # percentile sample ring
        self._latency = self.registry.histogram(
            "request_latency_seconds", 2048, window_s=slo_window_s or None,
            buckets=LATENCY_BUCKETS,
        )
        self._queue_wait = self.registry.histogram(
            "queue_wait_seconds", 2048, buckets=LATENCY_BUCKETS
        )
        self._dispatch_wait = self.registry.histogram(
            "dispatch_wait_seconds", 2048, buckets=LATENCY_BUCKETS
        )
        self._occupancy = self.registry.histogram(
            "batch_occupancy", 1024, buckets=OCCUPANCY_BUCKETS
        )
        self._queue_depth = self.registry.gauge("queue_depth")
        self._last_occ = self.registry.gauge("last_batch_occupancy")
        self._requests = self.registry.counter("requests")
        self._docs = self.registry.counter("docs")
        self._batches = self.registry.counter("batches")
        # padding tax (data plane, docs/SERVING.md): tokens the device
        # actually computed vs tokens the bucket shape forced it to pad
        # to — pad share = pad / (pad + real) is the number length-aware
        # routing exists to reduce, so it must be measured where the
        # shape is chosen (dispatch assembly), not estimated downstream
        self._pad_tokens = self.registry.counter("pad_tokens")
        self._real_tokens = self.registry.counter("real_tokens")
        # conditional responses: requests answered 304 from the
        # ETag/If-None-Match check — inference AND serialization skipped
        self._not_modified = self.registry.counter("not_modified")
        self._rej_full = self.registry.counter("rejected_queue_full")
        self._rej_drain = self.registry.counter("rejected_draining")
        self._rej_quota = self.registry.counter("rejected_quota")
        self._deadline = self.registry.counter("deadline_exceeded")
        self._errors = self.registry.counter("errors")
        # hot-swap instruments (serving/live): how often the resident
        # generation flipped, and what each swap cost — staging (load +
        # overlay + device put, off the dispatch path) and the flip
        # itself (the only part a dispatch boundary can observe) are
        # timed SEPARATELY, because "swaps are cheap" is only honest if
        # the flip — the part that could stall traffic — is the cheap
        # part
        self._swaps = self.registry.counter("swaps")
        self._rollbacks = self.registry.counter("rollbacks")
        self._swap_total = self.registry.histogram("swap_seconds", 256)
        self._swap_stage = self.registry.histogram("swap_stage_seconds", 256)
        self._swap_flip = self.registry.histogram("swap_flip_seconds", 256)
        self._generation = self.registry.gauge("serving_generation")
        # slow-request exemplars (docs/OBSERVABILITY.md): a bounded ring
        # of p99-outlier requests with their per-stage breakdown, keyed
        # by request id — the bridge from "p99 got worse" to "THIS
        # request spent 80ms waiting for dispatch". The threshold is the
        # latency ring's p99, refreshed every _EXEMPLAR_REFRESH
        # completions (sorting 2048 samples per request would be hot-path
        # work for a diagnostic).
        self._exemplars: "deque" = deque(maxlen=int(exemplar_capacity))
        self._exemplar_count = self.registry.counter("slow_exemplars")
        self._exemplar_lock = threading.Lock()
        self._exemplar_seen = 0
        self._exemplar_threshold: Optional[float] = None

    _EXEMPLAR_REFRESH = 64
    _EXEMPLAR_MIN_SAMPLES = 100

    def now(self) -> float:
        return self.trace.now()

    def request_admitted(self, n_docs: int, queue_depth: int) -> None:
        self._requests.inc()
        self._docs.inc(n_docs)
        self._queue_depth.set(queue_depth)

    def request_rejected(
        self, error: ServingError, request_id: Optional[str] = None
    ) -> None:
        if isinstance(error, Draining):
            self._rej_drain.inc()
        elif isinstance(error, DeadlineExceeded):
            self._deadline.inc()
        elif isinstance(error, ServingError) and error.code == "queue_full":
            self._rej_full.inc()
        elif isinstance(error, ServingError) and error.code == "quota_exceeded":
            self._rej_quota.inc()
        else:
            self._errors.inc()
        args = {"error": str(error)}
        if request_id is not None:
            args["request_id"] = request_id
        self.trace.add_instant(f"reject:{error.code}", cat="serve", args=args)

    def request_completed(
        self,
        *,
        latency_s: float,
        queue_wait_s: Optional[float],
        t0: Optional[float],
        error: Optional[ServingError],
        dispatch_wait_s: Optional[float] = None,
        request_id: Optional[str] = None,
    ) -> None:
        if error is not None:
            self.request_rejected(error, request_id)
        else:
            self._latency.observe(latency_s)
            if queue_wait_s is not None:
                self._queue_wait.observe(queue_wait_s)
            if dispatch_wait_s is not None:
                self._dispatch_wait.observe(dispatch_wait_s)
        if t0 is not None:
            args: Dict[str, Any] = {
                "error": error.code if error is not None else None
            }
            if request_id is not None:
                args["request_id"] = request_id
            self.trace.add_span(
                "request",
                t0,
                max(self.now() - t0, 0.0),
                cat="serve",
                args=args,
            )

    def conditional_hit(self) -> None:
        self._not_modified.inc()

    def batch_span(
        self,
        occupancy: int,
        B: int,
        T: int,
        request_ids: Optional[List[str]] = None,
        real_tokens: Optional[int] = None,
    ):
        self._batches.inc()
        self._occupancy.observe(occupancy)
        self._last_occ.set(occupancy)
        if real_tokens is not None:
            # B*T is what the device computes; real is what was asked for
            self._real_tokens.inc(real_tokens)
            self._pad_tokens.inc(max(B * T - real_tokens, 0))
        kwargs: Dict[str, Any] = {"occupancy": occupancy, "B": B, "T": T}
        if request_ids:
            # a batch holds at most max_batch_docs requests — small
            # enough to name them all, making every dispatch span
            # attributable to the requests it served
            kwargs["request_ids"] = request_ids
        return self.trace.span("serve_batch", cat="serve", **kwargs)

    # -- slow-request exemplars ----------------------------------------
    def consider_exemplar(
        self,
        *,
        request_id: str,
        latency_s: float,
        stages: Dict[str, Optional[float]],
        **meta: Any,
    ) -> bool:
        """Record this completed request in the exemplar ring iff it is
        a p99 outlier (latency STRICTLY ABOVE the latency ring's p99,
        once at least ``_EXEMPLAR_MIN_SAMPLES`` completions exist —
        before that there is no tail to be an outlier of). ``stages`` is
        the per-stage breakdown (queue_wait/dispatch_wait/device/
        serialize seconds, None = stage unobserved). Returns True when
        recorded."""
        with self._exemplar_lock:
            self._exemplar_seen += 1
            if (
                self._exemplar_threshold is None
                or self._exemplar_seen % self._EXEMPLAR_REFRESH == 0
            ):
                if self._latency.count >= self._EXEMPLAR_MIN_SAMPLES:
                    self._exemplar_threshold = self._latency.percentile(0.99)
            threshold = self._exemplar_threshold
            # strictly ABOVE p99: in a flat distribution p99 equals every
            # sample, and "everything is an outlier" is no exemplar at all
            if threshold is None or latency_s <= threshold:
                return False
            self._exemplars.append(
                {
                    "request_id": request_id,
                    "latency_s": round(float(latency_s), 6),
                    "t": round(self.now(), 6),
                    "stages": {
                        k: (round(float(v), 6) if v is not None else None)
                        for k, v in stages.items()
                    },
                    **meta,
                }
            )
        self._exemplar_count.inc()
        return True

    def exemplars(self) -> Dict[str, Any]:
        """The /admin/exemplars payload: the ring (newest last) plus the
        threshold that admitted its members."""
        with self._exemplar_lock:
            return {
                "threshold_s": self._exemplar_threshold,
                "count": len(self._exemplars),
                "exemplars": list(self._exemplars),
            }

    def set_queue_depth(self, depth: int) -> None:
        self._queue_depth.set(depth)

    def swap_completed(
        self,
        *,
        stage_s: float,
        flip_s: float,
        t0: Optional[float],
        generation: Optional[int],
        rollback: bool = False,
    ) -> None:
        """One resident-generation flip: counters, the stage/flip/total
        histograms, the generation gauge, and two trace spans (staging
        then flip, back to back on the swapping thread's track)."""
        self._swaps.inc()
        if rollback:
            self._rollbacks.inc()
        self._swap_stage.observe(stage_s)
        self._swap_flip.observe(flip_s)
        self._swap_total.observe(stage_s + flip_s)
        if generation is not None:
            self._generation.set(float(generation))
        if t0 is not None:
            args = {"generation": generation, "rollback": rollback}
            self.trace.add_span(
                "swap_stage", t0, max(stage_s, 0.0), cat="serve", args=args
            )
            self.trace.add_span(
                "swap_flip", t0 + stage_s, max(flip_s, 0.0), cat="serve",
                args=args,
            )

    def snapshot(self) -> Dict[str, Any]:
        """The /metrics payload: registry snapshot + the SLO percentiles.
        ``slo`` keeps the sample-ring convention (last 2048 requests);
        ``slo_window`` re-states the latency percentiles over the last
        ``slo_window_s`` SECONDS only — the block the autoscaler reads,
        because a run-lifetime-ish ring dilutes a fresh spike exactly
        when the control loop needs to react to it (fake-clock
        regression-tested in test_telemetry.py)."""
        snap = self.registry.snapshot()
        snap["slo"] = {
            "request_latency_p50": self._latency.percentile(0.50),
            "request_latency_p95": self._latency.percentile(0.95),
            "request_latency_p99": self._latency.percentile(0.99),
            "batch_occupancy_p50": self._occupancy.percentile(0.50),
            "dispatch_wait_p50": self._dispatch_wait.percentile(0.50),
            "dispatch_wait_p99": self._dispatch_wait.percentile(0.99),
        }
        win = self._latency.window_snapshot()
        if win is not None:
            snap["slo_window"] = {
                "window_s": win["window_s"],
                "samples": win["samples"],
                "request_latency_p50": win["p50"],
                "request_latency_p95": win["p95"],
                "request_latency_p99": win["p99"],
            }
        # host truth rides every snapshot: the server's JSON /metrics,
        # the observer tick (recorder ring + process.* alert rules) and
        # the router's replica polls all read this one key
        snap["process"] = self.hoststats.sample()
        return snap


class InferenceEngine:
    """Owns the pipeline + device params and the dispatch thread.

    ``submit_texts``/``submit_docs`` run on caller (HTTP handler)
    threads: tokenize, admission-check, enqueue, block until the
    dispatch thread completes the request (or a typed error says why
    not). The dispatch thread assembles batches via
    :class:`DynamicBatcher` (continuous slot-based admission by default;
    the window discipline behind ``batching="window"``) and executes ONE
    ``predict_docs`` call per batch with the padded bucket pinned
    explicitly — exactly a warmed shape. The params it dispatches are
    ``serve_params`` — the precision overlay's output (f32 untouched, or
    a bf16 trunk overlay on accelerators; serving/overlay.py) — and
    ``overlay.label`` is the honest precision story every surface
    reports.
    """

    def __init__(
        self,
        nlp,
        *,
        max_batch_docs: int = SERVING_DEFAULTS["max_batch_docs"],
        max_wait_s: float = SERVING_DEFAULTS["max_wait_s"],
        max_queue_docs: int = SERVING_DEFAULTS["max_queue_docs"],
        timeout_s: float = SERVING_DEFAULTS["timeout_s"],
        max_doc_len: int = SERVING_DEFAULTS["max_doc_len"],
        batching: str = SERVING_DEFAULTS["batching"],
        precision: str = SERVING_DEFAULTS["precision"],
        telemetry: Optional[ServingTelemetry] = None,
        clock: Callable[[], float] = time.monotonic,
        class_weights: Optional[Dict[str, float]] = None,
    ) -> None:
        if nlp.params is None:
            raise ValueError(
                "serving needs an initialized/loaded pipeline (params are "
                "None — load a trained model with Pipeline.from_disk)"
            )
        self.nlp = nlp
        self.max_batch_docs = int(max_batch_docs)
        self.max_doc_len = int(max_doc_len)
        self.timeout_s = float(timeout_s)
        self.tel = telemetry
        self.clock = clock
        self.batching = batching
        # class_weights arms weighted fair queuing across SLO classes
        # (multi-tenant serving); None keeps the legacy single FIFO
        self.batcher = DynamicBatcher(
            max_queue_docs=max_queue_docs,
            max_batch_docs=max_batch_docs,
            max_wait_s=max_wait_s,
            mode=batching,
            clock=clock,
            class_weights=class_weights,
        )
        # precision overlay, applied ONCE at construction: every dispatch
        # (warmup sweep included, so warmed programs match live traffic's
        # param dtypes) consumes self.serve_params, never nlp.params
        # directly. overlay.resolved/label are the honest story /healthz
        # and the bench records carry.
        from .overlay import build_serving_overlay

        self.precision = precision
        self.overlay = build_serving_overlay(nlp, precision)
        self.serve_params = self.overlay.params
        # live hot-swap state (serving/live, docs/SERVING.md "Continuous
        # learning"): the f32 master tree the overlay was built from,
        # the generation stamp it came from (None = the model as loaded
        # from disk), and ONE previous resident kept for instant
        # rollback. _flip_lock makes (serve_params, overlay, generation)
        # one atomic unit: the dispatch thread snapshots all three at a
        # batch boundary, so no batch ever runs mixed weights or carries
        # another generation's stamp.
        self._master_params = nlp.params
        self.serving_generation: Optional[int] = None
        self.swap_count = 0
        self.rollback_count = 0
        self._previous: Optional[Tuple[Optional[int], Any, Any]] = None
        self._swap_lock = threading.Lock()   # serializes swap/rollback
        self._flip_lock = threading.Lock()   # guards the resident unit
        self._thread: Optional[threading.Thread] = None
        self._state_lock = threading.Lock()
        self._idle = threading.Condition(self._state_lock)
        self._active_batches = 0
        self._started = False
        # readiness is distinct from started-ness: the HTTP listener may
        # be up (so a router can probe /healthz and learn the port) while
        # the warmup sweep is still compiling. Until ready, traffic gets
        # a typed 503 NotReady — never a live mid-warmup compile.
        self.ready = False
        self.warmed: List[Tuple[int, int]] = []

    # -- lifecycle ------------------------------------------------------
    def warmup(self) -> List[Tuple[int, int]]:
        """Compile the forward program for every admissible bucket shape
        (synthetic docs, one ``predict_docs`` per (B, T)); returns the
        swept grid. Runs on the calling thread BEFORE dispatch starts,
        so the jit cache is never touched concurrently."""
        from ..pipeline.doc import Doc

        grid = warmup_buckets(
            self.max_batch_docs, self.max_doc_len, self.nlp.length_buckets
        )
        for B, T in grid:
            docs = [Doc(words=["the"] * T) for _ in range(B)]
            self.nlp.predict_docs(
                docs, params=self.serve_params,
                batch_size=B, pad_batch_to=B, pad_len_to=T,
            )
        self.warmed = grid
        return grid

    def start(self, *, warmup: bool = True) -> "InferenceEngine":
        if self._started:
            return self
        if warmup:
            self.warmup()
        self._started = True
        self._thread = threading.Thread(
            target=self._dispatch_loop, name="serve-dispatch", daemon=True
        )
        self._thread.start()
        self.ready = True  # last: readiness implies warmed AND dispatching
        return self

    # -- submission (handler threads) -----------------------------------
    def submit_texts(
        self,
        texts: Sequence[str],
        timeout_s: Optional[float] = None,
        request_id: Optional[str] = None,
        klass: str = "default",
    ) -> ServeRequest:
        docs = [self.nlp.tokenizer(t) for t in texts]
        return self.submit_docs(
            docs, timeout_s=timeout_s, request_id=request_id, klass=klass
        )

    def submit_docs(
        self,
        docs: List[Any],
        timeout_s: Optional[float] = None,
        request_id: Optional[str] = None,
        klass: str = "default",
    ) -> ServeRequest:
        timeout = self.timeout_s if timeout_s is None else float(timeout_s)
        too_long = [i for i, d in enumerate(docs) if len(d) > self.max_doc_len]
        if too_long:
            err: ServingError = RequestTooLarge(
                f"doc(s) {too_long} exceed max_doc_len={self.max_doc_len} "
                "tokens (the warmed shape cap) — split or truncate"
            )
            if self.tel is not None:
                self.tel.request_rejected(err, request_id)
            raise err
        now = self.clock()
        req = ServeRequest(
            docs, deadline=now + timeout, enqueued_at=now,
            request_id=request_id, klass=klass,
        )
        t0 = self.tel.now() if self.tel is not None else None
        try:
            self.batcher.submit(req)
        except ServingError as e:
            if self.tel is not None:
                self.tel.request_rejected(e, req.request_id)
            raise
        if self.tel is not None:
            self.tel.request_admitted(len(docs), self.batcher.queue_depth())
        # +grace so the dispatch thread (which owns deadline accounting)
        # is the one that times the request out, not this wait
        req.wait(timeout + 1.0)
        latency = self.clock() - req.enqueued_at
        req.latency_s = latency
        queue_wait = (
            req.started_at - req.enqueued_at
            if req.started_at is not None
            else None
        )
        dispatch_wait = (
            req.dispatched_at - req.enqueued_at
            if req.dispatched_at is not None
            else None
        )
        if not req.done:
            err = DeadlineExceeded(
                f"request not completed within {timeout:.3f}s"
            )
            if self.tel is not None:
                self.tel.request_completed(
                    latency_s=latency, queue_wait_s=queue_wait, t0=t0,
                    error=err, request_id=req.request_id,
                )
            raise err
        if self.tel is not None:
            self.tel.request_completed(
                latency_s=latency,
                queue_wait_s=queue_wait,
                t0=t0,
                error=req.error,
                dispatch_wait_s=dispatch_wait,
                request_id=req.request_id,
            )
        if req.error is not None:
            raise req.error
        return req  # docs annotated in place; batch_info says how it ran

    # -- dispatch (one thread) ------------------------------------------
    def _dispatch_loop(self) -> None:
        while True:
            batch = self.batcher.next_batch()
            if batch is None:
                return
            if not batch:
                continue
            with self._state_lock:
                self._active_batches += 1
            try:
                self._run_batch(batch)
            finally:
                with self._state_lock:
                    self._active_batches -= 1
                    self._idle.notify_all()

    def _run_batch(self, requests: List[ServeRequest]) -> None:
        docs = [d for r in requests for d in r.docs]
        n = len(docs)
        B = bucket_batch_size(n)
        T = bucket_length(
            max((len(d) for d in docs), default=1), self.nlp.length_buckets
        )
        # the dispatch boundary: snapshot the resident (params,
        # generation) unit ONCE, under the flip lock. A swap that lands
        # after this point is observed by the NEXT batch; this batch
        # runs entirely on one tree and is stamped with that tree's
        # generation — the no-mixed-weights contract (test-enforced).
        with self._flip_lock:
            serve_params = self.serve_params
            generation = self.serving_generation
        dispatched_at = self.clock()  # assembly over, handed to the device
        for r in requests:
            r.dispatched_at = dispatched_at
        request_ids = [r.request_id for r in requests]
        info = {"occupancy": n, "B": B, "T": T, "generation": generation}
        real_tokens = sum(len(d) for d in docs)
        t_dev = self.clock()
        try:
            if self.tel is not None:
                with self.tel.batch_span(
                    n, B, T, request_ids, real_tokens=real_tokens
                ):
                    self.nlp.predict_docs(
                        docs, params=serve_params,
                        batch_size=n, pad_batch_to=B, pad_len_to=T,
                    )
                self.tel.set_queue_depth(self.batcher.queue_depth())
            else:
                self.nlp.predict_docs(
                    docs, params=serve_params,
                    batch_size=n, pad_batch_to=B, pad_len_to=T,
                )
        except Exception as e:  # a poisoned batch must not kill the server
            log_event(
                "serve-batch-failed",
                f"dispatch of {n} docs (B={B}, T={T}) failed: "
                f"{type(e).__name__}: {e}",
                occupancy=n,
                request_ids=request_ids,
            )
            err = ServingError(f"inference failed: {type(e).__name__}: {e}")
            for r in requests:
                r.batch_info = dict(info)
                r.complete(err)
            return
        # the device stage of the per-request breakdown (exemplars):
        # predict wall time for the batch this request rode in — on the
        # request, not batch_info (the response body stays deterministic)
        dev_s = round(self.clock() - t_dev, 6)
        for r in requests:
            r.device_s = dev_s
            r.batch_info = dict(info)
            r.complete()

    # -- live hot-swap (serving/live; docs/SERVING.md) -------------------
    @staticmethod
    def _tree_spec(tree: Any, prefix: str = "") -> Dict[str, Tuple]:
        """(path -> (shape, dtype)) without materializing anything — the
        compatibility fingerprint a candidate tree must match for the
        warmed (B, T) programs (shape- AND dtype-keyed in the jit cache)
        to keep applying after a flip."""
        out: Dict[str, Tuple] = {}
        if isinstance(tree, dict):
            for k in sorted(tree):
                out.update(
                    InferenceEngine._tree_spec(tree[k], f"{prefix}/{k}")
                )
        else:
            out[prefix] = (
                tuple(getattr(tree, "shape", ())),
                str(getattr(tree, "dtype", type(tree).__name__)),
            )
        return out

    def _stage(self, params: Any):
        """Build the candidate's precision overlay (same requested knob,
        fresh resolution — honest label preserved) and force it onto the
        device NOW, so the flip itself transfers nothing. Runs on the
        swapping thread; the dispatch thread keeps serving the current
        resident throughout. Raises :class:`SwapFailed` on any tree
        mismatch — a candidate that would void the warmed-program
        contract (or silently re-shape the model) is refused, and the
        engine keeps serving what it was serving."""
        import jax

        from .overlay import build_params_overlay

        want = self._tree_spec(self._master_params)
        got = self._tree_spec(params)
        if want != got:
            missing = sorted(set(want) - set(got))[:4]
            extra = sorted(set(got) - set(want))[:4]
            changed = sorted(
                k for k in set(want) & set(got) if want[k] != got[k]
            )[:4]
            raise SwapFailed(
                "candidate param tree does not match the resident one "
                f"(missing: {missing}, unexpected: {extra}, reshaped/"
                f"retyped: {changed}) — swap refused, still serving "
                f"generation {self.serving_generation}"
            )
        overlay = build_params_overlay(params, self.precision)
        try:
            jax.block_until_ready(jax.device_put(overlay.params))
        except Exception:  # older jax without pytree support here:
            # arrays will transfer lazily on the first post-flip
            # dispatch instead — correct, just less instant
            pass
        return overlay

    def swap_params(
        self, params: Any, generation: int, *, source: str = "api"
    ) -> Dict[str, Any]:
        """Hot-swap the resident param tree to ``params`` (a verified
        checkpoint generation's f32 masters). Staging — overlay build +
        device put — happens off the dispatch path; the flip is an
        O(pointers) exchange at a dispatch boundary (the single dispatch
        thread snapshots the resident unit once per batch, so no
        in-flight batch ever sees mixed weights). The displaced resident
        stays staged for instant :meth:`rollback`. Returns a summary
        dict; raises :class:`SwapFailed` on an incompatible tree."""
        t_wall = self.clock()
        t0 = self.tel.now() if self.tel is not None else None
        with self._swap_lock:
            overlay = self._stage(params)
            stage_s = self.clock() - t_wall
            t_flip = self.clock()
            with self._flip_lock:
                prev = (
                    self.serving_generation, self.overlay,
                    self._master_params,
                )
                self.overlay = overlay
                self.serve_params = overlay.params
                self._master_params = params
                self.serving_generation = int(generation)
                self.swap_count += 1
                self._previous = prev
            flip_s = self.clock() - t_flip
        if self.tel is not None:
            self.tel.swap_completed(
                stage_s=stage_s, flip_s=flip_s, t0=t0,
                generation=int(generation),
            )
        log_event(
            "serve-swap",
            f"hot-swapped serving params to generation {generation} "
            f"(from {prev[0]}; staged {stage_s * 1e3:.1f} ms, flip "
            f"{flip_s * 1e3:.3f} ms, precision {overlay.label}; "
            f"source {source})",
            level=logging.INFO,
            generation=int(generation),
            previous=prev[0],
            stage_s=round(stage_s, 6),
            flip_s=round(flip_s, 6),
            source=source,
        )
        return {
            "generation": int(generation),
            "previous_generation": prev[0],
            "swap_count": self.swap_count,
            "stage_s": stage_s,
            "flip_s": flip_s,
            "precision_label": overlay.label,
        }

    def rollback(self) -> Dict[str, Any]:
        """Instant rollback to the previous RESIDENT generation: its
        overlay never left staging, so this is a pure flip (no load, no
        digest work, no device transfer). The displaced generation
        becomes the new previous — rollback is its own inverse. Raises
        :class:`SwapFailed` when no previous resident exists."""
        t0 = self.tel.now() if self.tel is not None else None
        with self._swap_lock:
            if self._previous is None:
                raise SwapFailed(
                    "no previous resident generation to roll back to "
                    f"(serving generation {self.serving_generation}, "
                    f"{self.swap_count} swap(s) so far)"
                )
            t_flip = self.clock()
            with self._flip_lock:
                displaced = (
                    self.serving_generation, self.overlay,
                    self._master_params,
                )
                gen, overlay, master = self._previous
                self.overlay = overlay
                self.serve_params = overlay.params
                self._master_params = master
                self.serving_generation = gen
                self.swap_count += 1
                self.rollback_count += 1
                self._previous = displaced
            flip_s = self.clock() - t_flip
        if self.tel is not None:
            self.tel.swap_completed(
                stage_s=0.0, flip_s=flip_s, t0=t0, generation=gen,
                rollback=True,
            )
        log_event(
            "serve-rollback",
            f"rolled serving params back to generation {gen} (from "
            f"{displaced[0]}; flip {flip_s * 1e3:.3f} ms)",
            generation=gen,
            displaced=displaced[0],
            flip_s=round(flip_s, 6),
        )
        return {
            "generation": gen,
            "displaced_generation": displaced[0],
            "swap_count": self.swap_count,
            "flip_s": flip_s,
            "precision_label": self.overlay.label,
        }

    # -- drain / stop ----------------------------------------------------
    def drain(self, timeout_s: float = 30.0) -> bool:
        """Graceful drain: stop admitting, finish every queued and
        in-flight batch, stop the dispatch thread. Returns True when the
        queue fully drained within the timeout (False = gave up; callers
        escalate to :meth:`stop`)."""
        self.batcher.begin_drain()
        deadline = time.monotonic() + float(timeout_s)
        with self._idle:
            while (
                self.batcher.queue_depth() > 0 or self._active_batches > 0
            ):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._idle.wait(timeout=min(remaining, 0.1))
        self.stop()
        return True

    def stop(self) -> None:
        """Hard stop: close the batcher (failing anything still queued)
        and join the dispatch thread."""
        self.ready = False
        self.batcher.close()
        self.batcher.fail_all_queued(Draining("server shut down"))
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        self._started = False
