"""Online serving subsystem: dynamic batching engine + HTTP front-end.

Three layers (see each module's docstring for the contracts):

* :mod:`.batcher` — bounded admission queue, size-or-deadline
  micro-batch coalescing, typed rejects;
* :mod:`.engine` — device-resident params, (B, T) bucket warmup sweep,
  the single dispatch thread, SLO telemetry facade;
* :mod:`.server` — stdlib HTTP JSON API (``/v1/parse``, ``/healthz``,
  ``/metrics``) and SIGTERM graceful drain.

Entry point: ``spacy-ray-tpu serve <model_dir>`` (cli.py).
"""

from .batcher import (
    DeadlineExceeded,
    Draining,
    DynamicBatcher,
    NotReady,
    QueueFull,
    RequestTooLarge,
    ServeRequest,
    ServingError,
)
from .engine import (
    InferenceEngine,
    SERVING_DEFAULTS,
    ServingTelemetry,
    warmup_buckets,
)
from .server import Server, ServingHTTPServer

__all__ = [
    "ServingError",
    "QueueFull",
    "Draining",
    "NotReady",
    "DeadlineExceeded",
    "RequestTooLarge",
    "ServeRequest",
    "DynamicBatcher",
    "InferenceEngine",
    "ServingTelemetry",
    "SERVING_DEFAULTS",
    "warmup_buckets",
    "Server",
    "ServingHTTPServer",
]
