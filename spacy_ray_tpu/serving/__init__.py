"""Online serving subsystem: dynamic batching engine + HTTP front-end.

Three layers (see each module's docstring for the contracts):

* :mod:`.batcher` — bounded admission queue, continuous slot-based or
  size-or-deadline window batch assembly, typed rejects;
* :mod:`.engine` — device-resident params, (B, T) bucket warmup sweep,
  the single dispatch thread, SLO telemetry facade;
* :mod:`.overlay` — serving precision policy: bf16 trunk overlays of
  the f32 param tree, probe-gated/auto-armed with honest labels;
* :mod:`.server` — stdlib HTTP JSON API (``/v1/parse``, ``/healthz``,
  ``/metrics``, ``/admin/swap``, ``/admin/rollback``) and SIGTERM
  graceful drain;
* :mod:`.live` — continuous learning: checkpoint watcher, hot-swap
  orchestration, canary guard (docs/SERVING.md "Continuous learning");
* :mod:`.tracecollect` — cross-process trace collector: merges the
  router's, every replica's, and the trainer's Perfetto buffers into
  one timeline via /healthz clock anchors (docs/OBSERVABILITY.md
  "Distributed request tracing").

Entry point: ``spacy-ray-tpu serve <model_dir>`` (cli.py).
"""

from .batcher import (
    DeadlineExceeded,
    Draining,
    DynamicBatcher,
    NotReady,
    QueueFull,
    REQUEST_ID_HEADER,
    RequestTooLarge,
    ServeRequest,
    ServingError,
    SwapFailed,
    clean_request_id,
    mint_request_id,
)
from .engine import (
    InferenceEngine,
    SERVING_DEFAULTS,
    ServingTelemetry,
    warmup_buckets,
)
from .overlay import (
    OverlayResult,
    PRECISION_CHOICES,
    build_params_overlay,
    build_serving_overlay,
    resolve_precision,
)
from .server import Server, ServingHTTPServer

__all__ = [
    "ServingError",
    "QueueFull",
    "Draining",
    "NotReady",
    "DeadlineExceeded",
    "RequestTooLarge",
    "SwapFailed",
    "ServeRequest",
    "DynamicBatcher",
    "REQUEST_ID_HEADER",
    "mint_request_id",
    "clean_request_id",
    "InferenceEngine",
    "ServingTelemetry",
    "SERVING_DEFAULTS",
    "warmup_buckets",
    "OverlayResult",
    "PRECISION_CHOICES",
    "build_params_overlay",
    "build_serving_overlay",
    "resolve_precision",
    "Server",
    "ServingHTTPServer",
]
