"""HTTP front-end for the serving engine: a stdlib ``ThreadingHTTPServer``
JSON API plus the graceful-drain orchestration.

Endpoints:

* ``POST /v1/parse`` — body ``{"texts": [...], "timeout_ms": optional}``;
  response ``{"docs": [...], "batch": {"occupancy", "B", "T"}}`` with
  docs in the same JSON schema the bulk ``parse`` CLI writes
  (``training/corpus._doc_to_json`` — one schema for offline and online
  output). Typed serving errors map to HTTP statuses: 429 queue full,
  503 draining, 504 deadline, 413 too large, 400 malformed.
* ``GET /healthz`` — 200 ``{"status": "ok"}`` while serving, 503
  ``{"status": "draining"}`` once shutdown began (a load balancer's
  take-me-out signal).
* ``GET /metrics`` — the :class:`~.engine.ServingTelemetry` snapshot
  (counters/gauges + latency p50/p95/p99); with telemetry disabled it
  reports ``{"telemetry": "disabled"}`` and touches nothing.

Graceful drain reuses the trainer's step-boundary-drain semantics
(``training/resilience.ShutdownCoordinator``): SIGTERM/SIGINT set a flag
(plus a callback that trips the admission gate immediately), the main
thread then 1) rejects new admissions, 2) waits for every queued and
in-flight batch to complete — the serving analog of "finish the step,
then checkpoint" — and 3) stops the listener and exits 0. A drain that
exceeds the timeout escalates to a hard stop with a nonzero exit, the
same honest-failure contract the trainer's escalation path keeps.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from ..training.resilience import ShutdownCoordinator, log_event
from .batcher import (
    Draining,
    NotReady,
    REQUEST_ID_HEADER,
    ServingError,
    SwapFailed,
    UnknownModel,
    clean_request_id,
    etag_for,
    if_none_match_hit,
    mint_request_id,
)
from .engine import InferenceEngine, ServingTelemetry

__all__ = ["ServingHTTPServer", "Server"]

logger = logging.getLogger("spacy_ray_tpu.serving")

MAX_BODY_BYTES = 8 << 20  # an 8 MiB text payload is an abuse, not a parse


class ServingHTTPServer(ThreadingHTTPServer):
    """One handler thread per connection; handlers do host-side work
    (JSON, tokenization) and block in ``engine.submit_*`` — the device
    never sees more than the one dispatch thread."""

    daemon_threads = True

    def __init__(
        self,
        addr: Tuple[str, int],
        engine: InferenceEngine,
        telemetry: Optional[ServingTelemetry] = None,
    ) -> None:
        super().__init__(addr, _Handler)
        self.engine = engine
        self.tel = telemetry
        # multi-model serving (docs/SERVING.md "Multi-model fleet"),
        # all three None unless serve --model-manifest wired them:
        # registry resolves names, residency owns the per-model engine
        # hot set, admission enforces tenant quotas + class mapping.
        # With no manifest the request path below never touches them —
        # the legacy single-model contract, bit-identical.
        self.registry = None
        self.residency = None
        self.admission = None
        # optional diagnosis layer (docs/OBSERVABILITY.md "Alerting &
        # incidents"): the in-process AlertEngine whose states
        # /admin/alerts and the /metrics alerts block serve. None unless
        # telemetry is on AND the CLI wired one (zero-calls contract).
        self.alerts = None
        self.draining = False
        # checkpoint directories /admin/swap may load from. EMPTY means
        # the admin swap surface is OFF (403): accepting an arbitrary
        # client-supplied path would let anyone who can reach the port
        # point the server at weights they control. Configured via
        # serve --watch / --swap-dir (Server wires it through).
        self.allowed_swap_dirs: list = []


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    # loopback is immune, but over a real link Nagle + delayed ACK can
    # add ~40ms between the header write and the body write
    disable_nagle_algorithm = True
    server: ServingHTTPServer

    # stdlib default logs every request to stderr; route to the logger so
    # production stderr stays signal, not access-log noise
    def log_message(self, fmt: str, *args: Any) -> None:
        logger.debug("%s " + fmt, self.address_string(), *args)

    def _reply(
        self,
        status: int,
        payload: Dict[str, Any],
        request_id: Optional[str] = None,
        etag: Optional[str] = None,
    ) -> None:
        body = json.dumps(payload).encode("utf8")
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        if request_id is not None:
            # the trace identity rides the response on EVERY outcome —
            # a 504 is exactly the response whose id gets looked up
            self.send_header(REQUEST_ID_HEADER, request_id)
        if etag is not None:
            self.send_header("ETag", etag)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _reply_not_modified(
        self, etag: str, request_id: Optional[str] = None
    ) -> None:
        """Body-less 304: the client's cached body is still exact. A 304
        carries no body by definition, but Content-Length: 0 is stamped
        anyway so naive keep-alive clients can't desync the stream."""
        self.send_response(304)
        self.send_header("ETag", etag)
        if request_id is not None:
            self.send_header(REQUEST_ID_HEADER, request_id)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def _reply_text(self, status: int, text: str, content_type: str) -> None:
        body = text.encode("utf8")
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _reply_error(
        self, err: ServingError, request_id: Optional[str] = None
    ) -> None:
        self._reply(
            err.http_status, {"error": err.code, "message": str(err)},
            request_id,
        )

    # -- GET ------------------------------------------------------------
    def do_GET(self) -> None:  # noqa: N802 (stdlib handler naming)
        parsed = urlparse(self.path)
        self.path = parsed.path  # route on the bare path below
        if self.path == "/metrics":
            self._get_metrics(parse_qs(parsed.query))
            return
        if self.path == "/trace":
            self._get_trace()
            return
        if self.path == "/admin/exemplars":
            self._get_exemplars()
            return
        if self.path == "/admin/alerts":
            # read-only (like /admin/exemplars): alert STATE is
            # diagnosis, not control, so it is not swap-gated
            if self.server.alerts is None:
                self._reply(200, {"alerts": "disabled"})
            else:
                from ..training.telemetry import sanitize_json

                self._reply(
                    200,
                    sanitize_json({"alerts": self.server.alerts.states()}),
                )
            return
        if self.path == "/healthz":
            if self.server.draining:
                self._reply(503, {"status": "draining"})
            elif not self.server.engine.ready:
                # readiness gate: the listener comes up BEFORE the bucket
                # warmup sweep (so a router can probe), but traffic routed
                # here now would hit a live mid-warmup compile — 503 until
                # the sweep completes and the dispatch thread is running
                self._reply(
                    503,
                    {
                        "status": "warming",
                        "warmed_buckets": len(self.server.engine.warmed),
                    },
                )
            else:
                payload = {
                    "status": "ok",
                    "pipeline": list(self.server.engine.nlp.pipe_names),
                    "warmed_buckets": len(self.server.engine.warmed),
                    "max_batch_docs": self.server.engine.max_batch_docs,
                    "max_doc_len": self.server.engine.max_doc_len,
                    # the engine's honest labels: admission discipline
                    # and the precision the device actually runs —
                    # operators and bench records read them here
                    "batching": self.server.engine.batching,
                    "precision": self.server.engine.overlay.resolved,
                    "precision_label": self.server.engine.overlay.label,
                    # live-serving identity: which checkpoint
                    # generation the dispatch thread is serving (null
                    # = the model as loaded from disk) and how many
                    # flips got it there — the router's canary split
                    # and the fleet's generation-tagged metrics key
                    # on exactly this pair
                    "generation": self.server.engine.serving_generation,
                    "swap_count": self.server.engine.swap_count,
                }
                if self.server.residency is not None:
                    # multi-model placement advertisement: the router's
                    # probe loop learns which models live here (and each
                    # one's generation) from this block — placement
                    # discovery costs zero extra requests
                    payload["resident_models"] = (
                        self.server.residency.resident_info()
                    )
                    payload["residency"] = self.server.residency.stats()
                    if self.server.registry is not None:
                        payload["default_model"] = (
                            self.server.registry.default_model
                        )
                if self.server.tel is not None:
                    # monotonic-clock anchor for the cross-process trace
                    # collector (docs/OBSERVABILITY.md "Distributed
                    # tracing"): maps this replica's trace timestamps
                    # onto the shared wall-clock timeline
                    payload["anchor"] = self.server.tel.trace.anchor()
                self._reply(200, payload)
        else:
            self._reply(404, {"error": "not_found", "message": self.path})

    def _get_metrics(self, query: Dict[str, Any]) -> None:
        tel = self.server.tel
        engine = self.server.engine
        fmt = (query.get("format") or [""])[0]
        if tel is None:
            if fmt == "prometheus":
                from ..training.prometheus import EXPOSITION_CONTENT_TYPE

                # comment-only exposition: a scraper sees an honest
                # empty scrape, and the disabled path still constructs
                # zero telemetry objects (test-enforced)
                self._reply_text(
                    200, "# srt telemetry disabled\n",
                    EXPOSITION_CONTENT_TYPE,
                )
                return
            self._reply(
                200,
                {
                    "telemetry": "disabled",
                    "generation": engine.serving_generation,
                    "swap_count": engine.swap_count,
                },
            )
            return
        from ..training.telemetry import sanitize_json

        snap = tel.snapshot()
        # stamp the snapshot with the generation it describes:
        # merge_serving_snapshots groups per-replica snapshots by
        # this key, which is what makes fleet slo_window
        # percentiles splittable by generation
        snap["generation"] = engine.serving_generation
        snap["swap_count"] = engine.swap_count
        residency = self.server.residency
        if residency is not None:
            # per-model sub-snapshots (each resident engine carries its
            # own telemetry): merge_serving_snapshots groups these into
            # the fleet's by_model block, and the Prometheus branch
            # below emits them as model-labeled series
            models: Dict[str, Any] = {}
            for name, eng in sorted(residency.engines().items()):
                if eng.tel is None:
                    continue
                msnap = eng.tel.snapshot()
                msnap["model"] = name
                msnap["generation"] = eng.serving_generation
                msnap["swap_count"] = eng.swap_count
                models[name] = msnap
            if models:
                snap["models"] = models
            snap["residency"] = residency.stats()
        if self.server.alerts is not None:
            # the compact alert block `telemetry top` renders; full
            # per-rule states live on /admin/alerts
            snap["alerts"] = self.server.alerts.summary()
        if fmt == "prometheus":
            from ..training.prometheus import (
                EXPOSITION_CONTENT_TYPE,
                PromFamilies,
            )

            fam = PromFamilies()
            fam.add_snapshot(snap, prefix="srt_serving")
            # add_snapshot only walks counters/gauges/histograms — the
            # snapshot's "process" block becomes the shared (unprefixed)
            # srt_process_* family here, same names on every surface
            from ..training.hoststats import add_process_family

            add_process_family(fam, snap.get("process"))
            # live-serving identity as explicit gauges (counters span
            # generations, so the generation is NOT a label on them —
            # it is its own series)
            if engine.serving_generation is not None:
                fam.add(
                    "srt_serving_generation_id", "gauge",
                    engine.serving_generation,
                )
            fam.add("srt_serving_swap_count", "gauge", engine.swap_count)
            win = snap.get("slo_window")
            if isinstance(win, dict):
                for q in ("p50", "p95", "p99"):
                    fam.add(
                        "srt_serving_request_latency_window_seconds",
                        "gauge",
                        win.get(f"request_latency_{q}"),
                        {
                            "quantile": q.replace("p", "0."),
                            "window_s": int(win.get("window_s") or 0),
                        },
                    )
            if isinstance(snap.get("models"), dict):
                # model-labeled twins of the srt_serving_* families: one
                # series set per resident model, so per-model p99 is
                # scrapeable without parsing the JSON surface
                for name, msnap in sorted(snap["models"].items()):
                    fam.add_snapshot(
                        msnap, prefix="srt_serving",
                        labels={"model": name},
                    )
                    mwin = msnap.get("slo_window")
                    if isinstance(mwin, dict):
                        for q in ("p50", "p95", "p99"):
                            fam.add(
                                "srt_serving_request_latency_window_seconds",
                                "gauge",
                                mwin.get(f"request_latency_{q}"),
                                {
                                    "model": name,
                                    "quantile": q.replace("p", "0."),
                                    "window_s": int(
                                        mwin.get("window_s") or 0
                                    ),
                                },
                            )
            if self.server.alerts is not None:
                # srt_alert_state{alert,severity} 0/1/2 + fired totals —
                # the scraper-side view of the in-process state machine
                self.server.alerts.add_prometheus(fam)
            self._reply_text(200, fam.render(), EXPOSITION_CONTENT_TYPE)
            return
        self._reply(200, sanitize_json(snap))

    def _get_trace(self) -> None:
        tel = self.server.tel
        if tel is None:
            self._reply(200, {"trace": "disabled"})
            return
        from ..training.telemetry import sanitize_json

        payload = tel.trace.payload()
        payload["anchor"] = tel.trace.anchor()
        payload["role"] = "replica"
        self._reply(200, sanitize_json(payload))

    def _get_exemplars(self) -> None:
        tel = self.server.tel
        if tel is None:
            self._reply(200, {"exemplars": "disabled"})
            return
        from ..training.telemetry import sanitize_json

        self._reply(200, sanitize_json(tel.exemplars()))

    # -- POST -----------------------------------------------------------
    def do_POST(self) -> None:  # noqa: N802
        try:
            length = int(self.headers.get("Content-Length") or 0)
        except ValueError:
            length = -1
        if length < 0 or length > MAX_BODY_BYTES:
            # body not consumed: the connection must close, or its bytes
            # would be parsed as the next keep-alive request
            self.close_connection = True
            self._reply(
                400,
                {
                    "error": "bad_request",
                    "message": f"Content-Length must be 0..{MAX_BODY_BYTES}",
                },
            )
            return
        body = self.rfile.read(length)  # consume BEFORE any early reply:
        # an unread body desyncs every later request on this connection
        if self.path in ("/admin/swap", "/admin/rollback"):
            self._handle_admin(body)
            return
        if self.path == "/admin/models/load":
            self._handle_model_load(body)
            return
        if self.path != "/v1/parse" and not (
            self.server.registry is not None
            and self.path.startswith("/v1/models/")
        ):
            self._reply(404, {"error": "not_found", "message": self.path})
            return
        # trace identity: honor a client/router-supplied id, mint one
        # otherwise — every reply below (success AND typed errors)
        # carries it back in the response header
        request_id = clean_request_id(
            self.headers.get(REQUEST_ID_HEADER)
        ) or mint_request_id()
        if self.server.draining:
            self._reply_error(Draining("server is draining"), request_id)
            return
        if not self.server.engine.ready:
            self._reply_error(
                NotReady("bucket warmup in progress; not admitting yet"),
                request_id,
            )
            return
        # multi-model resolution (no manifest → registry is None and
        # this whole block is skipped; the legacy path is untouched):
        # path wins over the X-SRT-Model header wins over the default —
        # an unknown name is the typed 404, never a silent fallback
        model_name: Optional[str] = None
        if self.server.registry is not None:
            try:
                model_name, _ = self.server.registry.resolve_model(
                    self.path, self.headers
                )
            except UnknownModel as e:
                if self.server.tel is not None:
                    self.server.tel.request_rejected(e, request_id)
                self._reply_error(e, request_id)
                return
        try:
            payload = json.loads(body or b"{}")
        except ValueError:
            self._reply(
                400, {"error": "bad_request", "message": "body is not JSON"},
                request_id,
            )
            return
        texts = payload.get("texts") if isinstance(payload, dict) else None
        if (
            not isinstance(texts, list)
            or not texts
            or not all(isinstance(t, str) for t in texts)
        ):
            self._reply(
                400,
                {
                    "error": "bad_request",
                    "message": 'body must be {"texts": [<non-empty list of '
                    'strings>], "timeout_ms": optional int}',
                },
                request_id,
            )
            return
        timeout_s: Optional[float] = None
        if isinstance(payload.get("timeout_ms"), (int, float)):
            timeout_s = max(float(payload["timeout_ms"]) / 1000.0, 1e-3)
        from ..training.corpus import _doc_to_json

        # tenant admission (quota BEFORE the queue, metered in docs) +
        # SLO-class resolution for the batcher's weighted fair queue
        klass = "default"
        if self.server.admission is not None:
            from .multimodel.registry import TENANT_HEADER

            try:
                klass = self.server.admission.admit(
                    self.headers.get(TENANT_HEADER), n_docs=len(texts)
                )
            except ServingError as e:
                if self.server.tel is not None:
                    self.server.tel.request_rejected(e, request_id)
                self._reply_error(e, request_id)
                return
        # resolve the engine: the residency hot set for a named model
        # (loading it on first use, LRU-evicting past capacity), the
        # server's single engine otherwise
        engine = self.server.engine
        if self.server.residency is not None and model_name is not None:
            try:
                engine = self.server.residency.engine_for(model_name)
            except ServingError as e:
                if self.server.tel is not None:
                    self.server.tel.request_rejected(e, request_id)
                self._reply_error(e, request_id)
                return
        # conditional response (docs/SERVING.md "Data plane"): the ETag
        # is a pure function of (texts, model, generation), so it is
        # known HERE, before any inference — a matching If-None-Match
        # skips the queue, the device, and serialization entirely. The
        # check validates against the CURRENT generation: post-swap, the
        # tag differs and the request falls through to a full parse.
        admission_etag = etag_for(
            texts, model_name or "", engine.serving_generation
        )
        if if_none_match_hit(
            self.headers.get("If-None-Match"), admission_etag
        ):
            if engine.tel is not None:
                engine.tel.conditional_hit()
            self._reply_not_modified(admission_etag, request_id)
            return
        try:
            req = engine.submit_texts(
                texts, timeout_s=timeout_s, request_id=request_id,
                klass=klass,
            )
        except ServingError as e:
            self._reply_error(e, request_id)
            return
        t_ser = time.perf_counter()
        docs_json = [_doc_to_json(d) for d in req.docs]
        serialize_s = time.perf_counter() - t_ser
        # exemplars ride the tel of the engine that served the request,
        # so a per-model engine's p99 threshold judges its own traffic
        tel = engine.tel
        if tel is not None and req.latency_s is not None:
            # slow-request exemplar: the per-stage breakdown that turns
            # "p99 regressed" into "this request waited HERE"
            tel.consider_exemplar(
                request_id=req.request_id,
                latency_s=req.latency_s,
                stages={
                    "queue_wait": (
                        req.started_at - req.enqueued_at
                        if req.started_at is not None else None
                    ),
                    "dispatch_wait": (
                        req.dispatched_at - req.enqueued_at
                        if req.dispatched_at is not None else None
                    ),
                    "device": req.device_s,
                    "serialize": serialize_s,
                },
                n_docs=len(req.docs),
                B=req.batch_info.get("B"),
                T=req.batch_info.get("T"),
                generation=req.batch_info.get("generation"),
            )
        # the stamped ETag uses the generation the batch ACTUALLY ran on
        # (a swap can land between admission and dispatch) — the tag must
        # identify the body it rides, not the body admission expected
        self._reply(
            200,
            {"docs": docs_json, "batch": req.batch_info},
            request_id,
            etag=etag_for(
                texts, model_name or "", req.batch_info.get("generation")
            ),
        )


    def _handle_model_load(self, body: bytes) -> None:
        """Placement control (docs/SERVING.md "Multi-model fleet"):
        ``{"model": <name>}`` pulls a MANIFEST model into this replica's
        hot set (load + warmup on this handler thread; resident traffic
        keeps dispatching). Unlike /admin/swap this needs no directory
        allowlist — the loadable set is exactly the operator-provided
        manifest, never a client-supplied path."""
        if self.server.residency is None:
            self._reply(
                403,
                {
                    "error": "forbidden",
                    "message": "multi-model serving is not configured "
                    "(serve --model-manifest)",
                },
            )
            return
        if self.server.draining:
            self._reply_error(Draining("server is draining; no loads"))
            return
        try:
            payload = json.loads(body or b"{}")
        except ValueError:
            self._reply(
                400, {"error": "bad_request", "message": "body is not JSON"}
            )
            return
        name = payload.get("model") if isinstance(payload, dict) else None
        if not isinstance(name, str) or not name:
            self._reply(
                400,
                {"error": "bad_request", "message": 'body must be {"model": '
                 "<manifest model name>}"},
            )
            return
        try:
            self.server.residency.engine_for(name)
        except ServingError as e:
            self._reply_error(e)
            return
        self._reply(
            200,
            {
                "model": name,
                "resident": self.server.residency.resident(),
                "residency": self.server.residency.stats(),
            },
        )

    # -- admin: live hot-swap control (docs/SERVING.md "Continuous
    # learning"). These run on the LISTENER, not a side channel, so the
    # fleet controller reaches replicas over the address it already
    # knows; staging runs on this handler thread while the dispatch
    # thread keeps serving, and the flip itself is an O(pointers)
    # exchange at a dispatch boundary.
    def _handle_admin(self, body: bytes) -> None:
        engine = self.server.engine
        if self.server.draining:
            self._reply_error(Draining("server is draining; no swaps"))
            return
        # optional per-model target (multi-model serving): swap/rollback
        # the named RESIDENT engine instead of the default — hot-swap
        # works per model, and swapping a model that is not resident is
        # a typed refusal, not a surprise cold load
        model = None
        if body:
            try:
                parsed = json.loads(body)
                if isinstance(parsed, dict):
                    model = parsed.get("model")
            except ValueError:
                pass  # the swap path below replies 400 for non-JSON
        if isinstance(model, str) and model:
            if self.server.residency is None:
                self._reply(
                    403,
                    {
                        "error": "forbidden",
                        "message": "per-model swap needs multi-model "
                        "serving (serve --model-manifest)",
                    },
                )
                return
            try:
                engine = self.server.residency.engine_for(model, load=False)
            except ServingError as e:
                self._reply_error(e)
                return
        if not self.server.allowed_swap_dirs:
            # the WHOLE admin surface keys off the swap-dir config —
            # rollback included: an ungated rollback on an open port
            # would let any client revert a fleet to stale weights (and
            # toggle generations at will, since rollback is its own
            # inverse)
            self._reply(
                403,
                {
                    "error": "forbidden",
                    "message": "admin swap/rollback is disabled: no swap "
                    "directory configured (serve --watch/--swap-dir)",
                },
            )
            return
        if self.path == "/admin/rollback":
            try:
                result = engine.rollback()
            except ServingError as e:
                self._reply_error(e)
                return
            self._reply(200, {k: v for k, v in result.items()})
            return
        # /admin/swap {"dir": <ckpt dir>, "generation": optional stamp}
        if not engine.ready:
            self._reply_error(
                NotReady("bucket warmup in progress; not swapping yet")
            )
            return
        try:
            payload = json.loads(body or b"{}")
        except ValueError:
            self._reply(
                400, {"error": "bad_request", "message": "body is not JSON"}
            )
            return
        ckpt_dir = payload.get("dir") if isinstance(payload, dict) else None
        if not isinstance(ckpt_dir, str) or not ckpt_dir:
            self._reply(
                400,
                {
                    "error": "bad_request",
                    "message": 'body must be {"dir": <checkpoint dir>, '
                    '"generation": optional int}',
                },
            )
            return
        from pathlib import Path

        allowed = self.server.allowed_swap_dirs
        try:
            requested = Path(ckpt_dir).resolve()
        except OSError:
            requested = None
        if requested is None or not any(
            requested == Path(d).resolve() for d in allowed
        ):
            # not an allowlisted checkpoint directory: loading weights
            # from an arbitrary client-supplied path is how a reachable
            # port becomes an arbitrary-model (or worse) endpoint
            self._reply(
                403,
                {
                    "error": "forbidden",
                    "message": (
                        "dir is not an allowed swap directory (configure "
                        "via serve --watch/--swap-dir)"
                        if allowed
                        else "admin swap is disabled: no swap directory "
                        "configured (serve --watch/--swap-dir)"
                    ),
                },
            )
            return
        from ..training.checkpoint import CheckpointCorrupt, Checkpoints

        try:
            ckpts = Checkpoints(ckpt_dir)
            generation = payload.get("generation")
            if generation is None:
                generation = ckpts.latest_intact_generation(
                    params_only=True
                )
                if generation is None:
                    raise SwapFailed(
                        f"no intact checkpoint generation in {ckpt_dir}"
                    )
            # params-only: the swap discards opt_state, so the admin
            # route neither hashes nor unpickles it (no pickle.load on
            # a network-reachable path, and half the I/O per swap)
            state = ckpts.load_generation_params(int(generation))
            result = engine.swap_params(
                state["params"], int(generation), source="admin"
            )
        except CheckpointCorrupt as e:
            # a torn generation is a refused swap, not a crash — the
            # caller (controller/operator) picks another generation
            self._reply_error(SwapFailed(str(e)))
            return
        except ServingError as e:
            self._reply_error(e)
            return
        self._reply(200, {k: v for k, v in result.items()})


class Server:
    """Lifecycle orchestration: start the listener, wait for a shutdown
    request (signal or programmatic), drain gracefully, exit.

    ``run()`` is the CLI path (installs SIGTERM/SIGINT handlers);
    ``start()`` + ``request_shutdown()`` + ``wait()`` is the in-process
    test path — same drain code either way.
    """

    def __init__(
        self,
        engine: InferenceEngine,
        host: str = "127.0.0.1",
        port: int = 8080,
        *,
        telemetry: Optional[ServingTelemetry] = None,
        drain_timeout_s: float = 30.0,
        watcher: Optional[Any] = None,
        swap_dirs: Optional[list] = None,
        alerts: Optional[Any] = None,
        recorder: Optional[Any] = None,
        observe_interval_s: float = 2.0,
        registry: Optional[Any] = None,
        residency: Optional[Any] = None,
        admission: Optional[Any] = None,
    ) -> None:
        self.engine = engine
        self.tel = telemetry
        # multi-model serving (all None without --model-manifest)
        self.registry = registry
        self.residency = residency
        self.admission = admission
        # the diagnosis layer (docs/OBSERVABILITY.md "Alerting &
        # incidents"): an AlertEngine and/or FlightRecorder, both fed by
        # one observer ticker off the hot path. Only ever constructed by
        # the CLI when telemetry is on — with telemetry off there is no
        # ticker, zero rule evaluations, zero ring writes (guard-tested).
        self.alerts = alerts
        self.recorder = recorder
        self.observe_interval_s = float(observe_interval_s)
        self._observer: Optional[threading.Thread] = None
        self._observer_stop = threading.Event()
        self.drain_timeout_s = float(drain_timeout_s)
        # optional live-serving CheckpointWatcher (serve --watch): started
        # only after the engine is ready (swapping mid-warmup would race
        # the sweep), stopped before the drain (a swap mid-drain serves
        # nobody)
        self.watcher = watcher
        self.httpd = ServingHTTPServer((host, port), engine, telemetry)
        self.httpd.alerts = alerts
        self.httpd.registry = registry
        self.httpd.residency = residency
        self.httpd.admission = admission
        # /admin/swap allowlist: the watched dir plus any explicit
        # --swap-dir entries; empty = admin swaps 403 (see
        # ServingHTTPServer.allowed_swap_dirs)
        dirs = [str(d) for d in (swap_dirs or [])]
        if watcher is not None and str(watcher.ckpt_dir) not in dirs:
            dirs.append(str(watcher.ckpt_dir))
        self.httpd.allowed_swap_dirs = dirs
        self._stop = threading.Event()
        self._serve_thread: Optional[threading.Thread] = None

    @property
    def address(self) -> Tuple[str, int]:
        host, port = self.httpd.server_address[:2]
        return str(host), int(port)

    def start(self) -> Tuple[str, int]:
        self._serve_thread = threading.Thread(
            target=self.httpd.serve_forever,
            kwargs={"poll_interval": 0.1},
            name="serve-http",
            daemon=True,
        )
        self._serve_thread.start()
        if self.tel is not None and (
            self.alerts is not None or self.recorder is not None
        ):
            self._observer = threading.Thread(
                target=self._observe_loop,
                name="serve-observer",
                daemon=True,
            )
            self._observer.start()
        return self.address

    def _observe_loop(self) -> None:
        """The diagnosis ticker: snapshot the telemetry registry every
        ``observe_interval_s``, feed the flight-recorder ring (which
        also persists the black box, the SIGKILL-survivable copy), and
        evaluate the alert rules. First tick runs immediately so a
        replica that dies young still leaves a black box."""
        while True:
            try:
                snap = self.tel.snapshot()
                snap["generation"] = self.engine.serving_generation
                snap["swap_count"] = self.engine.swap_count
                if self.recorder is not None:
                    self.recorder.record(snap)
                if self.alerts is not None:
                    self.alerts.evaluate(snap)
            except Exception:
                logger.exception("observer tick failed")
            if self._observer_stop.wait(self.observe_interval_s):
                return

    def request_shutdown(self, signum: Optional[int] = None) -> None:
        """Safe from a signal handler: flag writes and an Event set only
        — no locks. The batcher's own drain gate (a Condition under a
        non-reentrant lock) is tripped by ``wait`` on the waiting
        thread; taking it HERE could self-deadlock if a second signal
        lands while that thread holds the lock (e.g. k8s re-signalling
        mid-drain). The HTTP admission gate (``draining``) still flips
        instantly, so new requests 503 from the first signal on."""
        self.httpd.draining = True
        self._stop.set()

    def wait(self) -> int:
        """Block until shutdown is requested, then drain. Returns the
        process exit code: 0 for a clean drain, 1 when in-flight work
        had to be abandoned at the timeout."""
        self._stop.wait()
        self.httpd.draining = True
        self._observer_stop.set()
        if self._observer is not None:
            self._observer.join(timeout=5.0)
            self._observer = None
        if self.watcher is not None:
            self.watcher.stop()
        self.engine.batcher.begin_drain()
        if self.residency is not None:
            self.residency.begin_drain()
        log_event(
            "serve-drain",
            "shutdown requested — draining "
            f"{self.engine.batcher.queue_depth()} queued doc(s)",
            level=logging.INFO,
        )
        clean = self.engine.drain(self.drain_timeout_s)
        if not clean:
            log_event(
                "serve-drain-timeout",
                f"drain exceeded {self.drain_timeout_s:.1f}s — hard stop",
            )
            self.engine.stop()
        if self.residency is not None:
            # every resident engine gets the same graceful drain the
            # default engine got (the default is in the hot set too —
            # its second drain is an idempotent no-op)
            if not self.residency.stop_all(self.drain_timeout_s):
                clean = False
        self.httpd.shutdown()
        self.httpd.server_close()
        return 0 if clean else 1

    def run(
        self, *, banner: bool = True, warmup_engine: Optional[bool] = None
    ) -> int:
        coordinator = ShutdownCoordinator()
        coordinator.add_callback(self.request_shutdown)
        coordinator.install()
        try:
            host, port = self.start()
            if banner:
                # exact, parseable line: the drain subprocess test, the
                # fleet replica supervisor (and any operator script) read
                # the bound port from it
                print(f"serving on http://{host}:{port}", flush=True)
            if warmup_engine is not None:
                # listener-first startup: the port is announced and
                # /healthz answers "warming" (503) while the bucket sweep
                # compiles; a SIGTERM landing mid-warmup is honored right
                # after (wait() returns immediately on the set flag)
                self.engine.start(warmup=warmup_engine)
                if banner and self.engine.warmed:
                    print(
                        f"warmed {len(self.engine.warmed)} (B, T) bucket "
                        "programs; ready", flush=True,
                    )
            if self.watcher is not None and not self._stop.is_set():
                self.watcher.start()
                if banner:
                    print(
                        f"watching {self.watcher.ckpt_dir} for new "
                        "checkpoint generations "
                        f"(every {self.watcher.interval_s:.1f}s)",
                        flush=True,
                    )
            return self.wait()
        finally:
            coordinator.restore()
