"""Cross-process trace collection: merge the Perfetto buffers of the
router, every replica, the trainer — or every worker of a TRAINER fleet
(positional endpoints via :func:`fleet_worker_urls`; a grad push leaving
worker 2 and its apply landing on owner 0 render as one visible hop
across process tracks) — into ONE timeline file.

Each process's :class:`~..training.telemetry.TraceBuffer` stamps events
in microseconds relative to its own construction origin on its own
monotonic clock — perfect within a process, meaningless across two. The
bridge is the clock ANCHOR every process exposes on ``/healthz`` and
``/trace``: one simultaneous reading ``(origin, clock_now, unix_now)``
of the buffer's clock against the wall clock. With it, any event maps to
wall time as ``unix_now - (clock_now - (origin + ts/1e6))`` — no shared
clock, no clock-sync protocol, just one exchange per process (the same
trick Ray's timeline uses to line up per-worker event logs, PAPERS.md
arXiv:1712.05889).

The merged file keeps one Chrome-trace ``pid`` (= one Perfetto process
track group) per source process, with ``process_name`` metadata, so a
single request's spans — router ``route`` span, replica ``request`` +
``serve_batch`` spans, all carrying the same ``request_id`` arg — render
as one visible hop across tracks.

Stdlib-only and jax-free: the collector runs anywhere (operator laptop,
CI) against live endpoints.
"""

from __future__ import annotations

import http.client
import json
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple
from urllib.parse import urlparse

__all__ = [
    "merge_process_traces",
    "fetch_json",
    "fleet_worker_urls",
    "collect_fleet_traces",
    "write_merged_trace",
]


def fleet_worker_urls(
    base_port: int, workers: int, host: str = "127.0.0.1"
) -> List[str]:
    """Endpoint URLs for a TRAINER fleet: worker k's peer server (which
    doubles as its telemetry endpoint) binds ``base_port + k``, so the
    fleet is addressed positionally — there is no router whose
    ``/healthz`` replica list could discover it. The CLI's
    ``collect-trace --fleet-base-port N --workers K`` expands through
    here."""
    if int(workers) <= 0:
        raise ValueError(f"workers must be positive, got {workers}")
    return [
        f"http://{host}:{int(base_port) + k}" for k in range(int(workers))
    ]


def _anchor_offset_us(anchor: Optional[Dict[str, Any]]) -> Optional[float]:
    """Microseconds to ADD to an event's relative ``ts`` to land on the
    unix-epoch timeline; None when the anchor is absent/malformed (the
    process cannot be placed honestly and is skipped, not guessed)."""
    if not isinstance(anchor, dict):
        return None
    try:
        origin = float(anchor["origin"])
        clock_now = float(anchor["clock_now"])
        unix_now = float(anchor["unix_now"])
    except (KeyError, TypeError, ValueError):
        return None
    return (unix_now - clock_now + origin) * 1e6


def merge_process_traces(
    processes: List[Dict[str, Any]]
) -> Dict[str, Any]:
    """Merge per-process trace payloads into one Chrome-trace object.

    ``processes``: ``[{"name": str, "trace": {"traceEvents": [...]},
    "anchor": {origin, clock_now, unix_now}}, ...]``. Each source gets
    its own ``pid`` (0..N-1 in input order) and a ``process_name``
    metadata row; event timestamps are re-based onto one shared timeline
    whose zero is the earliest event across all sources. Sources with a
    missing/malformed anchor are skipped and listed under
    ``otherData.skipped`` — misplacing a track by an unknown offset
    would be worse than omitting it.
    """
    shifted: List[Tuple[int, str, List[Dict[str, Any]]]] = []
    skipped: List[str] = []
    merged_names: List[str] = []
    for proc in processes:
        name = str(proc.get("name") or f"process-{len(shifted)}")
        offset = _anchor_offset_us(proc.get("anchor"))
        events = list((proc.get("trace") or {}).get("traceEvents") or [])
        if offset is None:
            skipped.append(name)
            continue
        pid = len(shifted)
        out_events: List[Dict[str, Any]] = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "tid": 0,
                "args": {"name": name},
            }
        ]
        for ev in events:
            ev = dict(ev)
            ev["pid"] = pid
            if ev.get("ph") != "M" and isinstance(
                ev.get("ts"), (int, float)
            ):
                ev["ts"] = float(ev["ts"]) + offset
            out_events.append(ev)
        shifted.append((pid, name, out_events))
        merged_names.append(name)
    all_ts = [
        ev["ts"]
        for _, _, events in shifted
        for ev in events
        if ev.get("ph") != "M" and isinstance(ev.get("ts"), (int, float))
    ]
    t0 = min(all_ts) if all_ts else 0.0
    merged_events: List[Dict[str, Any]] = []
    for _, _, events in shifted:
        for ev in events:
            if ev.get("ph") != "M" and isinstance(
                ev.get("ts"), (int, float)
            ):
                ev["ts"] = round(ev["ts"] - t0, 1)
            merged_events.append(ev)
    return {
        "traceEvents": merged_events,
        "displayTimeUnit": "ms",
        "otherData": {
            "merged_from": merged_names,
            "skipped": skipped,
            "epoch_origin_us": t0,
        },
    }


def fetch_json(
    base_url: str, path: str, timeout_s: float = 10.0
) -> Tuple[int, Any]:
    """GET ``base_url + path``, parse JSON. Raises OSError on transport
    failure (or an unsupported scheme — silently speaking cleartext to
    an https:// endpoint would be worse); returns (status,
    payload-or-None)."""
    parsed = urlparse(base_url if "//" in base_url else f"http://{base_url}")
    host = parsed.hostname or "127.0.0.1"
    scheme = parsed.scheme or "http"
    try:
        port = parsed.port
    except ValueError as e:  # malformed port ("…:80x0") must surface as
        # the transport failure callers already handle, not a traceback
        raise OSError(f"invalid port in {base_url!r}: {e}")
    if scheme == "https":
        conn: http.client.HTTPConnection = http.client.HTTPSConnection(
            host, port or 443, timeout=timeout_s
        )
    elif scheme == "http":
        conn = http.client.HTTPConnection(
            host, port or 80, timeout=timeout_s
        )
    else:
        raise OSError(f"unsupported URL scheme {scheme!r} in {base_url!r}")
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        raw = resp.read()
    except http.client.HTTPException as e:
        # a peer exiting mid-response (RemoteDisconnected, torn status
        # line) raises HTTPException, which is NOT an OSError — without
        # this mapping, every caller that handles "endpoint went away"
        # as OSError (telemetry top's poll loop, the trace collector)
        # would crash on exactly the mid-poll exit it exists to survive
        raise OSError(f"HTTP exchange with {base_url!r} failed: {e}")
    finally:
        conn.close()
    try:
        return resp.status, json.loads(raw)
    except ValueError:
        return resp.status, None


def collect_fleet_traces(
    base_urls: List[str],
    *,
    discover: bool = True,
    timeout_s: float = 10.0,
) -> Dict[str, Any]:
    """Fetch ``/healthz`` (anchor) + ``/trace`` from every endpoint and
    merge. When an endpoint's ``/healthz`` carries a ``replicas`` list
    (the fleet router) and ``discover`` is on, each addressed replica is
    scraped too — one router URL collects the whole fleet.

    Endpoints that are unreachable or report no trace (telemetry
    disabled) are skipped and recorded in ``otherData.skipped``."""
    # (name, base_url, discovery-phase /healthz payload or None) — the
    # health payload is reused as the anchor fallback below, so each
    # endpoint pays exactly one /healthz round trip
    targets: List[Tuple[str, str, Optional[Dict[str, Any]]]] = []
    seen: set = set()
    for base in base_urls:
        if base in seen:
            continue
        seen.add(base)
        name = base
        replicas: List[Dict[str, Any]] = []
        try:
            _, health = fetch_json(base, "/healthz", timeout_s)
        except OSError:
            health = None
        if isinstance(health, dict):
            if isinstance(health.get("replicas"), list):
                name = f"router {base}"
                replicas = health["replicas"]
            elif health.get("role"):
                name = f"{health['role']} {base}"
            else:
                name = f"replica {base}"
        targets.append((name, base, health if isinstance(health, dict) else None))
        if discover:
            parsed = urlparse(
                base if "//" in base else f"http://{base}"
            )
            for row in replicas:
                port = row.get("port")
                if not isinstance(port, int):
                    continue
                host = row.get("host") or parsed.hostname or "127.0.0.1"
                url = f"http://{host}:{port}"
                if url not in seen:
                    seen.add(url)
                    targets.append(
                        (f"replica-{row.get('id', '?')} {url}", url, None)
                    )
    processes: List[Dict[str, Any]] = []
    unreachable: List[str] = []
    for name, base, health in targets:
        try:
            if health is None:
                _, health_raw = fetch_json(base, "/healthz", timeout_s)
                health = (
                    health_raw if isinstance(health_raw, dict) else None
                )
            _, trace = fetch_json(base, "/trace", timeout_s)
        except OSError:
            unreachable.append(name)
            continue
        if not isinstance(trace, dict) or "traceEvents" not in trace:
            unreachable.append(name)
            continue
        anchor = trace.get("anchor")
        if not isinstance(anchor, dict) and health is not None:
            anchor = health.get("anchor")
        processes.append(
            {"name": name, "trace": trace, "anchor": anchor}
        )
    merged = merge_process_traces(processes)
    merged["otherData"]["skipped"] = sorted(
        set(merged["otherData"]["skipped"]) | set(unreachable)
    )
    return merged


def write_merged_trace(merged: Dict[str, Any], path: Path) -> Path:
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_name(path.name + ".tmp")
    tmp.write_text(json.dumps(merged), encoding="utf8")
    tmp.replace(path)
    return path
