"""Tenant admission: token-bucket quotas in front of the class-aware
batcher (docs/SERVING.md "Multi-model fleet").

Division of labor, stated once:

* **quota** (this module) answers "is THIS TENANT over its declared
  rate" — a per-tenant token bucket metered in docs/s, shed with the
  typed ``QuotaExceeded(429)`` BEFORE the request touches the queue, so
  an over-quota burst costs the fleet nothing but the reject;
* **fairness** (batcher.DynamicBatcher ``class_weights``) answers "of
  the admitted work, who dispatches next" — deficit round robin across
  SLO classes, so even two in-quota tenants cannot starve each other
  past their class weights.

Buckets are PER PROCESS: each replica meters the traffic it actually
receives, so a fleet's effective tenant ceiling is quota x replicas
under perfect balance (docs/TUNING.md §23 covers sizing for that).
The controller makes ZERO telemetry calls — rejects are counted by the
serving telemetry at the HTTP layer, exactly like the other typed
rejects, and with telemetry off nothing is counted anywhere.

The clock is injectable; tests drive refill with a fake clock.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional, TYPE_CHECKING

from ..batcher import QuotaExceeded

if TYPE_CHECKING:  # pragma: no cover
    from .registry import ModelRegistry, TenantSpec

__all__ = ["TokenBucket", "AdmissionController"]


class TokenBucket:
    """The classic meter: ``rate`` tokens/s refill up to ``burst``;
    ``try_acquire(n)`` atomically spends ``n`` or spends nothing.
    Refill is computed lazily from elapsed clock time — no timer
    thread, safe under concurrent handler threads."""

    def __init__(
        self,
        rate: float,
        burst: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if not (rate > 0):
            raise ValueError(f"rate must be > 0, got {rate!r}")
        self.rate = float(rate)
        # default burst = one second of rate: a tenant can always spend
        # its steady-state second in one instant, nothing more
        self.burst = float(burst) if burst is not None else float(rate)
        if not (self.burst > 0):
            raise ValueError(f"burst must be > 0, got {burst!r}")
        self.clock = clock
        self._lock = threading.Lock()
        self._tokens = self.burst
        self._last = clock()

    def _refill(self, now: float) -> None:
        elapsed = now - self._last
        if elapsed > 0:
            self._tokens = min(self.burst, self._tokens + elapsed * self.rate)
        self._last = now

    def try_acquire(self, n: float = 1.0) -> bool:
        with self._lock:
            self._refill(self.clock())
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def available(self) -> float:
        with self._lock:
            self._refill(self.clock())
            return self._tokens


class AdmissionController:
    """Per-tenant quota enforcement + tenant → class resolution, built
    from a ``ModelRegistry``. One instance per serving process (replica
    or single-model server); buckets exist only for tenants that
    declare a quota — the anonymous tenant and unlimited tenants pay a
    dict lookup and nothing else."""

    def __init__(
        self,
        registry: "ModelRegistry",
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.registry = registry
        self._buckets: Dict[str, TokenBucket] = {}
        for name, spec in registry.tenants.items():
            if spec.quota_docs_per_s is not None:
                self._buckets[name] = TokenBucket(
                    spec.quota_docs_per_s,
                    burst=spec.quota_burst,
                    clock=clock,
                )
        # shed ledger (plain ints, mirrored into telemetry by the HTTP
        # layer — this module itself makes zero telemetry calls)
        self.rejected_quota = 0
        self._lock = threading.Lock()

    def admit(self, tenant: Optional[str], n_docs: int = 1) -> str:
        """Charge ``n_docs`` against ``tenant``'s bucket and return the
        SLO class the request rides in. Raises ``QuotaExceeded`` (typed
        429) when the bucket cannot cover the request; tenants without
        a quota (including the anonymous default) always admit."""
        spec = self.registry.tenant(tenant)
        bucket = self._buckets.get(spec.name) if tenant is not None else None
        if bucket is not None and not bucket.try_acquire(float(n_docs)):
            with self._lock:
                self.rejected_quota += 1
            raise QuotaExceeded(
                f"tenant {spec.name!r} is over quota "
                f"({spec.quota_docs_per_s:g} docs/s, burst "
                f"{bucket.burst:g}); retry after the bucket refills"
            )
        return spec.klass

    def stats(self) -> Dict[str, float]:
        """JSON-safe snapshot: remaining tokens per metered tenant plus
        the shed count (the /metrics surface for quota pressure)."""
        out: Dict[str, float] = {"rejected_quota": float(self.rejected_quota)}
        for name, bucket in self._buckets.items():
            out[f"tokens_{name}"] = bucket.available()
        return out
