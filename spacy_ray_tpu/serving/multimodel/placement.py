"""Placement-aware scaling: WHICH models replicas host, not just how
many replicas exist (docs/SERVING.md "Multi-model fleet").

The existing ``AutoscalerPolicy`` sizes the fleet from the merged
window p99; this extension reads the PER-MODEL window p99 (the
``by_model`` block the same Prometheus plumbing already merges) plus
the placement the router's probe loop learned from /healthz resident
sets, and decides per-model residency moves:

* a model whose window p99 breaches its class target (or the fleet
  default) on enough consecutive observations gets replicated onto the
  ready replica with the fewest resident models that does not already
  host it — spreading the hot model widens its least-outstanding
  routing subset, which is the fleet-level pressure release;
* models never breach → no decisions: replicas keep their organic
  (traffic-driven, LRU) residency.

Decisions are hysteresis-gated exactly like the replica-count policy
(consecutive breaches + cooldown, injectable clock) so one noisy
window never shuffles placement. The policy only DECIDES; the fleet
applies a decision by POSTing ``/admin/models/load`` to the chosen
replica and appends it to the placement ledger (``placement.jsonl``
under the incidents dir — the CI failure artifact).

Constructed only when a manifest is configured; makes zero telemetry
calls itself.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Mapping, Optional

__all__ = ["PlacementDecision", "PlacementPolicy"]


@dataclass(frozen=True)
class PlacementDecision:
    """One residency move: load ``model`` onto replica ``replica_id``."""

    model: str
    replica_id: int
    reason: str


@dataclass
class _ModelState:
    breach_streak: int = 0
    last_move_at: float = field(default=float("-inf"))


class PlacementPolicy:
    def __init__(
        self,
        registry: Any,
        *,
        default_p99_target_ms: float = 500.0,
        breach_consecutive: int = 3,
        cooldown_s: float = 30.0,
        min_window_samples: int = 20,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.registry = registry
        self.default_p99_target_ms = float(default_p99_target_ms)
        self.breach_consecutive = int(breach_consecutive)
        self.cooldown_s = float(cooldown_s)
        self.min_window_samples = int(min_window_samples)
        self.clock = clock
        self._state: Dict[str, _ModelState] = {}

    def _target_s(self, model: str) -> float:
        """The tightest class target any tenant could hold this model
        to; without classes, the fleet default."""
        targets = [
            c.p99_target_ms
            for c in getattr(self.registry, "classes", {}).values()
            if c.p99_target_ms is not None
        ]
        target_ms = min(targets) if targets else self.default_p99_target_ms
        return target_ms / 1e3

    def observe(
        self,
        by_model: Mapping[str, Mapping[str, Any]],
        placement: Mapping[int, List[str]],
        ready_replicas: List[int],
    ) -> List[PlacementDecision]:
        """One observe-decide cycle.

        ``by_model``: model → ``{"p99": seconds, "samples": int}`` (the
        fleet /metrics ``by_model`` slo_window, already merged);
        ``placement``: replica_id → resident model names (probe-learned);
        ``ready_replicas``: replica ids currently routable.
        """
        now = self.clock()
        decisions: List[PlacementDecision] = []
        for model in sorted(by_model):
            obs = by_model[model]
            p99 = obs.get("p99")
            samples = int(obs.get("samples") or 0)
            state = self._state.setdefault(model, _ModelState())
            if (
                not isinstance(p99, (int, float))
                or samples < self.min_window_samples
                or float(p99) <= self._target_s(model)
            ):
                state.breach_streak = 0
                continue
            state.breach_streak += 1
            if state.breach_streak < self.breach_consecutive:
                continue
            if now - state.last_move_at < self.cooldown_s:
                continue
            hosts = {
                rid for rid, models in placement.items() if model in models
            }
            candidates = [rid for rid in ready_replicas if rid not in hosts]
            if not candidates:
                # every ready replica already hosts it: placement is
                # saturated — replica-COUNT scaling is the next lever,
                # and that is the base autoscaler's job
                state.breach_streak = 0
                continue
            target = min(
                candidates, key=lambda rid: len(placement.get(rid, []))
            )
            decisions.append(
                PlacementDecision(
                    model=model,
                    replica_id=target,
                    reason=(
                        f"window p99 {float(p99) * 1e3:.0f}ms > target "
                        f"{self._target_s(model) * 1e3:.0f}ms for "
                        f"{state.breach_streak} consecutive observations"
                    ),
                )
            )
            state.breach_streak = 0
            state.last_move_at = now
        return decisions
