"""Multi-tenant, multi-model serving (docs/SERVING.md "Multi-model
fleet"): model registry + request resolution, per-tenant token-bucket
quotas + SLO-class weighted fair queuing, replica model residency with
an LRU hot set, and placement-aware scaling.

Everything here is OPT-IN via a manifest (``--model-manifest``): with
no manifest configured, none of these objects is constructed and the
single-model serving path is bit-identical to before this subsystem
existed.
"""

from .admission import AdmissionController, TokenBucket
from .placement import PlacementDecision, PlacementPolicy
from .registry import (
    MODEL_HEADER,
    MODEL_PATH_RE,
    TENANT_HEADER,
    ClassSpec,
    ModelRegistry,
    ModelSpec,
    TenantSpec,
)
from .residency import ResidencyManager

__all__ = [
    "MODEL_HEADER",
    "TENANT_HEADER",
    "MODEL_PATH_RE",
    "ClassSpec",
    "TenantSpec",
    "ModelSpec",
    "ModelRegistry",
    "AdmissionController",
    "TokenBucket",
    "ResidencyManager",
    "PlacementDecision",
    "PlacementPolicy",
]
