"""Replica model residency: an LRU hot set of warmed engines
(docs/SERVING.md "Multi-model fleet").

PR 8's staged-then-flip swap replaced the WEIGHTS of one model at a
dispatch boundary; residency generalizes the same discipline to WHICH
MODELS a replica hosts. A replica holds up to ``capacity`` engines —
each a full ``InferenceEngine`` with its own dispatch thread and its
own per-model warmed bucket programs — keyed by registry model name:

* a request for a resident model touches the LRU order and submits —
  the hot path takes one dict lookup under the manager lock, and is
  NEVER blocked by another model's cold load;
* a request for a known-but-absent model triggers a load (pipeline
  from disk + warmup sweep) OUTSIDE the manager lock; concurrent
  requests for the same model wait on one load instead of stampeding;
* once over capacity, the least-recently-used engine is evicted at its
  dispatch boundary: ``drain`` lets queued batches finish, ``stop``
  releases the device buffers. An eviction is a refused residency,
  never a dropped request — in-flight work on the victim completes.

The manager itself makes ZERO telemetry calls (the guard extends to
this subsystem); engines carry whatever telemetry the injected factory
gives them. The clock is injectable for LRU-order tests.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..batcher import ServingError, UnknownModel

__all__ = ["ResidencyManager"]

logger = logging.getLogger("spacy_ray_tpu.serving")


class ResidencyManager:
    """``engine_factory(spec) -> engine`` must return a STARTED, WARMED
    engine (the server's factory builds ``InferenceEngine`` + ``warmup``
    + ``start`` with the replica's serving knobs); the manager only
    decides which engines exist."""

    def __init__(
        self,
        registry: Any,
        engine_factory: Callable[[Any], Any],
        *,
        capacity: int = 2,
        evict_drain_s: float = 5.0,
        pinned: Optional[set] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.registry = registry
        self.engine_factory = engine_factory
        self.capacity = int(capacity)
        self.evict_drain_s = float(evict_drain_s)
        # pinned models (the manifest's default, normally) are never
        # chosen as the LRU victim: the legacy /v1/parse contract says
        # the default model is ALWAYS servable without a cold load. When
        # everything else resident is pinned the hot set may transiently
        # exceed capacity rather than evict a pinned engine.
        self.pinned = set(pinned or ())
        self.clock = clock
        self._lock = threading.Lock()
        self._engines: Dict[str, Any] = {}
        self._last_used: Dict[str, float] = {}
        self._loading: Dict[str, threading.Event] = {}
        self._load_errors: Dict[str, str] = {}
        # residency churn ledger (plain ints; /healthz and the bench
        # record read them — no telemetry objects constructed here)
        self.loads = 0
        self.evictions = 0

    def adopt(self, name: str, engine: Any) -> None:
        """Pre-register an externally built engine without counting a
        load — the server's default engine, whose warmup/start the
        server lifecycle owns (listener-first banner), arrives here."""
        self.registry.spec(name)  # typed 404 for unknown names
        with self._lock:
            self._engines[name] = engine
            self._last_used[name] = self.clock()

    # -- hot path --------------------------------------------------------
    def engine_for(self, name: str, *, load: bool = True) -> Any:
        """The engine serving ``name``, loading it into the hot set if
        absent (and ``load``). Raises ``UnknownModel`` for names the
        registry does not know; raises ``ServingError`` when a load
        fails (the model stays non-resident — a failed load is a
        refused load, never a half-resident engine)."""
        spec = self.registry.spec(name)  # typed 404 for unknown names
        while True:
            with self._lock:
                engine = self._engines.get(name)
                if engine is not None:
                    self._last_used[name] = self.clock()
                    return engine
                if not load:
                    raise ServingError(
                        f"model {name!r} is not resident on this replica"
                    )
                ev = self._loading.get(name)
                if ev is None:
                    ev = self._loading[name] = threading.Event()
                    break  # this thread leads the load
            # another thread is loading this model: wait, then re-check
            ev.wait()
            with self._lock:
                err = self._load_errors.get(name)
            if err is not None:
                raise ServingError(f"model {name!r} failed to load: {err}")
        return self._load(name, spec, ev)

    def _load(self, name: str, spec: Any, ev: threading.Event) -> Any:
        """Leader path: build the engine outside the lock (seconds of
        from-disk + warmup must not block resident models), insert,
        then evict past capacity."""
        started = self.clock()
        try:
            engine = self.engine_factory(spec)
        except Exception as exc:
            with self._lock:
                self._load_errors[name] = str(exc)
                self._loading.pop(name, None)
            ev.set()
            logger.exception("model %r load failed", name)
            raise ServingError(f"model {name!r} failed to load: {exc}")
        victims: List[Any] = []
        with self._lock:
            self._engines[name] = engine
            self._last_used[name] = self.clock()
            self._load_errors.pop(name, None)
            self._loading.pop(name, None)
            self.loads += 1
            while len(self._engines) > self.capacity:
                lru = min(
                    (
                        m for m in self._engines
                        if m != name and m not in self.pinned
                    ),
                    key=lambda m: self._last_used[m],
                    default=None,
                )
                if lru is None:
                    break
                victims.append((lru, self._engines.pop(lru)))
                self._last_used.pop(lru, None)
                self.evictions += 1
        ev.set()
        for victim_name, victim in victims:
            self._retire(victim_name, victim)
        logger.info(
            "model %r resident after %.2fs (hot set: %s)",
            name, self.clock() - started, self.resident(),
        )
        return engine

    def _retire(self, name: str, engine: Any) -> None:
        """Evict at the dispatch boundary: queued batches finish, then
        the dispatch thread stops and device buffers are released."""
        try:
            engine.drain(self.evict_drain_s)
        except Exception:
            logger.exception("evicting model %r: drain failed", name)
        try:
            engine.stop()
        except Exception:
            logger.exception("evicting model %r: stop failed", name)
        logger.info("model %r evicted (LRU)", name)

    # -- introspection ---------------------------------------------------
    def engines(self) -> Dict[str, Any]:
        """A point-in-time copy of the hot set (the /metrics per-model
        snapshot walk reads this; an engine may be evicted right after,
        which is fine — snapshots of a draining engine are still true)."""
        with self._lock:
            return dict(self._engines)

    def resident(self) -> List[str]:
        """Resident model names, least- to most-recently used."""
        with self._lock:
            return sorted(self._engines, key=lambda m: self._last_used[m])

    def resident_info(self) -> Dict[str, Dict[str, Any]]:
        """Per-model residency facts for /healthz: the router's probe
        loop learns placement from this block for free."""
        with self._lock:
            engines = dict(self._engines)
        out: Dict[str, Dict[str, Any]] = {}
        for name, engine in engines.items():
            out[name] = {
                "generation": getattr(engine, "serving_generation", None),
                "swap_count": int(getattr(engine, "swap_count", 0) or 0),
                "warmed": bool(getattr(engine, "warmed", False)),
            }
        return out

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            resident = sorted(
                self._engines, key=lambda m: self._last_used[m]
            )
            return {
                "resident": resident,
                "capacity": self.capacity,
                "loads": self.loads,
                "evictions": self.evictions,
                "residency_swaps": self.loads + self.evictions,
            }

    # -- lifecycle -------------------------------------------------------
    def begin_drain(self) -> None:
        with self._lock:
            engines = list(self._engines.items())
        for _, engine in engines:
            batcher = getattr(engine, "batcher", None)
            if batcher is not None:
                batcher.begin_drain()

    def stop_all(self, drain_timeout_s: Optional[float] = None) -> bool:
        """Drain + stop every resident engine (server shutdown). Returns
        True iff every drain completed within its timeout."""
        timeout = (
            self.evict_drain_s if drain_timeout_s is None
            else float(drain_timeout_s)
        )
        with self._lock:
            engines = list(self._engines.items())
            self._engines.clear()
            self._last_used.clear()
        clean = True
        for name, engine in engines:
            try:
                if not engine.drain(timeout):
                    clean = False
            except Exception:
                logger.exception("stopping model %r: drain failed", name)
                clean = False
            try:
                engine.stop()
            except Exception:
                logger.exception("stopping model %r: stop failed", name)
                clean = False
        return clean
