"""Model registry + request resolution for multi-tenant, multi-model
serving (docs/SERVING.md "Multi-model fleet").

One manifest file declares everything the fleet needs to serve many
pipelines to many tenants: the model catalog (name → pipeline dir), the
SLO classes (weight for fair queuing + a per-class window-p99 target),
and the tenants (class membership + token-bucket quota). The router and
every replica load the SAME manifest, so "which model is this request
for" and "which class does this tenant ride in" resolve identically at
the edge and at the device.

Resolution contract (property-tested):

* path wins: ``/v1/models/<name>/parse`` names the model explicitly and
  overrides any header;
* the ``X-SRT-Model`` header selects a model on the legacy ``/v1/parse``
  path;
* neither present → the manifest's ``default_model`` — which is what
  preserves the legacy single-model contract bit-identically (a client
  that never heard of models sees no difference);
* an unknown name → typed 404 ``unknown_model`` (batcher.UnknownModel),
  never a silent fallback: serving the default under the wrong name
  would poison the per-model cache and per-model SLO accounting.
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Dict, List, Mapping, Optional, Tuple

from ..batcher import UnknownModel

__all__ = [
    "MODEL_HEADER",
    "TENANT_HEADER",
    "MODEL_PATH_RE",
    "ClassSpec",
    "TenantSpec",
    "ModelSpec",
    "ModelRegistry",
]

# request headers (the path form wins over MODEL_HEADER; TENANT_HEADER
# absent → the anonymous default tenant: default class, no quota)
MODEL_HEADER = "X-SRT-Model"
TENANT_HEADER = "X-SRT-Tenant"

# /v1/models/<name>/parse — name restricted to sane token characters so
# a hostile path segment can never smuggle separators into cache keys,
# Prometheus labels, or forwarded URLs
MODEL_PATH_RE = re.compile(r"\A/v1/models/([A-Za-z0-9._-]{1,64})/parse\Z")

_NAME_RE = re.compile(r"\A[A-Za-z0-9._-]{1,64}\Z")

DEFAULT_CLASS = "default"


@dataclass(frozen=True)
class ClassSpec:
    """One SLO class: ``weight`` is the fair-queuing share (docs
    dispatched under saturation converge to the weight ratio), and
    ``p99_target_ms`` is the window-p99 bound the placement policy and
    the bench isolation contract judge this class against."""

    name: str
    weight: float = 1.0
    p99_target_ms: Optional[float] = None


@dataclass(frozen=True)
class TenantSpec:
    """One tenant: class membership plus an optional token-bucket quota
    in DOCS per second (docs are the serving cost unit everywhere —
    queue bounds, batch occupancy — so quotas meter the same thing).
    ``quota_docs_per_s`` None = unlimited (the anonymous default)."""

    name: str
    klass: str = DEFAULT_CLASS
    quota_docs_per_s: Optional[float] = None
    quota_burst: Optional[float] = None


@dataclass(frozen=True)
class ModelSpec:
    """One servable pipeline: ``path`` is a spaCy pipeline directory
    exactly like the ``serve`` command's positional argument."""

    name: str
    path: str


class ModelRegistry:
    """The manifest, parsed and validated once; immutable thereafter.

    Construction performs NO I/O beyond reading the manifest file and
    NO telemetry: the zero-telemetry-calls guard extends to this whole
    subsystem (a registry is pure lookup tables).
    """

    def __init__(
        self,
        models: Dict[str, ModelSpec],
        default_model: str,
        classes: Optional[Dict[str, ClassSpec]] = None,
        tenants: Optional[Dict[str, TenantSpec]] = None,
    ) -> None:
        if not models:
            raise ValueError("manifest declares no models")
        for name in models:
            if not _NAME_RE.match(name):
                raise ValueError(f"invalid model name {name!r}")
        if default_model not in models:
            raise ValueError(
                f"default_model {default_model!r} is not in the manifest's "
                f"models ({sorted(models)})"
            )
        self.models: Dict[str, ModelSpec] = dict(models)
        self.default_model = default_model
        self.classes: Dict[str, ClassSpec] = dict(classes or {})
        # the default class always exists (weight 1.0): the anonymous
        # tenant and any tenant without a class ride in it
        self.classes.setdefault(DEFAULT_CLASS, ClassSpec(DEFAULT_CLASS))
        for cname, spec in self.classes.items():
            if not (spec.weight > 0):
                raise ValueError(
                    f"class {cname!r} weight must be > 0, got {spec.weight!r}"
                )
        self.tenants: Dict[str, TenantSpec] = dict(tenants or {})
        for tname, tspec in self.tenants.items():
            if tspec.klass not in self.classes:
                raise ValueError(
                    f"tenant {tname!r} names unknown class {tspec.klass!r}"
                )
            if (
                tspec.quota_docs_per_s is not None
                and not (tspec.quota_docs_per_s > 0)
            ):
                raise ValueError(
                    f"tenant {tname!r} quota_docs_per_s must be > 0"
                )

    # -- manifest I/O ----------------------------------------------------
    @classmethod
    def from_manifest(cls, path: str) -> "ModelRegistry":
        """Parse a JSON manifest::

            {
              "default_model": "tagger",
              "models": {"tagger": {"path": "models/tagger"},
                         "ner":    {"path": "models/ner"}},
              "classes": {"gold":  {"weight": 4, "p99_target_ms": 500},
                          "batch": {"weight": 1, "p99_target_ms": 5000}},
              "tenants": {"acme":  {"class": "gold",
                                    "quota_docs_per_s": 200,
                                    "quota_burst": 400}}
            }

        Relative model paths resolve against the manifest's directory,
        so a manifest travels with its models.
        """
        p = Path(path)
        raw = json.loads(p.read_text(encoding="utf-8"))
        if not isinstance(raw, dict):
            raise ValueError(f"manifest {path} is not a JSON object")
        models_raw = raw.get("models")
        if not isinstance(models_raw, dict) or not models_raw:
            raise ValueError(f"manifest {path} has no 'models' table")
        models: Dict[str, ModelSpec] = {}
        for name, m in models_raw.items():
            if not isinstance(m, dict) or "path" not in m:
                raise ValueError(
                    f"manifest model {name!r} needs a 'path' entry"
                )
            mpath = Path(str(m["path"]))
            if not mpath.is_absolute():
                mpath = p.parent / mpath
            models[str(name)] = ModelSpec(name=str(name), path=str(mpath))
        default_model = str(raw.get("default_model") or "")
        if not default_model:
            if len(models) == 1:
                default_model = next(iter(models))
            else:
                raise ValueError(
                    f"manifest {path} needs 'default_model' when it "
                    "declares more than one model"
                )
        classes: Dict[str, ClassSpec] = {}
        for cname, c in (raw.get("classes") or {}).items():
            if not isinstance(c, dict):
                raise ValueError(f"manifest class {cname!r} must be an object")
            classes[str(cname)] = ClassSpec(
                name=str(cname),
                weight=float(c.get("weight", 1.0)),
                p99_target_ms=(
                    float(c["p99_target_ms"])
                    if c.get("p99_target_ms") is not None else None
                ),
            )
        tenants: Dict[str, TenantSpec] = {}
        for tname, t in (raw.get("tenants") or {}).items():
            if not isinstance(t, dict):
                raise ValueError(
                    f"manifest tenant {tname!r} must be an object"
                )
            tenants[str(tname)] = TenantSpec(
                name=str(tname),
                klass=str(t.get("class", DEFAULT_CLASS)),
                quota_docs_per_s=(
                    float(t["quota_docs_per_s"])
                    if t.get("quota_docs_per_s") is not None else None
                ),
                quota_burst=(
                    float(t["quota_burst"])
                    if t.get("quota_burst") is not None else None
                ),
            )
        return cls(models, default_model, classes=classes, tenants=tenants)

    # -- lookups ---------------------------------------------------------
    def names(self) -> List[str]:
        return sorted(self.models)

    def spec(self, name: str) -> ModelSpec:
        try:
            return self.models[name]
        except KeyError:
            raise UnknownModel(
                f"model {name!r} is not in the registry "
                f"(known: {self.names()})"
            ) from None

    def class_weights(self) -> Dict[str, float]:
        """``{class: weight}`` for the batcher's weighted fair queue."""
        return {c.name: c.weight for c in self.classes.values()}

    def tenant(self, name: Optional[str]) -> TenantSpec:
        """The tenant spec for a (possibly absent) tenant header. An
        unknown or missing tenant is the ANONYMOUS tenant: default
        class, no quota — the legacy contract for clients that never
        heard of tenancy."""
        if name is not None and name in self.tenants:
            return self.tenants[name]
        return TenantSpec(name=name or "anonymous")

    def p99_target_ms(self, klass: str) -> Optional[float]:
        spec = self.classes.get(klass)
        return spec.p99_target_ms if spec is not None else None

    # -- request resolution ---------------------------------------------
    def resolve_model(
        self, path: str, headers: Optional[Mapping[str, str]] = None
    ) -> Tuple[str, bool]:
        """Resolve the model a request names. Returns ``(name,
        explicit)`` where ``explicit`` is True when the client named the
        model (path or header) rather than falling through to the
        default. Raises ``UnknownModel`` (typed 404) for a name the
        registry does not know, and for any path that is neither
        ``/v1/parse`` nor a well-formed ``/v1/models/<name>/parse``.

        Precedence: path > header > default_model.
        """
        m = MODEL_PATH_RE.match(path)
        if m:
            name = m.group(1)
            self.spec(name)  # raises UnknownModel
            return name, True
        if path.startswith("/v1/models/"):
            raise UnknownModel(
                f"malformed model path {path!r} (expected "
                "/v1/models/<name>/parse)"
            )
        header = None
        if headers is not None:
            header = headers.get(MODEL_HEADER)
        if header:
            self.spec(header)  # raises UnknownModel
            return header, True
        return self.default_model, False

    def describe(self) -> Dict[str, Any]:
        """JSON-safe summary (healthz / metrics surfaces)."""
        return {
            "default_model": self.default_model,
            "models": self.names(),
            "classes": {
                c.name: {
                    "weight": c.weight, "p99_target_ms": c.p99_target_ms,
                }
                for c in self.classes.values()
            },
            "tenants": sorted(self.tenants),
        }
