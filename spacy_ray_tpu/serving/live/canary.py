"""Canary guard: the promote/rollback policy for generation rollouts.

The guard consumes exactly the signal the serving stack already emits —
per-generation error/request counters and the sliding-window latency
percentiles (the PR 7 ``slo_window`` block, split by generation in
``merge_serving_snapshots``) — and answers one question per tick: keep
the canary, kill it, or keep watching.

Design rules, borrowed from the autoscaler (the repo's other control
loop, fleet/autoscaler.py), because boring is what you want when the
action is "rewire production traffic":

* **Counter deltas, not lifetimes.** Replica counters are process-
  lifetime; a canary replica carries its pre-swap history into the new
  generation's group. :meth:`begin` snapshots both sides' counters at
  canary start, so error rates are measured over canary traffic only.
* **Hysteresis both ways.** A rollback needs ``bad_consecutive``
  CONSECUTIVE breaching ticks (one latency blip must not kill a good
  generation); a promote needs ``good_consecutive`` clean ticks AND a
  minimum canary sample count (a canary that served three requests has
  proven nothing).
* **No-signal is not good news.** Missing percentiles (idle window) or
  too few samples HOLD the rollout; only evidence promotes. The
  asymmetry vs the autoscaler (where no-signal means no-pressure) is
  deliberate: scaling up on silence wastes a replica; promoting on
  silence ships an unvalidated model.

Every verdict is a structured ``log_event`` row; the controller turns
it into admin swap/rollback calls.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from ...training.resilience import log_event

__all__ = ["GenerationStats", "CanaryGuard"]


@dataclass
class GenerationStats:
    """One generation group's signal for one tick — distilled from a
    ``by_generation`` entry of ``merge_serving_snapshots`` (or built
    directly in tests)."""

    generation: Optional[int] = None
    requests: float = 0.0            # lifetime counter (delta'd by guard)
    errors: float = 0.0              # lifetime counter (delta'd by guard)
    window_samples: int = 0          # latency samples in the slo window
    p99_s: Optional[float] = None    # sliding-window p99 (worst replica)

    @classmethod
    def from_merged(
        cls, block: Optional[Dict[str, Any]], generation: Optional[int] = None
    ) -> "GenerationStats":
        """Distill a merged per-generation metrics block. Missing pieces
        stay at no-signal defaults — the guard treats those as "hold",
        never as evidence."""
        if not isinstance(block, dict):
            return cls(generation=generation)
        counters = block.get("counters") or {}
        win = block.get("slo_window") or {}
        p99 = win.get("request_latency_p99_worst")
        if not isinstance(p99, (int, float)):
            p99 = win.get("request_latency_p99")
        # "errors" for guard purposes = dispatch failures PLUS request
        # timeouts: a generation that blows every deadline produces no
        # 500s and no latency samples (timed-out requests never reach
        # the latency histogram), so deadline_exceeded is the ONLY
        # signal that distinguishes it from a healthy canary
        errors = float(counters.get("errors") or 0.0) + float(
            counters.get("deadline_exceeded") or 0.0
        )
        return cls(
            generation=block.get("generation", generation),
            requests=float(counters.get("requests") or 0.0),
            errors=errors,
            window_samples=int(win.get("samples") or 0),
            p99_s=float(p99) if isinstance(p99, (int, float)) else None,
        )


class CanaryGuard:
    """Feed :meth:`observe` once per tick during a rollout; it returns
    ``"promote"``, ``"rollback"``, or None (keep watching).

    Rollback triggers (either, for ``bad_consecutive`` ticks):

    * canary error rate above ``error_rate_high`` AND above the
      baseline's rate over the same interval (an absolute cap alone
      would kill a canary for inheriting a fleet-wide problem);
    * canary window p99 above ``p99_frac`` x baseline window p99, both
      windows holding >= ``min_window_samples`` samples.

    Promote requires ``good_consecutive`` consecutive clean ticks with
    >= ``min_canary_requests`` canary requests observed since
    :meth:`begin` — and "clean" includes a latency verdict: either both
    windows have enough samples and the canary is within budget, or the
    baseline has no latency signal to compare against (single-replica
    fleets, idle baselines) and the error-rate evidence stands alone.
    """

    def __init__(
        self,
        *,
        p99_frac: float = 1.5,
        error_rate_high: float = 0.02,
        min_window_samples: int = 20,
        min_canary_requests: int = 20,
        bad_consecutive: int = 2,
        good_consecutive: int = 3,
    ) -> None:
        if p99_frac <= 0:
            raise ValueError("p99_frac must be > 0")
        if not (0.0 <= error_rate_high <= 1.0):
            raise ValueError("error_rate_high must be within 0..1")
        if bad_consecutive < 1 or good_consecutive < 1:
            raise ValueError("hysteresis windows must be >= 1 tick")
        self.p99_frac = float(p99_frac)
        self.error_rate_high = float(error_rate_high)
        self.min_window_samples = int(min_window_samples)
        self.min_canary_requests = int(min_canary_requests)
        self.bad_consecutive = int(bad_consecutive)
        self.good_consecutive = int(good_consecutive)
        self._bad_streak = 0
        self._good_streak = 0
        self._base0: Dict[str, float] = {}
        self.decisions: List[Dict[str, Any]] = []

    # -- rollout lifecycle ----------------------------------------------
    def begin(
        self, baseline: GenerationStats, canary: GenerationStats
    ) -> None:
        """Mark canary start: snapshot both sides' lifetime counters so
        every later tick measures THIS rollout's traffic only."""
        self._bad_streak = self._good_streak = 0
        self._base0 = {
            "canary_requests": canary.requests,
            "canary_errors": canary.errors,
            "baseline_requests": baseline.requests,
            "baseline_errors": baseline.errors,
        }

    # -- the tick --------------------------------------------------------
    def observe(
        self, baseline: GenerationStats, canary: GenerationStats
    ) -> Optional[str]:
        c_req = max(canary.requests - self._base0.get("canary_requests", 0.0), 0.0)
        c_err = max(canary.errors - self._base0.get("canary_errors", 0.0), 0.0)
        b_req = max(
            baseline.requests - self._base0.get("baseline_requests", 0.0), 0.0
        )
        b_err = max(
            baseline.errors - self._base0.get("baseline_errors", 0.0), 0.0
        )
        c_rate = c_err / c_req if c_req > 0 else 0.0
        b_rate = b_err / b_req if b_req > 0 else 0.0

        reasons: List[str] = []
        if (
            c_req >= self.min_canary_requests
            and c_rate > self.error_rate_high
            and c_rate > b_rate
        ):
            reasons.append(
                f"error rate {c_rate:.3f} > {self.error_rate_high:.3f} "
                f"(baseline {b_rate:.3f})"
            )
        latency_comparable = (
            canary.p99_s is not None
            and baseline.p99_s is not None
            and canary.window_samples >= self.min_window_samples
            and baseline.window_samples >= self.min_window_samples
        )
        if (
            latency_comparable
            and canary.p99_s > self.p99_frac * baseline.p99_s  # type: ignore[operator]
        ):
            reasons.append(
                f"window p99 {canary.p99_s:.4f}s > {self.p99_frac:.2f} x "
                f"baseline {baseline.p99_s:.4f}s"
            )

        bad = bool(reasons)
        self._bad_streak = self._bad_streak + 1 if bad else 0
        if bad:
            self._good_streak = 0
        else:
            # a clean tick only counts toward promote once the canary
            # has seen real traffic AND carries a latency verdict: the
            # canary within budget against a comparable baseline, or a
            # baseline with no latency signal at all (then the
            # error-rate evidence stands alone). A baseline WITH signal
            # but a canary window too thin to compare is silence, and
            # silence must not promote — it holds, and the verdict
            # timeout eventually rolls it back.
            baseline_has_signal = (
                baseline.p99_s is not None
                and baseline.window_samples >= self.min_window_samples
            )
            latency_ok = not baseline_has_signal or (
                latency_comparable
                and canary.p99_s <= self.p99_frac * baseline.p99_s  # type: ignore[operator]
            )
            if c_req >= self.min_canary_requests and latency_ok:
                self._good_streak += 1
        if self._bad_streak >= self.bad_consecutive:
            return self._decide(
                "rollback", baseline, canary, c_req, c_rate, b_rate,
                "; ".join(reasons),
            )
        if self._good_streak >= self.good_consecutive:
            # latency evidence when comparable; error-rate evidence alone
            # when the baseline has nothing to compare against
            return self._decide(
                "promote", baseline, canary, c_req, c_rate, b_rate,
                "canary healthy over "
                f"{self._good_streak} consecutive tick(s)",
            )
        return None

    def _decide(
        self,
        verdict: str,
        baseline: GenerationStats,
        canary: GenerationStats,
        c_req: float,
        c_rate: float,
        b_rate: float,
        why: str,
    ) -> str:
        decision = {
            "verdict": verdict,
            "canary_generation": canary.generation,
            "baseline_generation": baseline.generation,
            "canary_requests": c_req,
            "canary_error_rate": round(c_rate, 4),
            "baseline_error_rate": round(b_rate, 4),
            "canary_p99_s": canary.p99_s,
            "baseline_p99_s": baseline.p99_s,
            "why": why,
        }
        self.decisions.append(decision)
        self._bad_streak = self._good_streak = 0
        log_event(
            f"canary-{verdict}",
            f"generation {canary.generation} vs {baseline.generation}: "
            f"{verdict} ({why})",
            level=logging.WARNING if verdict == "rollback" else logging.INFO,
            **decision,
        )
        return verdict
