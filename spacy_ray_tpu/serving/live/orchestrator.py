"""Train-and-serve orchestration: one training subprocess and one
serving fleet sharing a checkpoint directory, under a single
ShutdownCoordinator — the continuous-learning loop as one command.

Process tree (``spacy_ray_tpu train-and-serve``)::

    train-and-serve                      <- this process (coordinator)
      |- train subprocess                -> writes <output>/last-model/
      |                                     generations (digest-stamped)
      |- Fleet (router + controller)     <- watches <output>/last-model
           |- serve replica #0..N-1      <- hot-swap via /admin/swap

Lifecycle contracts:

* **Bootstrap.** The fleet needs a servable model directory before
  training has produced anything. Either the caller supplies one
  (``FleetConfig.model_path`` already set — serve the previous best
  while the new run improves it), or the orchestrator waits for the
  training run's first ``best-model/`` save and snapshots it into
  ``<output>/serve-bootstrap`` (a copy, because ``best-model/`` is
  rewritten in place on every improvement and a replica must never read
  a directory mid-rewrite).
* **SIGTERM drains BOTH, in parallel.** The coordinator callback
  forwards SIGTERM to the trainer (its step-boundary preemption path:
  checkpoint, exit :data:`~...training.resilience.RC_PREEMPTED`) and
  trips the fleet drain (router stops admitting, replicas finish
  in-flight work). Exit 0 iff the fleet drained clean AND the trainer
  exited 0 (finished) or RC_PREEMPTED (checkpointed out) — preemption
  is the *designed* shutdown here, not a failure.
* **A dead trainer does not kill serving.** A trainer crash is a loud
  structured event; the fleet keeps serving the last good generation —
  that is the entire point of generation-verified hot-swap.
"""

from __future__ import annotations

import logging
import shutil
import subprocess
import threading
import time
from collections import deque
from pathlib import Path
from typing import Callable, Dict, List, Optional

from ...training.resilience import (
    RC_PREEMPTED,
    ShutdownCoordinator,
    log_event,
    terminate_with_grace,
)

__all__ = ["TrainAndServe", "wait_for_best_model"]

logger = logging.getLogger("spacy_ray_tpu.serving")


def wait_for_best_model(
    output_dir,
    stop: threading.Event,
    *,
    timeout_s: float = 600.0,
    settle_s: float = 1.0,
    poll_s: float = 0.5,
) -> Optional[Path]:
    """Block until ``<output>/best-model`` holds a complete model
    (config + params), then snapshot-copy it to
    ``<output>/serve-bootstrap`` and return that path. None on timeout
    or when ``stop`` is set first. ``settle_s`` lets the writer finish
    the sidecar files that land after params.npz before the copy."""
    output_dir = Path(output_dir)
    best = output_dir / "best-model"
    deadline = time.monotonic() + float(timeout_s)
    while not stop.is_set() and time.monotonic() < deadline:
        if (best / "config.cfg").exists() and (best / "params.npz").exists():
            stop.wait(settle_s)
            snapshot = output_dir / "serve-bootstrap"
            try:
                # best-model/ is rewritten IN PLACE on every improvement
                # (per-file os.replace) — a copy racing the rewrite can
                # see a listed file vanish mid-walk. That is a retry,
                # not a failure: loop around and copy the newer save.
                shutil.rmtree(snapshot, ignore_errors=True)
                shutil.copytree(best, snapshot)
            except OSError:
                stop.wait(poll_s)
                continue
            return snapshot
        stop.wait(poll_s)
    return None


class TrainAndServe:
    """Own the whole loop: spawn the trainer, bootstrap a model,
    run the fleet, drain both on shutdown.

    ``fleet_config.watch_dir`` should point at ``<output>/last-model``
    (the CLI wires this); ``fleet_config.model_path`` may be empty, in
    which case ``model_bootstrap`` (default: :func:`wait_for_best_model`
    over ``output_dir``) supplies it after training starts.
    """

    def __init__(
        self,
        train_cmd: List[str],
        fleet_config,
        *,
        output_dir,
        train_env: Optional[Dict[str, str]] = None,
        model_bootstrap: Optional[
            Callable[["TrainAndServe"], Optional[Path]]
        ] = None,
        bootstrap_timeout_s: float = 600.0,
        train_grace_s: float = 75.0,
    ) -> None:
        self.train_cmd = list(train_cmd)
        self.fleet_config = fleet_config
        self.output_dir = Path(output_dir)
        self.train_env = train_env
        self.model_bootstrap = model_bootstrap
        self.bootstrap_timeout_s = float(bootstrap_timeout_s)
        self.train_grace_s = float(train_grace_s)
        self.train_proc: Optional[subprocess.Popen] = None
        self.train_rc: Optional[int] = None
        self.fleet = None
        self.train_tail: "deque[str]" = deque(maxlen=40)
        self._shutdown = threading.Event()

    # -- shutdown (signal-handler-safe: flag + signal forward only) ------
    def request_shutdown(self, signum: Optional[int] = None) -> None:
        self._shutdown.set()
        fleet = self.fleet
        if fleet is not None:
            fleet.request_shutdown(signum)
        proc = self.train_proc
        if proc is not None and proc.poll() is None:
            try:
                proc.terminate()  # the trainer's preemption path
            except OSError:
                pass

    # -- trainer ---------------------------------------------------------
    def _spawn_train(self) -> None:
        import os

        env = dict(os.environ)
        if self.train_env:
            env.update(self.train_env)
        self.train_proc = subprocess.Popen(
            self.train_cmd,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        threading.Thread(
            target=self._relay_train_output, daemon=True, name="train-stdout"
        ).start()

    def _relay_train_output(self) -> None:
        proc = self.train_proc
        assert proc is not None and proc.stdout is not None
        try:
            for line in proc.stdout:
                line = line.rstrip("\n")
                self.train_tail.append(line)
                print(f"[train] {line}", flush=True)
        except (ValueError, OSError):
            pass
        rc = proc.wait()
        self.train_rc = rc
        if self._shutdown.is_set() or rc in (0, RC_PREEMPTED):
            return
        # crash while we were supposed to keep learning: loud event,
        # serving continues on the last good generation
        tail = " | ".join(list(self.train_tail)[-3:])
        log_event(
            "train-and-serve-trainer-crash",
            f"training subprocess exited rc={rc} — the fleet keeps "
            "serving the last promoted generation"
            + (f" (last output: {tail})" if tail else ""),
            rc=rc,
        )

    def _stop_train(self) -> Optional[int]:
        proc = self.train_proc
        if proc is None:
            return None
        if proc.poll() is None:
            if self._shutdown.is_set():
                # the coordinator callback already SIGTERMed the trainer;
                # it is mid-drain (checkpointing at a step boundary). A
                # second SIGTERM could land AFTER it restored default
                # handlers and kill the graceful exit (-15 instead of
                # 75) — wait for the exit it is already performing,
                # escalate only past the grace budget
                try:
                    rc: Optional[int] = proc.wait(
                        timeout=self.train_grace_s
                    )
                except subprocess.TimeoutExpired:
                    rc = terminate_with_grace(proc, grace_s=5.0)
            else:
                rc = terminate_with_grace(proc, grace_s=self.train_grace_s)
        else:
            rc = proc.returncode
        self.train_rc = rc
        return rc

    def _train_clean(self) -> bool:
        # 0 = ran to completion; RC_PREEMPTED = checkpointed out on our
        # SIGTERM — the designed shutdown, not a failure
        return self.train_rc in (0, RC_PREEMPTED)

    # -- the run ---------------------------------------------------------
    def run(self, *, banner: bool = True) -> int:
        from ..fleet import Fleet

        coordinator = ShutdownCoordinator()
        coordinator.add_callback(self.request_shutdown)
        coordinator.install()
        try:
            self._spawn_train()
            assert self.train_proc is not None
            if banner:
                print(
                    f"train-and-serve: training pid {self.train_proc.pid} "
                    f"-> {self.output_dir}",
                    flush=True,
                )
            if not self.fleet_config.model_path:
                bootstrap = self.model_bootstrap or (
                    lambda ts: wait_for_best_model(
                        ts.output_dir, ts._shutdown,
                        timeout_s=ts.bootstrap_timeout_s,
                    )
                )
                model_path = bootstrap(self)
                if model_path is None:
                    rc = self._stop_train()
                    if self._shutdown.is_set():
                        # SIGTERM before serving began: clean iff the
                        # trainer checkpointed out cleanly
                        print("shutdown before fleet start; trainer "
                              f"exited {rc}", flush=True)
                        return 0 if self._train_clean() else 1
                    print(
                        "no best-model appeared within "
                        f"{self.bootstrap_timeout_s:.0f}s (trainer rc "
                        f"{rc}) — nothing to serve", flush=True,
                    )
                    return 1
                self.fleet_config.model_path = str(model_path)
                if banner:
                    print(
                        f"bootstrapped serving model from {model_path}",
                        flush=True,
                    )
            self.fleet = Fleet(self.fleet_config)
            if self._shutdown.is_set():
                # SIGTERM landed between bootstrap and fleet start: the
                # callback missed the fleet — trip it now, then drain
                self.fleet.request_shutdown()
            host, port = self.fleet.start()
            if banner:
                print(
                    f"train-and-serve fleet on http://{host}:{port} "
                    f"({self.fleet_config.replicas} replica(s), watching "
                    f"{self.fleet_config.watch_dir})",
                    flush=True,
                )
            if self.fleet.wait_ready() and banner:
                print(
                    f"fleet ready: "
                    f"{len(self.fleet.router.ready_handles())} replica(s) "
                    "warmed", flush=True,
                )
            fleet_rc = self.fleet.wait()
            train_rc = self._stop_train()
            clean = fleet_rc == 0 and self._train_clean()
            print(
                f"train-and-serve drained (fleet rc {fleet_rc}, trainer "
                f"rc {train_rc}{' = preempted-clean' if train_rc == RC_PREEMPTED else ''})",
                flush=True,
            )
            return 0 if clean else 1
        except BaseException:
            # an orchestrator crash must not orphan the training
            # subprocess it spawned — SIGTERM it (request_shutdown also
            # trips the fleet drain if one is running), reap it, then
            # surface the error
            self.request_shutdown()
            self._stop_train()
            raise
        finally:
            coordinator.restore()
