"""Live fleet controller: roll new checkpoint generations across a
serving fleet — canary first, guard verdict, promote or roll back.

Runs inside the fleet/router process (jax-free: generation detection is
the stdlib digest scan from :mod:`watcher`; replicas do their own param
loading behind their ``/admin/swap`` endpoint). One rollout at a time::

    idle --(new intact generation)--> canary phase
      canary subset swapped via POST /admin/swap
      router splits traffic by generation (canary_fraction)
      guard watches per-replica error rates + window p99
    --promote--> swap the rest, generation becomes current --> idle
    --rollback--> POST /admin/rollback to canaries, stamp rejected --> idle

Grouping during a rollout is by REPLICA ID, not by the generation tag
in the scraped metrics: the probe learns a replica's new generation with
up to one probe-interval of lag, and counter baselines must be
snapshotted at the instant of the swap — replica-id grouping makes both
exact while ``by_generation`` in the router's ``/metrics`` stays the
operator-facing view of the same split.

Failure posture: a 409 from ``/admin/swap`` (torn generation on the
replica's read, tree mismatch) permanently rejects the stamp; transient
errors (replica mid-restart) abort the attempt and the next poll
retries. A rollout that gets no guard verdict within
``verdict_timeout_s`` rolls back — generations ship on evidence, never
on silence. In idle phase the controller also HEALS stragglers: a
replica that crashed and restarted from the disk model (generation
None) is re-swapped to the fleet's current generation.
"""

from __future__ import annotations

import http.client
import json
import logging
import threading
import time
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from ...training.resilience import log_event
from .canary import CanaryGuard, GenerationStats
from .watcher import scan_intact_generations

__all__ = ["LiveFleetController"]

logger = logging.getLogger("spacy_ray_tpu.serving")


def _admin_post(
    addr: Tuple[str, int], path: str, payload: Dict[str, Any],
    timeout_s: float,
) -> Tuple[int, Dict[str, Any]]:
    conn = http.client.HTTPConnection(addr[0], addr[1], timeout=timeout_s)
    try:
        body = json.dumps(payload).encode("utf8")
        conn.request("POST", path, body, {"Content-Type": "application/json"})
        resp = conn.getresponse()
        raw = resp.read()
    finally:
        conn.close()
    try:
        parsed = json.loads(raw)
    except ValueError:
        parsed = {}
    return resp.status, parsed if isinstance(parsed, dict) else {}


class LiveFleetController:
    """Ticks via :meth:`poll_once` (deterministic for tests) or a
    background thread (:meth:`start`); ``router`` supplies the live
    replica view, traffic split, and metrics scrape."""

    def __init__(
        self,
        ckpt_dir,
        router,
        *,
        canary_fraction: float = 0.25,
        interval_s: float = 2.0,
        guard: Optional[CanaryGuard] = None,
        admin_timeout_s: float = 120.0,
        verdict_timeout_s: float = 120.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.ckpt_dir = Path(ckpt_dir)
        self.router = router
        self.canary_fraction = float(canary_fraction)
        self.interval_s = float(interval_s)
        self.guard = guard or CanaryGuard()
        self.admin_timeout_s = float(admin_timeout_s)
        self.verdict_timeout_s = float(verdict_timeout_s)
        self.clock = clock
        # rollout state
        self.phase = "idle"                      # "idle" | "canary"
        self.current: Optional[int] = None       # fleet-wide generation
        self.target: Optional[int] = None        # generation under canary
        self.canary_ids: List[int] = []
        self.rejected: Set[int] = set()          # rolled-back stamps
        self._verdict_deadline: Optional[float] = None
        self.rollouts = 0
        self.promotes = 0
        self.rollbacks = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- metrics grouping ------------------------------------------------
    def _side_stats(
        self, snaps: List[Dict[str, Any]], canary: bool
    ) -> GenerationStats:
        from ...training.telemetry import merge_serving_snapshots

        ids = set(self.canary_ids)
        side = [
            s for s in snaps
            if (s.get("replica_id") in ids) == canary
        ]
        merged = merge_serving_snapshots(side, _tag_generations=False)
        return GenerationStats.from_merged(
            merged, generation=self.target if canary else self.current
        )

    # -- one tick --------------------------------------------------------
    def poll_once(self) -> Optional[str]:
        """One observe-decide-act cycle. Returns "canary", "promote",
        "rollback", "heal", or None (nothing happened)."""
        if self.phase == "canary":
            return self._guard_tick()
        # filtered scan: only stamps we might actually roll out are
        # digest-verified (params only — the replica swap discards
        # opt_state and re-verifies on its own read anyway), so an idle
        # tick hashes NOTHING instead of re-hashing every retained
        # generation's gigabytes each poll
        candidates = scan_intact_generations(
            self.ckpt_dir,
            newer_than=self.current,
            skip=self.rejected,
            params_only=True,
        )
        if candidates:
            return self._begin_rollout(max(candidates))
        return self._heal_stragglers()

    # -- rollout start ---------------------------------------------------
    def _begin_rollout(self, stamp: int) -> Optional[str]:
        ready = self.router.ready_handles()
        if not ready:
            return None  # nobody to roll to; retry next tick
        n = len(ready)
        if 0.0 < self.canary_fraction < 1.0:
            k = max(1, int(round(self.canary_fraction * n)))
        else:
            k = n
        if k >= n:
            # no baseline to guard against: direct rollout (the
            # single-replica / canary-disabled path — each replica still
            # flips at a dispatch boundary, so zero requests drop)
            ok = True
            for h in ready:
                if not self._swap_one(h, stamp):
                    ok = False
            if ok:
                self.current = stamp
                self.rollouts += 1
                # generation changed fleet-wide: versioned cache keys
                # already make the old entries unhittable; the flush
                # reclaims their bytes eagerly (ROADMAP 3b)
                self.router.flush_cache(f"direct rollout to gen {stamp}")
                log_event(
                    "live-rollout-direct",
                    f"generation {stamp} rolled out to all {n} replica(s) "
                    "(no canary split configured/possible)",
                    level=logging.INFO,
                    generation=stamp,
                    replicas=n,
                )
                return "promote"
            return None  # partial: retried next tick (swap is idempotent)
        # canary subset: youngest replicas (same choice scale-down makes
        # — the oldest replicas hold the longest-proven baseline)
        canaries = sorted(ready, key=lambda h: -h.replica_id)[:k]
        snaps = self.router.scrape_replica_metrics()
        self.canary_ids = [h.replica_id for h in canaries]
        self.target = stamp
        baseline0 = self._side_stats(snaps, canary=False)
        canary0 = self._side_stats(snaps, canary=True)
        swapped: List[Any] = []
        for h in canaries:
            if self._swap_one(h, stamp):
                swapped.append(h)
                continue
            # abort: restore any canary already flipped, keep idle state
            for done in swapped:
                self._rollback_one(done)
            self.canary_ids = []
            self.target = None
            return None
        self.guard.begin(baseline0, canary0)
        self._verdict_deadline = self.clock() + self.verdict_timeout_s
        self.phase = "canary"
        # activate the router's traffic split for exactly this rollout:
        # outside it, generation heterogeneity (e.g. a crash-restarted
        # replica on the disk model) must NOT redirect traffic
        self.router.canary_generation = stamp
        self.rollouts += 1
        log_event(
            "live-canary-start",
            f"generation {stamp} canarying on replica(s) "
            f"{self.canary_ids} ({k}/{n}; fraction "
            f"{self.canary_fraction:.2f} of traffic)",
            level=logging.INFO,
            generation=stamp,
            canary_ids=list(self.canary_ids),
            replicas=n,
        )
        return "canary"

    # -- guard phase -----------------------------------------------------
    def _guard_tick(self) -> Optional[str]:
        assert self.target is not None
        # canaries gone entirely (scale-down SIGTERM'd them, or they all
        # crashed): there is no evidence to judge and never will be —
        # abort WITHOUT rejecting the stamp (its quality was never the
        # problem) so the next idle tick starts a fresh rollout
        ids = set(self.canary_ids)
        if not any(
            h.replica_id in ids for h in self.router.ready_handles()
        ):
            stamp = self.target
            self._finish_rollout()
            log_event(
                "live-canary-aborted",
                f"every canary replica for generation {stamp} left the "
                "fleet (scale-down or crash) — rollout aborted, stamp "
                "stays eligible for a fresh canary",
                generation=stamp,
                canary_ids=sorted(ids),
            )
            return None
        snaps = self.router.scrape_replica_metrics()
        baseline = self._side_stats(snaps, canary=False)
        canary = self._side_stats(snaps, canary=True)
        verdict = self.guard.observe(baseline, canary)
        if verdict is None and (
            self._verdict_deadline is not None
            and self.clock() >= self._verdict_deadline
        ):
            verdict = "rollback"
            log_event(
                "canary-verdict-timeout",
                f"generation {self.target} produced no guard verdict "
                f"within {self.verdict_timeout_s:.0f}s — rolling back "
                "(generations ship on evidence, not silence)",
                generation=self.target,
            )
        if verdict == "promote":
            return self._promote()
        if verdict == "rollback":
            return self._rollback()
        return None

    def _promote(self) -> str:
        assert self.target is not None
        stamp = self.target
        for h in self.router.ready_handles():
            if h.generation != stamp:
                self._swap_one(h, stamp)
        self.current = stamp
        self.promotes += 1
        # promotion hook (ROADMAP 3b): old-generation response-cache
        # entries are dead the moment the fleet converges on `stamp` —
        # generation-stamped keys guarantee they can't hit, the flush
        # reclaims their bytes
        self.router.flush_cache(f"promoted gen {stamp}")
        self._finish_rollout()
        log_event(
            "live-promote",
            f"generation {stamp} promoted fleet-wide",
            level=logging.INFO,
            generation=stamp,
        )
        return "promote"

    def _rollback(self) -> str:
        assert self.target is not None
        stamp = self.target
        ids = set(self.canary_ids)
        for h in self.router.ready_handles():
            if h.replica_id in ids:
                self._rollback_one(h)
        self.rejected.add(stamp)
        self.rollbacks += 1
        self._finish_rollout()
        log_event(
            "live-rollback",
            f"generation {stamp} rolled back off the canary set "
            f"{sorted(ids)}; stamp rejected until a newer one appears",
            generation=stamp,
            canary_ids=sorted(ids),
        )
        return "rollback"

    def _finish_rollout(self) -> None:
        self.phase = "idle"
        self.target = None
        self.canary_ids = []
        self._verdict_deadline = None
        self.router.canary_generation = None  # split off outside rollouts

    # -- idle-phase healing ---------------------------------------------
    def _heal_stragglers(self) -> Optional[str]:
        """A replica that crashed mid-life restarts from the disk model
        (generation None) — bring it to the fleet's current generation
        so the split stays two-sided only during actual rollouts."""
        if self.current is None:
            return None
        healed = False
        for h in self.router.ready_handles():
            if h.generation != self.current:
                healed = self._swap_one(h, self.current) or healed
        return "heal" if healed else None

    # -- replica admin ---------------------------------------------------
    def _swap_one(self, handle, stamp: int) -> bool:
        addr = handle.address
        if addr is None:
            return False
        try:
            status, payload = _admin_post(
                addr, "/admin/swap",
                {"dir": str(self.ckpt_dir), "generation": int(stamp)},
                self.admin_timeout_s,
            )
        except OSError as e:
            log_event(
                "live-swap-error",
                f"replica {handle.replica_id}: /admin/swap unreachable "
                f"({e!r}) — will retry",
                replica=handle.replica_id,
                generation=int(stamp),
            )
            return False
        if status == 200:
            # don't wait a probe interval to see what we just did: the
            # router's split and this controller's straggler check both
            # read the handle
            with handle.lock:
                handle.generation = int(stamp)
            return True
        if status == 409:
            # the replica verified and REFUSED (torn files on its read,
            # tree mismatch): permanent for this stamp
            self.rejected.add(int(stamp))
        log_event(
            "live-swap-refused",
            f"replica {handle.replica_id} refused swap to generation "
            f"{stamp}: HTTP {status} {payload.get('message', '')}"
            + (" — stamp rejected" if status == 409 else ""),
            replica=handle.replica_id,
            generation=int(stamp),
            status=status,
        )
        return False

    def _rollback_one(self, handle) -> bool:
        addr = handle.address
        if addr is None:
            return False
        try:
            status, payload = _admin_post(
                addr, "/admin/rollback", {}, self.admin_timeout_s
            )
        except OSError:
            return False  # replica died mid-rollout: its restart boots
            # from the disk model anyway — already "rolled back"
        if status == 200:
            gen = payload.get("generation")
            with handle.lock:
                handle.generation = gen if isinstance(gen, int) else None
            return True
        return False

    # -- thread lifecycle ------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception:  # the rollout loop must survive anything
                logger.exception("live fleet controller tick failed")
            self._stop.wait(self.interval_s)

    def start(self) -> "LiveFleetController":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="live-controller"
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
