"""Live continuous-learning subsystem: a serving fleet that tracks a
running training job without dropping a request (docs/SERVING.md
"Continuous learning").

Three cooperating pieces:

* :mod:`watcher` — poll a :class:`~...training.checkpoint.TrainCheckpoint`
  directory for new generations, digest-verify before touching them
  (torn generations are skipped with a structured event, never loaded),
  hand verified param trees to subscribers.
* engine hot-swap — ``InferenceEngine.swap_params`` (serving/engine.py):
  stage the new tree + precision overlay off the dispatch path, flip at
  a dispatch boundary, one-call rollback.
* :mod:`canary` + :mod:`controller` — fleet-side rollout: swap a canary
  subset of replicas first, split traffic by generation (router
  ``canary_fraction``), promote or auto-roll-back on the guard's
  error-rate / p99 verdict over the sliding SLO window.

:mod:`orchestrator` wires the whole loop as one process tree: a training
subprocess and a serving fleet sharing the checkpoint directory under a
single ShutdownCoordinator (the ``train-and-serve`` CLI).

This package's modules import jax lazily (only on the param-loading
paths), so the fleet/router process — which never touches a device —
can drive rollouts without pulling a jax runtime into the proxy.
"""

from .canary import CanaryGuard, GenerationStats  # noqa: F401
from .controller import LiveFleetController  # noqa: F401
from .orchestrator import TrainAndServe, wait_for_best_model  # noqa: F401
from .watcher import (  # noqa: F401
    CheckpointWatcher,
    scan_intact_generations,
)

__all__ = [
    "CanaryGuard",
    "GenerationStats",
    "CheckpointWatcher",
    "LiveFleetController",
    "TrainAndServe",
    "scan_intact_generations",
    "wait_for_best_model",
]
