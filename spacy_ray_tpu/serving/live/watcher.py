"""Checkpoint watcher: the bridge from a live training run's checkpoint
directory to a serving engine's hot-swap.

The watcher polls a :class:`~...training.checkpoint.TrainCheckpoint`
directory on an interval, digest-verifies any generation newer than the
one it last delivered, and hands the verified state to a subscriber
callback. The integrity discipline is PR 2's, reused verbatim: a torn,
truncated, or mid-retirement generation raises the one typed
:class:`~...training.checkpoint.CheckpointCorrupt`, which the watcher
turns into a structured ``log_event`` row (once per stamp, not a storm)
and a fallback to the next-newest intact candidate — a bad generation
is *skipped*, never loaded, never fatal. The crash-safe rename protocol
the watcher relies on is documented on
:class:`~...training.checkpoint.Checkpoints` (array files land before
their meta; every rename atomic; retention deletes only committed-over
generations).

Two consumers with different weight classes:

* :func:`scan_intact_generations` — stdlib-only (hashlib/json) digest
  scan, importable WITHOUT jax. The fleet/router process uses it to
  detect new generations it will roll out via replica admin endpoints;
  it never deserializes arrays.
* :class:`CheckpointWatcher` — runs inside a serving (replica) process;
  its load path imports the checkpoint module (and thus jax) lazily to
  hand full param trees to ``engine.swap_params``.
"""

from __future__ import annotations

import hashlib
import json
import logging
import threading
import time
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Set

from ...training.resilience import log_event

__all__ = ["scan_intact_generations", "CheckpointWatcher"]

logger = logging.getLogger("spacy_ray_tpu.serving")


def _sha256(path: Path) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def scan_intact_generations(
    path,
    *,
    newer_than: Optional[int] = None,
    skip: Any = (),
    params_only: bool = False,
) -> List[int]:
    """Stamps of every generation in ``path`` whose files digest-verify,
    ascending — the jax-free twin of
    ``Checkpoints.latest_intact_generation`` (stdlib only, nothing
    deserialized), for processes that must not import a device runtime.
    A generation with unreadable meta, missing files, or a digest
    mismatch is silently absent from the result (the caller's policy
    decides whether that is worth an event; for a scan it is not —
    mid-write races make transient misses normal).

    ``newer_than``/``skip`` filter BEFORE any hashing — a control loop
    polling every couple of seconds must not re-SHA-256 gigabytes of
    already-adopted generations per tick; with both set, an idle tick
    hashes nothing. ``params_only`` skips the opt_state digest (the
    serving-swap scope: that file is discarded by a swap anyway)."""
    path = Path(path)
    intact: List[int] = []
    for meta_path in path.glob("train_meta-*.json"):
        name = meta_path.name
        try:
            stamp = int(name[len("train_meta-"):-len(".json")])
        except ValueError:
            continue
        if newer_than is not None and stamp <= newer_than:
            continue
        if stamp in skip:
            continue
        try:
            meta = json.loads(meta_path.read_text(encoding="utf8"))
        except (OSError, ValueError):
            continue
        if not isinstance(meta, dict) or meta.get("stamp") != stamp:
            continue
        digests = meta.get("digests") or {}
        fnames = [f"params-{stamp}.npz"]
        if not params_only:
            # format v2 (meta["format"] >= 2) shards the opt state into
            # owner-shard part files; v1 is one pickle — keep this logic in
            # lockstep with training/checkpoint.py:_opt_file_names (this
            # twin stays stdlib-only, so it cannot import it)
            if int(meta.get("format", 1) or 1) >= 2:
                parts = int(meta.get("opt_shards", 1) or 1)
                fnames.extend(
                    f"opt_state-{stamp}.part{k}of{parts}.pkl"
                    for k in range(parts)
                )
            else:
                fnames.append(f"opt_state-{stamp}.pkl")
        ok = True
        for fname in fnames:
            f = path / fname
            try:
                if not f.exists():
                    ok = False
                    break
                expect = digests.get(fname)
                if expect is not None and _sha256(f) != expect:
                    ok = False
                    break
            except OSError:
                ok = False
                break
        if ok:
            intact.append(stamp)
    return sorted(intact)


class CheckpointWatcher:
    """Poll a checkpoint directory; deliver each new verified generation
    to ``on_generation(stamp, state)`` exactly once, newest-first.

    ``state`` is the full ``Checkpoints.load_generation`` dict (params,
    step, ...). Delivery happens on the watcher thread (or the caller's
    thread via :meth:`poll_once` in tests) — subscribers that need a
    dispatch-boundary flip do their own staging, which is exactly what
    ``engine.swap_params`` provides.

    Skip semantics: a candidate that fails verification is skipped with
    ONE ``live-generation-skipped`` event per stamp (a torn generation
    sitting in the directory must not emit a row per poll), but is
    re-checked on later polls — a transient race with the writer (the
    meta landing a beat before our digest read of a being-replaced
    file) heals itself; a genuinely torn write stays skipped until
    retention deletes it. The newest intact candidate wins even when an
    older unseen one also exists (serving wants the freshest weights,
    not a replay of history).
    """

    def __init__(
        self,
        ckpt_dir,
        on_generation: Callable[[int, Dict[str, Any]], None],
        *,
        interval_s: float = 2.0,
        start_from: Optional[int] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.ckpt_dir = Path(ckpt_dir)
        self.on_generation = on_generation
        self.interval_s = float(interval_s)
        self.clock = clock
        # the newest stamp already delivered; candidates must beat it
        self.current: Optional[int] = start_from
        self._warned: Set[int] = set()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.polls = 0
        self.delivered = 0
        self.skipped = 0

    # -- one poll (deterministic; the thread loop calls this) -----------
    def poll_once(self) -> Optional[int]:
        """Scan once; deliver the newest intact generation newer than
        ``current`` (skipping torn candidates toward older ones).
        Returns the delivered stamp, or None when nothing new/intact."""
        from ...training.checkpoint import CheckpointCorrupt, Checkpoints

        self.polls += 1
        ckpts = Checkpoints(self.ckpt_dir)
        try:
            stamps = ckpts.generations()
        except OSError:
            return None  # directory vanished mid-poll: nothing to do
        floor = self.current if self.current is not None else -1
        for stamp in sorted(stamps, reverse=True):
            if stamp <= floor:
                break  # everything below is older than what we serve
            try:
                # params-only load: a swap discards opt_state, so the
                # watcher neither hashes nor unpickles it (for Adam
                # that is ~2x the param bytes per generation)
                state = ckpts.load_generation_params(stamp)
            except CheckpointCorrupt as e:
                self.skipped += 1
                if stamp not in self._warned:
                    self._warned.add(stamp)
                    log_event(
                        "live-generation-skipped",
                        f"checkpoint generation {stamp} failed verification "
                        f"({e}) — skipped, trying the previous candidate",
                        stamp=int(stamp),
                        path=str(self.ckpt_dir),
                    )
                continue
            log_event(
                "live-generation",
                f"verified checkpoint generation {stamp} "
                f"(step {state.get('step')}) — delivering to subscriber",
                level=logging.INFO,
                stamp=int(stamp),
                path=str(self.ckpt_dir),
            )
            # deliver FIRST, advance after: a subscriber that fails
            # transiently (device hiccup mid-stage) must get this
            # generation retried on the next poll, not have it slide
            # below the floor forever — a permanently-incompatible
            # generation therefore retries loudly every poll, which is
            # an operator signal, not a bug
            self.on_generation(stamp, state)
            self.current = stamp
            self._warned.discard(stamp)
            self.delivered += 1
            return stamp
        return None

    # -- thread lifecycle ------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception:  # the watcher must survive anything —
                # a failed swap or a subscriber bug must not kill the
                # polling loop (the NEXT generation may be fine)
                logger.exception("checkpoint watcher poll failed")
            self._stop.wait(self.interval_s)

    def start(self) -> "CheckpointWatcher":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="ckpt-watcher"
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
