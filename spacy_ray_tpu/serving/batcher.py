"""Dynamic micro-batching for online serving: a bounded request queue
that coalesces concurrent requests into bucket-shaped device batches.

The reference's serving substrate is Ray's task/actor queue (Moritz et
al., arXiv:1712.05889 — serving and training share one scheduling
fabric); the TPU-native equivalent separates ADMISSION (this module,
pure host-side, lock-and-condvar) from DISPATCH (serving/engine.py, one
thread driving the compiled program), the decoupling the adaptive-
placement line of work (PAPERS.md) shows is what keeps devices busy
under bursty load.

Contract:

* ``submit`` is called from many HTTP handler threads; it either admits
  the request (bounded queue — backpressure, not unbounded memory) or
  raises a TYPED error the server maps to an HTTP status. A full queue
  or a draining server rejects instantly; nobody's latency degrades
  because someone else's request sat behind an unserviceable backlog.
* ``next_batch`` is called by the single dispatch thread. Two admission
  disciplines, selected by ``mode``:

  - ``"window"`` — the classic size-or-deadline rule: block for the
    first request, then coalesce follow-ups until ``max_batch_docs``
    are in hand or ``max_wait_s`` has elapsed since the first arrival.
    Every partial batch pays the window timer as added latency, even
    when the device sits idle.
  - ``"continuous"`` — slot-based continuous admission: whatever is
    queued the instant the dispatch thread is free fills the batch's
    slots (up to ``max_batch_docs``) and dispatches IMMEDIATELY. There
    is no window timer; the in-flight device batch is the coalescing
    window — requests arriving while the device runs accumulate in the
    queue and are admitted into the next dispatch's free slots the
    moment the previous one is handed to the device. No queued request
    ever waits for a timer or for an in-flight batch to drain when a
    slot is free (property-tested).

  Requests whose deadline already passed are completed with
  ``DeadlineExceeded`` *here*, before they waste a device dispatch —
  both modes.
* Per-request deadlines are absolute clock() stamps. The clock is
  injectable; tests drive every timing path with a fake clock.
"""

from __future__ import annotations

import hashlib
import re
import threading
import time
import uuid
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional

__all__ = [
    "ServingError",
    "QueueFull",
    "Draining",
    "NotReady",
    "DeadlineExceeded",
    "RequestTooLarge",
    "SwapFailed",
    "QuotaExceeded",
    "UnknownModel",
    "ServeRequest",
    "DynamicBatcher",
    "REQUEST_ID_HEADER",
    "mint_request_id",
    "clean_request_id",
    "cache_key_for",
    "etag_for",
    "if_none_match_hit",
]


# Distributed request tracing (docs/OBSERVABILITY.md): every /v1/parse
# request carries ONE id from the edge to the device dispatch that
# served it. The router mints it (honoring a client-supplied header),
# forwards it to the replica, and both echo it back in the response —
# so a client, the router's trace, the replica's trace, and the
# slow-request exemplar ring all name the same request the same way.
REQUEST_ID_HEADER = "X-SRT-Request-Id"

# client-supplied ids are echoed into response headers and trace args:
# accept only sane header-token characters, bounded — anything else is
# replaced by a minted id rather than reflected
_REQUEST_ID_RE = re.compile(r"\A[A-Za-z0-9._:-]{1,128}\Z")  # \Z, not $:
# $ would also match before a trailing newline, letting "id\n" echo into
# a response header


# Conditional responses (docs/SERVING.md "Data plane"): a /v1/parse
# response is a pure function of (texts, model, serving generation) —
# same inputs against the same weights annotate identically, byte for
# byte. That makes a STRONG ETag computable at admission, before any
# inference: the input digest (the response cache's key, so router cache
# and ETag can never disagree about identity) plus the generation. A
# hot-swap promotion changes the generation and therefore every ETag,
# invalidating clients' cached bodies exactly when the annotations
# could differ.


def cache_key_for(texts: List[str], model: str = "") -> bytes:
    """Digest identifying a /v1/parse input. Shared by the router's
    response cache and the ETag so the two can never disagree."""
    h = hashlib.sha256()
    if model:
        # model joins the key (distinct models annotate the same texts
        # differently); \x01 keeps it unambiguous against the
        # \x00-separated texts. Empty model = the single-model serving
        # path — its keys are byte-identical to before the multi-model
        # subsystem existed.
        h.update(model.encode("utf8", "surrogatepass"))
        h.update(b"\x01")
    for t in texts:
        h.update(t.encode("utf8", "surrogatepass"))
        h.update(b"\x00")  # unambiguous: ["ab"] != ["a","b"]
    return h.digest()


def etag_for(
    texts: List[str], model: str = "", generation: Optional[int] = None
) -> str:
    """Strong ETag (quoted, per RFC 9110) for a /v1/parse response."""
    h = hashlib.sha256(cache_key_for(texts, model))
    h.update(b"\x02")
    h.update(repr(generation).encode("utf8"))
    return '"' + h.hexdigest()[:32] + '"'


def if_none_match_hit(header: Optional[str], etag: str) -> bool:
    """Does an If-None-Match header match ``etag``? Handles comma lists
    and ``*``; weak-comparison (a ``W/`` prefix on a listed tag still
    matches) because 304 is a cache-freshness decision, not a storage
    precondition."""
    if not header:
        return False
    for candidate in header.split(","):
        candidate = candidate.strip()
        if candidate == "*":
            return True
        if candidate.startswith("W/"):
            candidate = candidate[2:].strip()
        if candidate == etag:
            return True
    return False


def mint_request_id() -> str:
    return uuid.uuid4().hex[:16]


def clean_request_id(raw: Optional[str]) -> Optional[str]:
    """The validated client-supplied id, or None (caller mints)."""
    if isinstance(raw, str) and _REQUEST_ID_RE.match(raw):
        return raw
    return None


class ServingError(Exception):
    """Base of the typed admission/serving errors; ``http_status`` is the
    status code the HTTP front-end maps the error to."""

    http_status = 500
    code = "internal"


class QueueFull(ServingError):
    """Admission control: the bounded queue is full — shed load now
    instead of growing a backlog that blows every later deadline."""

    http_status = 429
    code = "queue_full"


class Draining(ServingError):
    """The server received SIGTERM and stopped admitting; in-flight
    requests still complete (the graceful-drain contract)."""

    http_status = 503
    code = "draining"


class NotReady(ServingError):
    """The replica's bucket warmup sweep has not completed: admitting a
    request now would run it into a live XLA compile (seconds of added
    latency) — the exact surprise warmup exists to prevent. A router
    treats this 503 as "do not send traffic yet", same as draining."""

    http_status = 503
    code = "warming"


class DeadlineExceeded(ServingError):
    """The request's deadline passed before a device batch picked it up."""

    http_status = 504
    code = "deadline_exceeded"


class RequestTooLarge(ServingError):
    """More docs than ``max_batch_docs`` or a doc longer than the warmed
    shape cap — an unservable request must fail with a reason, not
    trigger an unbounded-compile surprise."""

    http_status = 413
    code = "request_too_large"


class SwapFailed(ServingError):
    """A hot-swap/rollback request could not be honored: the candidate
    generation is torn (CheckpointCorrupt), its tree does not match the
    resident one (different shapes/dtypes would void the warmed-program
    contract), or there is no previous resident to roll back to. The
    engine keeps serving the CURRENT generation — a failed swap is a
    refused swap, never a degraded server — and the admin caller gets a
    typed 409 saying why."""

    http_status = 409
    code = "swap_failed"


class QuotaExceeded(ServingError):
    """A tenant's token bucket is empty: the request exceeds the quota
    the manifest grants that tenant, independent of queue occupancy —
    a distinct 429 from QueueFull so a client can tell "the server is
    saturated" (back off briefly) from "YOU are over quota" (back off
    until the bucket refills). Shedding here, before the queue, is what
    keeps one tenant's burst from starving another's SLO class."""

    http_status = 429
    code = "quota_exceeded"


class UnknownModel(ServingError):
    """The request named a model the registry does not know (bad path
    segment or ``X-SRT-Model`` header) — a typed 404, not a routing
    fallback: silently serving the default model under the wrong name
    would poison the per-model cache and per-model SLO accounting."""

    http_status = 404
    code = "unknown_model"


class ServeRequest:
    """One admitted request: a list of tokenized docs plus completion
    plumbing. The HTTP handler thread blocks on ``wait``; the dispatch
    thread fills ``docs`` (annotated in place) or ``error`` and sets the
    event."""

    __slots__ = (
        "docs", "deadline", "enqueued_at", "started_at", "dispatched_at",
        "_done", "error", "batch_info", "request_id", "latency_s",
        "device_s", "klass",
    )

    def __init__(
        self,
        docs: List[Any],
        deadline: float,
        enqueued_at: float,
        request_id: Optional[str] = None,
        klass: str = "default",
    ):
        self.docs = docs
        self.deadline = float(deadline)
        self.enqueued_at = float(enqueued_at)
        # SLO class (weighted-fair admission): which per-class queue this
        # request rides in a class-aware batcher; plain batchers ignore it
        self.klass = str(klass)
        # trace identity: minted at the edge (router or server) or
        # client-supplied; every span/exemplar/response header for this
        # request carries it
        self.request_id = request_id or mint_request_id()
        # admission→completion seconds, stamped by submit_docs when the
        # wait ends (the exemplar recorder reads it after the fact)
        self.latency_s: Optional[float] = None
        # predict wall time of the batch this request rode in — kept on
        # the request, NOT in batch_info: the response body must stay
        # deterministic per (params, texts) so rollback byte-identity
        # holds, while the exemplar breakdown still gets its device stage
        self.device_s: Optional[float] = None
        # started_at: picked out of the queue into a batch (time-in-queue
        # ends); dispatched_at: the assembled batch is handed to the
        # device (time-to-first-dispatch ends). In window mode the gap
        # between them is the remaining coalescing window; continuous
        # admission collapses it to ~0 — the telemetry pair that makes
        # the continuous-batching win visible per request.
        self.started_at: Optional[float] = None
        self.dispatched_at: Optional[float] = None
        self._done = threading.Event()
        self.error: Optional[ServingError] = None
        self.batch_info: Dict[str, Any] = {}

    def complete(self, error: Optional[ServingError] = None) -> None:
        self.error = error
        self._done.set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    @property
    def done(self) -> bool:
        return self._done.is_set()


class DynamicBatcher:
    """Bounded queue + batch assembly (docs are the unit: one request may
    carry several docs, and occupancy accounting is in docs because that
    is what fills a padded device batch). ``mode`` picks the admission
    discipline — ``"window"`` size-or-deadline coalescing or
    ``"continuous"`` slot-based immediate admission (module docstring).

    ``class_weights`` (multi-tenant serving, docs/SERVING.md
    "Multi-model fleet") opts the batcher into weighted fair queuing:
    one queue per SLO class, drained by deficit round robin so that
    under saturation each class's share of dispatched DOCS converges to
    its weight — a burst from one class fills its own queue, never the
    others'. ``None`` (the default) keeps the original single FIFO
    queue, bit-identical: the legacy single-tenant path never touches
    the per-class machinery."""

    MODES = ("window", "continuous")

    def __init__(
        self,
        *,
        max_queue_docs: int = 128,
        max_batch_docs: int = 16,
        max_wait_s: float = 0.005,
        mode: str = "window",
        clock: Callable[[], float] = time.monotonic,
        class_weights: Optional[Dict[str, float]] = None,
    ) -> None:
        if max_batch_docs < 1:
            raise ValueError("max_batch_docs must be >= 1")
        if max_queue_docs < max_batch_docs:
            raise ValueError(
                f"max_queue_docs ({max_queue_docs}) must be >= max_batch_docs "
                f"({max_batch_docs}) or a full batch could never be admitted"
            )
        if mode not in self.MODES:
            raise ValueError(
                f"mode must be one of {list(self.MODES)}, got {mode!r}"
            )
        self.max_queue_docs = int(max_queue_docs)
        self.max_batch_docs = int(max_batch_docs)
        self.max_wait_s = float(max_wait_s)
        self.mode = mode
        self.clock = clock
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        self._queue: Deque[ServeRequest] = deque()
        self._queued_docs = 0
        self._draining = False
        self._closed = False
        # shed/served accounting the telemetry counters mirror
        self.rejected_full = 0
        self.rejected_draining = 0
        self.expired = 0
        # -- weighted fair queuing (None = legacy single FIFO) ----------
        self.class_weights: Optional[Dict[str, float]] = None
        if class_weights is not None:
            if not class_weights:
                raise ValueError("class_weights must not be empty")
            for k, w in class_weights.items():
                if not (float(w) > 0):
                    raise ValueError(
                        f"class weight must be > 0, got {k}={w!r}"
                    )
            self.class_weights = {k: float(w) for k, w in class_weights.items()}
            self._cqueues: Dict[str, Deque[ServeRequest]] = {
                k: deque() for k in self.class_weights
            }
            self._corder: List[str] = list(self.class_weights)
            self._deficit: Dict[str, float] = {k: 0.0 for k in self._corder}
            self._rr_idx = 0
            self._turn_open = False
            self._recompute_quantum()
        # per-class served-docs ledger (WFQ fairness is observable, not
        # asserted): stays empty on the legacy path
        self.served_docs_by_class: Dict[str, int] = {}

    def _recompute_quantum(self) -> None:
        # one turn's grant must afford the largest admissible request
        # even for the lightest class, or a heavy head-of-line request
        # could starve behind a deficit that never catches up
        assert self.class_weights is not None
        self._quantum = self.max_batch_docs / min(self.class_weights.values())

    def _class_queue(self, klass: str) -> Deque[ServeRequest]:
        """The queue for ``klass``, auto-registering unknown classes at
        weight 1.0 (a tenant misconfigured into a class the batcher was
        not built with still gets service, never a KeyError)."""
        assert self.class_weights is not None
        q = self._cqueues.get(klass)
        if q is None:
            self.class_weights[klass] = 1.0
            q = self._cqueues[klass] = deque()
            self._corder.append(klass)
            self._deficit[klass] = 0.0
            self._recompute_quantum()
        return q

    def _has_queued(self) -> bool:
        if self.class_weights is None:
            return bool(self._queue)
        return any(self._cqueues.values())

    # -- producer side (HTTP handler threads) --------------------------
    def submit(self, request: ServeRequest) -> None:
        n = len(request.docs)
        if n > self.max_batch_docs:
            raise RequestTooLarge(
                f"request carries {n} docs; max_batch_docs is "
                f"{self.max_batch_docs} — split the request"
            )
        with self._lock:
            if self._draining or self._closed:
                self.rejected_draining += 1
                raise Draining("server is draining; not admitting requests")
            if self._queued_docs + n > self.max_queue_docs:
                self.rejected_full += 1
                raise QueueFull(
                    f"queue holds {self._queued_docs} docs "
                    f"(limit {self.max_queue_docs})"
                )
            if self.class_weights is None:
                self._queue.append(request)
            else:
                self._class_queue(request.klass).append(request)
            self._queued_docs += n
            self._nonempty.notify()

    # -- consumer side (the one dispatch thread) ------------------------
    def queue_depth(self) -> int:
        with self._lock:
            return self._queued_docs

    def _pop_ready(self, batch: List[ServeRequest], now: float) -> None:
        """Move queued requests into ``batch`` up to max_batch_docs,
        completing already-expired ones with DeadlineExceeded (never
        spending device time on a response nobody is waiting for).
        Caller holds the lock."""
        if self.class_weights is not None:
            self._pop_ready_wfq(batch, now)
            return
        have = sum(len(r.docs) for r in batch)
        while self._queue:
            head = self._queue[0]
            if head.deadline <= now:
                self._queue.popleft()
                self._queued_docs -= len(head.docs)
                self.expired += 1
                head.complete(
                    DeadlineExceeded(
                        f"deadline passed {now - head.deadline:.3f}s before "
                        "dispatch (queued "
                        f"{now - head.enqueued_at:.3f}s)"
                    )
                )
                continue
            if have + len(head.docs) > self.max_batch_docs:
                break  # keep whole requests together in one device batch
            self._queue.popleft()
            self._queued_docs -= len(head.docs)
            head.started_at = now
            batch.append(head)
            have += len(head.docs)

    def _expire_head(self, q: Deque[ServeRequest], now: float) -> None:
        """Complete already-expired requests at the head of ``q`` with
        DeadlineExceeded (the per-class twin of the legacy loop's inline
        expiry). Caller holds the lock."""
        while q and q[0].deadline <= now:
            head = q.popleft()
            self._queued_docs -= len(head.docs)
            self.expired += 1
            head.complete(
                DeadlineExceeded(
                    f"deadline passed {now - head.deadline:.3f}s before "
                    f"dispatch (queued {now - head.enqueued_at:.3f}s)"
                )
            )

    def _pop_ready_wfq(self, batch: List[ServeRequest], now: float) -> None:
        """Deficit round robin across the per-class queues: each class's
        TURN grants it ``weight * quantum`` doc credits; it dispatches
        whole requests while credits and batch room last, then the turn
        passes. The round-robin pointer and deficits persist across
        batch assemblies, so under saturation the dispatched-doc shares
        converge to the weights even when one batch is too small to show
        the ratio. An emptied queue forfeits its banked deficit (no
        credit hoarding while idle — the standard DRR rule).
        Caller holds the lock."""
        have = sum(len(r.docs) for r in batch)
        idle_turns = 0
        while have < self.max_batch_docs and idle_turns < len(self._corder):
            k = self._corder[self._rr_idx % len(self._corder)]
            q = self._cqueues[k]
            self._expire_head(q, now)
            if not q:
                self._deficit[k] = 0.0
                self._rr_idx += 1
                self._turn_open = False
                idle_turns += 1
                continue
            if not self._turn_open:
                self._deficit[k] += self.class_weights[k] * self._quantum
                self._turn_open = True
            served = False
            while q:
                self._expire_head(q, now)
                if not q:
                    break
                cost = len(q[0].docs)
                if have + cost > self.max_batch_docs:
                    # batch room exhausted; the turn stays open so the
                    # next assembly resumes exactly here
                    return
                if cost > self._deficit[k]:
                    break
                head = q.popleft()
                self._queued_docs -= cost
                head.started_at = now
                batch.append(head)
                have += cost
                self._deficit[k] -= cost
                self.served_docs_by_class[k] = (
                    self.served_docs_by_class.get(k, 0) + cost
                )
                served = True
            # queue drained or deficit exhausted: turn over (a drained
            # queue also forfeits its remaining credits)
            if not q:
                self._deficit[k] = 0.0
            self._rr_idx += 1
            self._turn_open = False
            idle_turns = 0 if served else idle_turns + 1

    def next_batch(self, poll_s: float = 0.05) -> Optional[List[ServeRequest]]:
        """Block for the next assembled batch. Returns None when the
        batcher is closed AND empty (the dispatch thread's exit signal);
        may return an empty list when every popped request had already
        expired (the caller loops around).

        ``poll_s`` bounds each condvar wait so a fake-clock test (or a
        drain) is never stuck inside a long real-time wait.
        """
        with self._lock:
            while not self._has_queued():
                if self._closed:
                    return None
                self._nonempty.wait(timeout=poll_s)
            batch: List[ServeRequest] = []
            first_at = self.clock()
            self._pop_ready(batch, first_at)
            if self.mode == "continuous":
                # slot-based continuous admission: dispatch NOW with
                # whatever filled the slots — zero added wait. Follow-ups
                # landing while this batch runs on the device are popped
                # the moment the dispatch thread returns here.
                return batch
            # coalescing window: more requests may land while we wait —
            # the entire point of dynamic batching. The window is capped
            # by max_wait_s from the FIRST request (bounded added
            # latency) and ends early on a full batch.
            while (
                sum(len(r.docs) for r in batch) < self.max_batch_docs
                and not self._closed
            ):
                remaining = self.max_wait_s - (self.clock() - first_at)
                if remaining <= 0:
                    break
                self._nonempty.wait(timeout=min(remaining, poll_s))
                self._pop_ready(batch, self.clock())
            # deadlines may have passed DURING the window: a requester
            # that already gave up must get its typed timeout, not a
            # response nobody reads (and must not occupy the batch)
            now = self.clock()
            kept: List[ServeRequest] = []
            for r in batch:
                if r.deadline <= now:
                    self.expired += 1
                    r.complete(
                        DeadlineExceeded(
                            f"deadline passed {now - r.deadline:.3f}s into "
                            "the coalescing window"
                        )
                    )
                else:
                    kept.append(r)
            # kept may be empty (everything expired): caller loops around
            return kept

    # -- drain / close --------------------------------------------------
    def begin_drain(self) -> None:
        """Stop admitting; already-queued requests still dispatch."""
        with self._lock:
            self._draining = True
            self._nonempty.notify_all()

    @property
    def draining(self) -> bool:
        return self._draining

    def close(self) -> None:
        """Drain + release the dispatch thread once the queue is empty."""
        with self._lock:
            self._draining = True
            self._closed = True
            self._nonempty.notify_all()

    def fail_all_queued(self, error: ServingError) -> int:
        """Complete every queued request with ``error`` (hard shutdown
        path — a non-graceful stop must not leave handler threads
        blocked forever). Returns how many were failed."""
        with self._lock:
            n = 0
            queues: List[Deque[ServeRequest]] = [self._queue]
            if self.class_weights is not None:
                queues.extend(self._cqueues.values())
            for q in queues:
                while q:
                    req = q.popleft()
                    self._queued_docs -= len(req.docs)
                    req.complete(error)
                    n += 1
            return n
