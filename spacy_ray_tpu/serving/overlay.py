"""Precision-overlay serving: reduced-precision device-resident copies
of the f32 parameter tree, applied once at engine startup.

PR 5 built the mechanism for the TRAINING update path — persistent bf16
copies of the transformer trunk's matmul weights
(``models/transformer.py build_param_shadow``), overlaid onto the f32
masters for the forward pass (``parallel/step.py overlay_shadow``) and
refreshed inside the donated update. Serving has no update: the params
never change, so the overlay is built ONCE and the f32 masters can even
be dropped from the device. This module generalizes the trunk-shadow
extraction out of the training step into that serving shape — the
phase-specific precision placement the adaptive-placement line of work
describes (PAPERS.md: different precision per workload phase, one param
source).

Honesty rules (the same discipline as every pallas kernel claim):

* ``auto`` arms the bf16 overlay ONLY on accelerators. On CPU it
  resolves OFF (f32): XLA CPU *emulates* bf16 by upcasting around every
  elementwise op — PR 5 measured the "saved" casts reappearing as
  emulation converts (PERF.md "Fixed-cost floor", front 2) — so a CPU
  auto-overlay would be a silent pessimization wearing a speedup label.
* An explicit ``bf16`` is honored anywhere (tests and drills need it on
  CPU) but the label says it was forced.
* The overlay is REFUSED — f32 served, refusal in the label — when the
  model has no shadow-eligible trunk leaves, or when a trunk layer
  carries leaves the shadow scheme does not know
  (``shadow_coverage``): a half-covered tree must not ship under a
  "bf16" label.
* ``int8`` is probe-gated like the pallas kernels: it resolves to an
  int8 weight-only overlay only where the pallas dequant-in-kernel
  matmul (ops/int8_matmul.py) compiles AND validates on the current
  backend — auto-armed on TPU, OFF on CPU unless ``SRT_PALLAS_INT8=1``
  forces the interpret-mode kernel (tests, drills, the forced bench
  arm), the same auto policy shape as bf16. The overlay quantizes the
  trunk's dense matmul weights per-output-channel
  (``models/transformer.py build_int8_overlay``) and REFUSES — f32
  served, refusal in the label — on unknown trunk leaves, trunk-less
  models, or MoE trunks (expert weights are outside the kernel's
  coverage; an "int8" label over mostly-f32 weight mass would lie).

Every refusal/downgrade is also a structured ``log_event`` row, and the
resolved label travels into ``/healthz``, bench records, and PERF.md —
a record can never claim a precision the device is not actually using.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Any, Optional, Tuple

from ..training.resilience import log_event

__all__ = [
    "PRECISION_CHOICES",
    "OverlayResult",
    "resolve_precision",
    "build_params_overlay",
    "build_serving_overlay",
]

logger = logging.getLogger("spacy_ray_tpu.serving")

PRECISION_CHOICES = ("auto", "f32", "bf16", "int8")


@dataclass(frozen=True)
class OverlayResult:
    """What the engine actually serves, with the paper trail attached."""

    requested: str       # the knob as given ("auto" | "f32" | "bf16" | "int8")
    resolved: str        # what the device runs: "f32" | "bf16" | "int8"
    label: str           # honest record label, e.g. "bf16 (overlay: 16 leaves)"
    reason: str          # why resolved != requested, or the auto decision
    params: Any          # the tree predict_docs should consume
    n_overlaid: int      # leaves replaced by reduced-precision copies


def _probe_int8(backend: str) -> Tuple[bool, str]:
    """Int8 serving-kernel probe: defers to ``ops/int8_matmul.int8_probe``
    — compile (or interpret, when forced) + numeric validation of the
    pallas dequant-in-kernel matmul on the current backend, with the
    CPU-auto-OFF / SRT_PALLAS_INT8 force policy. The reason string is
    the label's source of truth: "active (pallas)" only when the kernel
    actually runs."""
    from ..ops.int8_matmul import int8_probe

    return int8_probe(backend)


def resolve_precision(
    requested: str, backend: Optional[str] = None
) -> Tuple[str, str]:
    """Map the requested precision knob to what this backend will run.
    Returns ``(resolved, reason)`` where resolved is "f32", "bf16", or
    "int8" (the last only when the kernel probe passed).

    The auto policy is PR 5's, verbatim: accelerators arm the overlay,
    CPU resolves OFF (emulated bf16 is a measured pessimization there —
    PERF.md). Parity with ``[training] bf16_shadow = "auto"`` is
    test-enforced."""
    if requested not in PRECISION_CHOICES:
        raise ValueError(
            f"precision must be one of {list(PRECISION_CHOICES)}, "
            f"got {requested!r}"
        )
    if backend is None:
        import jax

        backend = jax.default_backend()
    if requested == "f32":
        return "f32", "explicit f32"
    if requested == "bf16":
        if backend == "cpu":
            return "bf16", "forced on cpu (auto would resolve f32 there)"
        return "bf16", f"explicit bf16 on {backend}"
    if requested == "int8":
        ok, why = _probe_int8(backend)
        if not ok:
            return "f32", why
        return "int8", why
    # auto
    if backend == "cpu":
        return "f32", (
            "auto resolves f32 on cpu — XLA CPU emulates bf16 "
            "(measured pessimization, PERF.md fixed-cost floor)"
        )
    return "bf16", f"auto arms bf16 on {backend}"


def build_serving_overlay(nlp, precision: str = "auto") -> OverlayResult:
    """Resolve the precision policy and build the param tree the serving
    engine dispatches with. f32 resolutions return ``nlp.params``
    untouched; bf16 builds the trunk overlay via the training shadow
    extraction (one mechanism, two phases) — or refuses with an honest
    f32 fallback when coverage would be partial."""
    assert nlp.params is not None, "serving overlay needs initialized params"
    return build_params_overlay(nlp.params, precision)


def build_params_overlay(params: Any, precision: str = "auto") -> OverlayResult:
    """The param-tree core of :func:`build_serving_overlay`, callable on
    a bare tree: the engine's hot-swap path re-runs the SAME overlay
    resolution on every incoming checkpoint generation (same requested
    knob, fresh coverage check, honest label preserved), so a swapped-in
    tree can never silently ship at a different precision than the one
    the engine advertised at startup."""
    resolved, reason = resolve_precision(precision)
    if resolved == "f32":
        return OverlayResult(
            requested=precision, resolved="f32",
            label=f"f32 ({reason})" if precision != "f32" else "f32",
            reason=reason, params=params, n_overlaid=0,
        )

    from ..models.transformer import (
        build_int8_overlay,
        build_param_shadow,
        int8_unsupported_leaves,
        shadow_coverage,
    )
    from ..parallel.step import overlay_shadow

    def _refuse(reason: str, level: int = logging.INFO, **extra):
        log_event("serving-overlay-refused", reason, level=level, **extra)
        return OverlayResult(
            requested=precision, resolved="f32", label=f"f32 ({reason})",
            reason=reason, params=params, n_overlaid=0,
        )

    eligible, unknown = shadow_coverage(params)
    if unknown:
        return _refuse(
            f"overlay refused: {len(unknown)} trunk leaf(s) unknown to the "
            f"shadow scheme ({', '.join(unknown[:4])}"
            + (", ..." if len(unknown) > 4 else "") + ")",
            level=logging.WARNING,
            unknown=unknown[:16],
        )
    if eligible == 0:
        return _refuse(
            "overlay refused: no shadow-eligible trunk leaves "
            "(no transformer trunk in the pipeline)"
        )
    if resolved == "int8":
        # the int8 kernel covers the dense matmul weights only: a trunk
        # whose FFNs are MoE experts would ship its weight mass f32
        # under an "int8" label — refuse instead (the probe passing is
        # necessary, not sufficient; coverage is per MODEL)
        moe = int8_unsupported_leaves(params)
        if moe:
            return _refuse(
                f"overlay refused: {len(moe)} MoE expert weight leaf(s) "
                "outside int8 coverage "
                f"({', '.join(moe[:4])}"
                + (", ..." if len(moe) > 4 else "") + ")"
            )
        served, n_q = build_int8_overlay(params)
        label = (
            f"int8 (overlay: {n_q} trunk weights quantized per-channel; "
            f"{reason})"
        )
        log_event(
            "serving-overlay-armed",
            f"serving params carry an int8 weight-only overlay of {n_q} "
            f"trunk weight(s) ({reason})",
            level=logging.INFO,
            leaves=n_q,
            requested=precision,
        )
        return OverlayResult(
            requested=precision, resolved="int8", label=label,
            reason=reason, params=served, n_overlaid=n_q,
        )
    shadow = build_param_shadow(params)
    assert shadow is not None  # eligible > 0 guarantees it
    served = overlay_shadow(params, shadow)
    label = f"bf16 (overlay: {eligible} trunk leaves; {reason})"
    log_event(
        "serving-overlay-armed",
        f"serving params carry a bf16 overlay of {eligible} trunk "
        f"leaf(s) ({reason})",
        level=logging.INFO,
        leaves=eligible,
        requested=precision,
    )
    return OverlayResult(
        requested=precision, resolved="bf16", label=label, reason=reason,
        params=served, n_overlaid=eligible,
    )
